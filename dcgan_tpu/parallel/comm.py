"""Collective overlap plane (ISSUE 20, DESIGN §6n).

PR 13's ZeRO hooks made the collective stream explicit but naive: one
small reduce-scatter / all-gather per LEAF (shard_map@zero2 censuses
16 + 16 per step), and stage 3's `gather_params` materializes the whole
tree before the first conv — exactly the latency-bound regime ParaGAN
(arXiv:2411.03999) identifies. This module restructures the wire plan
without touching the math:

- **Bucketed collectives** (`bucketed_reduce` / `bucketed_gather`,
  `--comm_overlap bucket`): the per-leaf trees are packed into
  dtype-grouped, size-capped flat buffers (`elastic/rules.py::
  zero_bucket_plan` derives the plan from the SAME rule table that
  placed the shards, so layout and wire can never disagree) and each
  bucket rides ONE dim-0 tiled collective. The packing is shard-major —
  leaf `g` with scatter dim `d` contributes `moveaxis(g, d, 0)
  .reshape(n_shards, -1)` rows, buckets concatenate along the row axis —
  so a single `psum_scatter(..., scatter_dimension=0, tiled=True)`
  hands every shard exactly the rows its per-leaf collective would
  have. Sum / divide are elementwise and data movement is bijective,
  so the result is BIT-exact vs the per-leaf plan (pinned by
  tests/test_comm_overlap.py), while the census shrinks from one op
  per leaf to one op per bucket (pinned by the `@overlap` manifest
  rows).
- **Layer-ahead gather prefetch** (`staged_gather`,
  `--comm_overlap prefetch`, ZeRO-3 only): instead of one up-front
  full-tree gather, params are gathered per top-level layer with a
  one-stage-ahead `lax.optimization_barrier` chain — releasing layer
  i's params to compute is tied to layer i+1's gather being issued, so
  XLA's latency-hiding scheduler overlaps gather i+1 with compute i.
  The barrier is the identity on values: bit-exact, same all-gather
  census as `off`.
- **Backward-overlapped reduce-scatter** falls out of bucketing: each
  bucket's psum_scatter depends only on ITS leaves' cotangents, so the
  scheduler issues it as soon as that slice of the backward completes
  rather than after the full walk. On gspmd the partitioner owns
  collective placement; `maybe_apply_xla_overlap_flags` arms the
  async-collective scheduler flags (TPU-only — unknown XLA_FLAGS
  entries are fatal on other backends) so its inserted collectives
  overlap too.

Module-level imports stay jax-free: the CLI applies the XLA flags
before jax's backend initializes, and the analyzer imports this module
on lint passes.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Any, Callable, Dict, List, Sequence, Tuple

Pytree = Any

#: Async-collective scheduler flags for the gspmd backend's half of the
#: backward-overlap story (DESIGN §6n): let XLA fuse collectives into
#: async start/done pairs and float compute between them. TPU-only —
#: the CPU/GPU XLA builds in this toolchain reject unknown flags hard.
XLA_OVERLAP_FLAGS: Tuple[str, ...] = (
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_reduce=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
)


def maybe_apply_xla_overlap_flags(env=None, *, platform: str = "",
                                  force: bool = False) -> Tuple[str, ...]:
    """Append XLA_OVERLAP_FLAGS to env["XLA_FLAGS"] when the run will
    actually land on TPU, skipping flags whose key the user already
    set. Two gates, BOTH required: the requested platform (the explicit
    `platform` arg, else env["JAX_PLATFORMS"]; "" = auto) must not name
    a non-TPU backend, and libtpu must be importable. The platform gate
    matters even on TPU-equipped hosts: `--platform cpu` local-debug
    runs init a CPU XLA client, which aborts on unknown --xla_tpu_*
    entries — libtpu presence alone is the wrong question (caught live:
    this container carries the TPU plugin, so a CPU-forced CLI run died
    at client init before the gate existed). Returns the tuple of flags
    actually added. `force=True` bypasses both probes for tests driving
    a fake env dict. Must run before jax initializes its backend."""
    env = os.environ if env is None else env
    if not force:
        requested = (platform or env.get("JAX_PLATFORMS", "")).lower()
        if requested and "tpu" not in requested:
            return ()
        if importlib.util.find_spec("libtpu") is None:
            return ()
    existing = env.get("XLA_FLAGS", "")
    added = tuple(f for f in XLA_OVERLAP_FLAGS
                  if f.split("=", 1)[0] not in existing)
    if added:
        joined = " ".join(added)
        env["XLA_FLAGS"] = f"{existing} {joined}".strip()
    return added


# -- pack / unpack -----------------------------------------------------------
#
# Shard-major layout. For a leaf of shape S with scatter dim d over an
# n-way axis (S[d] % n == 0, guaranteed by rules.zero_insert's
# divisibility guard), define moved = moveaxis(leaf, d, 0):
#
#   scatter packing: moved.reshape(n, -1) — row k is the flat of the
#     block the per-leaf psum_scatter would hand shard k. Buckets
#     concatenate rows along axis 1, flatten C-order, and ONE
#     psum_scatter(scatter_dimension=0, tiled=True) returns each shard
#     its own (seg_total,) row.
#   gather packing: the local shard's moved block flattens to one
#     segment; ONE all_gather(axis=0, tiled=True) stacks every shard's
#     segment, and reshape(n, seg_total) recovers the per-shard rows.
#
# Both directions are pure reshapes/transposes — bijective data
# movement, no arithmetic — so round-trip equality is exact by
# construction (unit-tested leaf-for-leaf in test_comm_overlap.py).

def pack_scatter(leaves: Sequence, dims: Sequence[int],
                 idxs: Sequence[int], n_shards: int):
    """Pack full (unreduced) leaves of one bucket into the shard-major
    flat buffer. Returns (buf, segs) where segs rows are
    (leaf_index, row_width, moved_shape) for `unpack_scatter`."""
    import jax.numpy as jnp

    rows, segs = [], []
    for i in idxs:
        moved = jnp.moveaxis(leaves[i], dims[i], 0)
        r = moved.reshape(n_shards, -1)
        segs.append((i, int(r.shape[1]), tuple(moved.shape)))
        rows.append(r)
    return jnp.concatenate(rows, axis=1).reshape(-1), segs


def unpack_scatter(seg_buf, segs, n_shards: int, dims: Sequence[int],
                   out: List) -> None:
    """Split this shard's reduced (seg_total,) row back into the
    per-leaf LOCAL blocks (shape = leaf shape with dim d divided by
    n_shards), writing them into `out` at each leaf's index."""
    import jax.numpy as jnp

    o = 0
    for i, width, moved_shape in segs:
        local = seg_buf[o:o + width]
        o += width
        local_moved = local.reshape(
            (moved_shape[0] // n_shards,) + tuple(moved_shape[1:]))
        out[i] = jnp.moveaxis(local_moved, 0, dims[i])


def pack_gather(leaves: Sequence, dims: Sequence[int],
                idxs: Sequence[int]):
    """Pack the LOCAL shard blocks of one bucket into a flat segment.
    Returns (seg, segs) with segs rows (leaf_index, width,
    local_moved_shape) for `unpack_gather`."""
    import jax.numpy as jnp

    flats, segs = [], []
    for i in idxs:
        moved = jnp.moveaxis(leaves[i], dims[i], 0)
        flat = moved.reshape(-1)
        segs.append((i, int(flat.shape[0]), tuple(moved.shape)))
        flats.append(flat)
    return jnp.concatenate(flats), segs


def unpack_gather(gathered, segs, n_shards: int, dims: Sequence[int],
                  out: List) -> None:
    """Split the all-gathered (n_shards * seg_total,) buffer back into
    FULL per-leaf arrays, writing them into `out` at each leaf's
    index."""
    import jax.numpy as jnp

    total = sum(w for _, w, _ in segs)
    view = gathered.reshape(n_shards, total)
    o = 0
    for i, width, moved_shape in segs:
        cols = view[:, o:o + width]
        o += width
        full = cols.reshape(
            (n_shards * moved_shape[0],) + tuple(moved_shape[1:]))
        out[i] = jnp.moveaxis(full, 0, dims[i])


# -- bucketed hook bodies ----------------------------------------------------

def bucketed_reduce(grads: Pytree, dims: Pytree,
                    plan: Sequence[Sequence[int]], *, axis_name: str,
                    n_shards: int) -> Pytree:
    """Drop-in body for ZeroHooks.reduce_grads: one psum_scatter per
    BUCKET (replicated leaves, dim == -1, keep their per-leaf pmean —
    they are outside every bucket by plan construction). Bit-exact vs
    the per-leaf plan: the packed psum_scatter sums the same operands
    elementwise and the /n_shards is the same elementwise divide."""
    import jax
    from jax import lax

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    dleaves = jax.tree_util.tree_leaves(dims)
    out = list(leaves)
    in_bucket = {i for b in plan for i in b}
    for i, (g, d) in enumerate(zip(leaves, dleaves)):
        if i in in_bucket:
            continue
        out[i] = (lax.pmean(g, axis_name) if d < 0 else
                  lax.psum_scatter(g, axis_name, scatter_dimension=d,
                                   tiled=True) / n_shards)
    for b in plan:
        buf, segs = pack_scatter(leaves, dleaves, b, n_shards)
        red = lax.psum_scatter(buf, axis_name, scatter_dimension=0,
                               tiled=True) / n_shards
        unpack_scatter(red, segs, n_shards, dleaves, out)
    return jax.tree_util.tree_unflatten(treedef, out)


def bucketed_gather(tree: Pytree, dims: Pytree,
                    plan: Sequence[Sequence[int]], *, axis_name: str,
                    n_shards: int) -> Pytree:
    """Drop-in body for ZeroHooks.gather_updates: one all_gather per
    BUCKET (replicated leaves pass through untouched). Pure data
    movement — bit-exact by construction."""
    import jax
    from jax import lax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    dleaves = jax.tree_util.tree_leaves(dims)
    out = list(leaves)
    in_bucket = {i for b in plan for i in b}
    for i, (x, d) in enumerate(zip(leaves, dleaves)):
        if i in in_bucket:
            continue
        out[i] = x if d < 0 else lax.all_gather(x, axis_name, axis=d,
                                                tiled=True)
    for b in plan:
        seg, segs = pack_gather(leaves, dleaves, b)
        g = lax.all_gather(seg, axis_name, axis=0, tiled=True)
        unpack_gather(g, segs, n_shards, dleaves, out)
    return jax.tree_util.tree_unflatten(treedef, out)


def staged_gather(params: Pytree,
                  gather_stage: Callable[[str], Pytree]) -> Pytree:
    """ZeRO-3 layer-ahead gather prefetch: walk the net's top-level
    layer dict in insertion order (== the model's stage walk), gather
    each layer with `gather_stage(name)`, and chain stages with a
    one-ahead `lax.optimization_barrier` — layer i's gathered params
    are released to compute only once layer i+1's gather is in flight,
    which is the dependence XLA's latency-hiding scheduler needs to
    overlap gather i+1 with compute i. optimization_barrier is the
    identity on values, so the result is bit-exact vs the up-front
    full-tree gather. Non-dict or single-layer trees degrade to the
    plain per-stage gather (nothing to prefetch ahead of)."""
    from jax import lax

    if not isinstance(params, dict) or len(params) < 2:
        if isinstance(params, dict):
            return {n: gather_stage(n) for n in params}
        return gather_stage(None)
    names = list(params)
    out: Dict[str, Pytree] = {}
    cur = gather_stage(names[0])
    for i, name in enumerate(names):
        nxt = gather_stage(names[i + 1]) if i + 1 < len(names) else None
        if nxt is not None:
            cur, nxt = lax.optimization_barrier((cur, nxt))
        out[name] = cur
        cur = nxt
    return out
