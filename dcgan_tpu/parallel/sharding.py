"""Sharding rules: pytree path -> PartitionSpec.

The reference's placement policy was one line — every variable pinned to
`/job:ps/task:0` (distriubted_model.py:70) — plus replica_device_setter for
driver-created variables (image_train.py:65-67). Here placement is explicit
per-leaf:

- batch-dim tensors shard over "data";
- the widest weights shard over "model" (tensor parallelism): the generator
  projection [z, top_ch*S*S] and discriminator head [flat, 1] on their large
  axis, conv/deconv kernels [h,w,i,o] on output channels;
- everything small (biases, BN scale/bias/stats, Adam scalars, step) is
  replicated.

With MeshConfig(model=1) the model axis has size 1 and every rule degrades to
pure data parallelism — params replicated, grads psum'd — the reference's
capability re-expressed synchronously.

ISSUE 12: the per-leaf derivation moved to the rule ENGINE
(dcgan_tpu/elastic/rules.py) — one regex table whose logical specs also
ride every checkpoint as the sharding sidecar, which is what lets a
checkpoint restore onto a different topology. This module keeps the
public surface (`state_shardings`, `batch_sharding`, `replicated`) so
both parallel backends and the serve sources are unchanged callers.
"""

from __future__ import annotations

from typing import Any

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dcgan_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

Pytree = Any


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def state_bytes_per_chip(state: Pytree) -> int:
    """Per-chip RESIDENT bytes of a live state tree: each leaf counts its
    per-device shard size (a rule-engine-sharded leaf — ZeRO stages, TP —
    contributes 1/N, a replicated leaf contributes in full), derived from
    the same NamedShardings the checkpoint sidecar records. ONE
    definition: bench.py's `peak_state_mib` and the zero-stage tests'
    strictly-decreasing ladder both read this, so the shipped metric and
    the test that pins it cannot drift apart (ISSUE 13)."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(state):
        sh = getattr(leaf, "sharding", None)
        if sh is not None and hasattr(sh, "shard_shape"):
            n = int(np.prod(sh.shard_shape(leaf.shape), dtype=np.int64)) \
                if leaf.ndim else 1
            total += n * leaf.dtype.itemsize
        else:
            total += int(getattr(leaf, "nbytes", 0))
    return total


def batch_sharding(mesh: Mesh, ndim: int = 4, *,
                   spatial: bool = False) -> NamedSharding:
    """Shard dim 0 (batch) over "data"; e.g. images [B,H,W,C], labels [B].

    spatial=True additionally shards dim 1 (image height) over "model" — the
    sequence-parallel analogue for convolutional data: XLA lowers convs over
    the halo-exchange pattern (ppermute of kernel_size//2 boundary rows over
    ICI) instead of gathering full feature maps.
    """
    if spatial and ndim == 4 and mesh.shape[MODEL_AXIS] > 1:
        return NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS, None, None))
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def state_shardings(state_shapes: Pytree, mesh: Mesh, *,
                    spatial: bool = False,
                    shard_opt: bool = False,
                    zero_stage: int = 1) -> Pytree:
    """Map a ShapeDtypeStruct tree (from jax.eval_shape on init) to a matching
    tree of NamedShardings. Works for the whole train state: params and Adam
    moments (mu/nu mirror the param tree, so the same path rules hit them) get
    TP rules; BN state and counters come out replicated.

    Since ISSUE 12 the derivation itself lives in the rule engine
    (dcgan_tpu/elastic/rules.py::PARTITION_RULES — one regex table per
    the SNIPPETS [3] match_partition_rules idiom, audited for exact-one
    coverage by DCG011), resolved against `mesh` with bit-identical
    results to the previous hand-built walk; this wrapper keeps both
    backends and the serve sources unchanged callers.

    spatial=True replicates ALL weights: the "model" axis then carries the
    height dimension of activations (batch_sharding), and sharding kernels
    over the same axis would force GSPMD to all-gather them around every conv.

    shard_opt=True additionally shards every optimizer-state leaf (paths
    under "opt") over the data axis where a dim divides — ZeRO-1: the memory
    and update-compute for Adam moments split across replicas instead of
    being redundantly materialized on each.

    zero_stage >= 2 (ZeRO-2/3, ISSUE 13) extends the same insertion policy
    beyond shard_opt's scope: stage 2 shards the optimizer state
    unconditionally (gradients pick up the matching specs via
    rules.grad_shardings inside the step), stage 3 additionally shards
    params and the EMA mirror so they stay resident sharded between steps.
    """
    from dcgan_tpu.elastic import rules

    return rules.state_shardings(state_shapes, mesh, spatial=spatial,
                                 shard_opt=shard_opt,
                                 zero_stage=zero_stage)
