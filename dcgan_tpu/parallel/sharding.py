"""Sharding rules: pytree path -> PartitionSpec.

The reference's placement policy was one line — every variable pinned to
`/job:ps/task:0` (distriubted_model.py:70) — plus replica_device_setter for
driver-created variables (image_train.py:65-67). Here placement is explicit
per-leaf:

- batch-dim tensors shard over "data";
- the widest weights shard over "model" (tensor parallelism): the generator
  projection [z, top_ch*S*S] and discriminator head [flat, 1] on their large
  axis, conv/deconv kernels [h,w,i,o] on output channels;
- everything small (biases, BN scale/bias/stats, Adam scalars, step) is
  replicated.

With MeshConfig(model=1) the model axis has size 1 and every rule degrades to
pure data parallelism — params replicated, grads psum'd — the reference's
capability re-expressed synchronously.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dcgan_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

Pytree = Any


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, ndim: int = 4, *,
                   spatial: bool = False) -> NamedSharding:
    """Shard dim 0 (batch) over "data"; e.g. images [B,H,W,C], labels [B].

    spatial=True additionally shards dim 1 (image height) over "model" — the
    sequence-parallel analogue for convolutional data: XLA lowers convs over
    the halo-exchange pattern (ppermute of kernel_size//2 boundary rows over
    ICI) instead of gathering full feature maps.
    """
    if spatial and ndim == 4 and mesh.shape[MODEL_AXIS] > 1:
        return NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS, None, None))
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def _spec_for_leaf(path, leaf, model_size: int) -> P:
    names = [p.key for p in path if hasattr(p, "key")]
    shape = getattr(leaf, "shape", ())
    if not names or len(shape) == 0:
        return P()

    def ok(dim):  # a dim only shards if the model axis divides it
        return shape[dim] % model_size == 0

    is_weight = names[-1] == "w"
    if is_weight and len(shape) == 4 and ok(3):
        # conv/deconv kernel [h, w, in, out] -> shard output channels
        # (the c_dim-output deconv stays replicated: 3 % model_size != 0)
        return P(None, None, None, MODEL_AXIS)
    if is_weight and len(shape) == 2:
        if "proj" in names and ok(1):   # generator projection: huge output dim
            return P(None, MODEL_AXIS)
        if "head" in names and ok(0):   # discriminator head: huge input dim
            return P(MODEL_AXIS, None)
    return P()


def _insert_data_axis(spec: P, shape, data_size: int) -> P:
    """Add DATA_AXIS on the first unsharded dim it divides (ZeRO-1-style
    optimizer-state sharding): each data-parallel replica then owns 1/N of
    the Adam moments, and GSPMD lowers grad-psum + sharded update into
    reduce-scatter -> local Adam -> all-gather (the cross-replica weight
    update sharding of arXiv:2004.13336, expressed as annotations)."""
    parts = list(spec) + [None] * (len(shape) - len(spec))
    for d, (axis, size) in enumerate(zip(parts, shape)):
        if axis is None and size >= data_size and size % data_size == 0:
            parts[d] = DATA_AXIS
            return P(*parts)
    return spec


def state_shardings(state_shapes: Pytree, mesh: Mesh, *,
                    spatial: bool = False,
                    shard_opt: bool = False) -> Pytree:
    """Map a ShapeDtypeStruct tree (from jax.eval_shape on init) to a matching
    tree of NamedShardings. Works for the whole train state: params and Adam
    moments (mu/nu mirror the param tree, so the same path rules hit them) get
    TP rules; BN state and counters come out replicated.

    spatial=True replicates ALL weights: the "model" axis then carries the
    height dimension of activations (batch_sharding), and sharding kernels
    over the same axis would force GSPMD to all-gather them around every conv.

    shard_opt=True additionally shards every optimizer-state leaf (paths
    under "opt") over the data axis where a dim divides — ZeRO-1: the memory
    and update-compute for Adam moments split across replicas instead of
    being redundantly materialized on each.
    """
    model_size = mesh.shape[MODEL_AXIS]
    data_size = mesh.shape[DATA_AXIS]

    def to_sharding(path, leaf):
        spec = P() if spatial else _spec_for_leaf(path, leaf, model_size)
        if shard_opt and path and getattr(path[0], "key", None) == "opt":
            spec = _insert_data_axis(spec, getattr(leaf, "shape", ()),
                                     data_size)
        return NamedSharding(mesh, spec)
    return jax.tree_util.tree_map_with_path(to_sharding, state_shapes)
