"""Parallelism: device mesh, sharding rules, sharded train step, multi-host init.

This package replaces the reference's entire cluster runtime — ClusterSpec /
tf.train.Server / ps-role / replica_device_setter / Supervisor session fabric
(image_train.py:52-67,122-141) and the `/job:ps/task:0` variable pinning
(distriubted_model.py:66-72). There is no parameter-server process: parameters
are replicated (or tensor-sharded) across the mesh per explicit sharding rules,
the batch is sharded over the "data" axis, and GSPMD inserts psum/all-gather
collectives over ICI where the reference did per-worker gRPC weight pulls and
Hogwild update pushes.
"""

from dcgan_tpu.parallel.mesh import make_mesh  # noqa: F401
from dcgan_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    replicated,
    state_shardings,
)
from dcgan_tpu.parallel.api import ParallelTrain, make_parallel_train  # noqa: F401
from dcgan_tpu.parallel.shard_map_backend import make_shard_map_train  # noqa: F401
from dcgan_tpu.parallel.distributed import (  # noqa: F401
    initialize_multihost,
    is_chief,
    process_count,
    process_index,
)
