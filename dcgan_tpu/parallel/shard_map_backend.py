"""Explicit-collective training backend: shard_map + psum/pmean by hand.

The default backend (parallel/api.py) states shardings and lets GSPMD insert
the collectives. This one is the other idiom: shard_map — via the
`utils/backend.shard_map` shim over `jax.experimental.shard_map`, the only
form this container's jax 0.4.37 ships (DCG003) — gives each device its
per-shard program and the cross-replica communication is written
out explicitly — `lax.pmean` over the "data" axis for gradients, losses, and
BatchNorm moments (train/steps.py and ops/norm.py take `axis_name` for exactly
this path). Same synchronous-SPMD semantics, same ICI collectives on TPU; what
changes is who writes them.

Two reasons this backend exists beyond idiom parity:

1. **Per-shard Pallas kernels.** `pallas_call` is opaque to the GSPMD
   partitioner, so the fused BN kernels (ops/pallas_kernels.py) are rejected
   under the default backend on multi-device meshes. Inside shard_map there is
   no partitioner — each device runs the kernel on its local shard and the
   moments are pmean'd explicitly — so `ModelConfig.use_pallas` composes with
   data parallelism here.
2. **A second, independently-testable implementation** of the communication
   pattern that replaced the reference's gRPC parameter-server traffic
   (image_train.py:55-67): tests assert the two backends agree, which checks
   the collective placement in both.

Scope: data parallelism only (mesh model axis must be 1 — tensor/spatial
parallelism live in the GSPMD backend, where the partitioner earns its keep).

Because every collective here is hand-written, this backend is the census
surface of the semantic analyzer (DCG008, ISSUE 11): the per-program
psum/all_gather counts in `analysis/programs.lock.jsonl` are counted from
THESE programs' jaxprs (the GSPMD backend's collectives are
partitioner-inserted and census 0 explicit). Changing the collective
pattern — a new pmean, a gather moved — is a manifest change: regenerate
with `python -m dcgan_tpu.analysis --semantic --write-manifest` and
review the census diff, or tier-1 fails on unexplained drift.

Per-shard randomness: the step key is folded with `lax.axis_index("data")`, so
each shard draws an independent z sub-batch — the same global semantics as the
GSPMD backend's single partitioned `jax.random.uniform`, though not the same
bits (the equivalence tests pin down what must match exactly: real-batch loss,
synced-BN statistics, and cross-shard parameter consistency).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dcgan_tpu.config import TrainConfig
from dcgan_tpu.parallel.api import ParallelTrain, make_multi_step_body
from dcgan_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from dcgan_tpu.parallel.sharding import replicated
from dcgan_tpu.train.steps import make_train_step


def make_shard_map_train(cfg: TrainConfig,
                         mesh: Optional[Mesh] = None) -> ParallelTrain:
    """Build a ParallelTrain whose step/sample are shard_map programs with
    hand-written collectives. Drop-in for make_parallel_train (same surface).
    """
    mesh = mesh or make_mesh(cfg.mesh)
    if mesh.shape[MODEL_AXIS] != 1:
        raise ValueError(
            "the shard_map backend is data-parallel only; got model axis "
            f"{mesh.shape[MODEL_AXIS]} (use the default GSPMD backend for "
            "tensor/spatial parallelism)")
    n_shards = mesh.shape[DATA_AXIS]
    if cfg.batch_size % n_shards:
        raise ValueError(
            f"global batch {cfg.batch_size} must divide over "
            f"{n_shards} data shards")
    if cfg.grad_accum > 1 and (cfg.batch_size // cfg.grad_accum) % n_shards:
        # inside shard_map the accumulation reshape is per-device, so each
        # device's local batch must itself split into grad_accum microbatches
        raise ValueError(
            f"microbatch {cfg.batch_size // cfg.grad_accum} "
            f"(batch_size/grad_accum) must divide over {n_shards} data "
            "shards")

    # --- ZeRO-2/3 hooks (ISSUE 13): the EXPLICIT form of what the gspmd
    # backend states as sharding constraints. Gradient trees leave the
    # per-shard bodies through `lax.psum_scatter` (each replica keeps the
    # summed 1/N slice of every leaf the rule engine's zero policy shards
    # — the SAME dims the NamedSharding derivation below stores mu/nu on),
    # the Adam update runs on those slices, and `lax.all_gather` rebuilds
    # full trees exactly where the stage needs them: the updates once per
    # update at stage 2, the params just-in-time per forward at stage 3.
    # Leaves the policy leaves replicated keep their pmean.
    zero = cfg.mesh.zero_stage
    zero_hooks = None
    state_shapes = None
    if zero >= 2:
        from dcgan_tpu.elastic import rules as _rules
        from dcgan_tpu.train.steps import ZeroHooks, init_train_state

        state_shapes = jax.eval_shape(
            lambda k: init_train_state(k, cfg), jax.random.key(0))
        mesh_shape = dict(mesh.shape)
        _rules.validate_zero_state(state_shapes, mesh_shape,
                                   zero_stage=zero)
        dims = {net: _rules.zero_scatter_dims(state_shapes["params"][net],
                                              mesh_shape)
                for net in ("gen", "disc")}

        def _scatter_mean(x, d):
            # psum_scatter sums; /n makes it the pmean the replicated
            # leaves keep — both are sum-then-divide, so a sharded and a
            # replicated leaf see identical reduction arithmetic
            if d < 0:
                return lax.pmean(x, DATA_AXIS)
            return lax.psum_scatter(x, DATA_AXIS, scatter_dimension=d,
                                    tiled=True) / n_shards

        def _gather(x, d):
            return x if d < 0 else lax.all_gather(x, DATA_AXIS, axis=d,
                                                  tiled=True)

        def _map(fn, tree, net):
            return jax.tree_util.tree_map(fn, tree, dims[net])

        reduce_grads = lambda g, net: _map(_scatter_mean, g, net)
        gather_updates = ((lambda u, net: _map(_gather, u, net))
                          if zero == 2 else (lambda u, net: u))
        gather_params = ((lambda p, net: _map(_gather, p, net))
                         if zero >= 3 else (lambda p, net: p))

        if cfg.comm_overlap != "off":
            # Collective overlap plane (ISSUE 20, DESIGN §6n): same math,
            # restructured wire plan. reduce_grads/gather_updates swap
            # their per-leaf collectives for one large collective per
            # dtype-grouped bucket (the plan comes from the SAME rule
            # table that placed the shards, so layouts cannot disagree);
            # each bucket's psum_scatter depends only on its own leaves'
            # cotangents, which is what lets the scheduler issue it while
            # the rest of the backward is still running. Under "prefetch"
            # (stage 3) the up-front full-tree param gather additionally
            # becomes a layer-ahead staged walk. All arms are bit-exact
            # vs "off" (tests/test_comm_overlap.py pins params to the
            # last bit); the @overlap manifest rows pin the shrunken
            # census.
            from dcgan_tpu.parallel import comm as _comm

            plans = {net: _rules.zero_bucket_plan(
                         state_shapes["params"][net], mesh_shape,
                         bucket_mb=cfg.comm_bucket_mb)
                     for net in ("gen", "disc")}
            reduce_grads = lambda g, net: _comm.bucketed_reduce(
                g, dims[net], plans[net], axis_name=DATA_AXIS,
                n_shards=n_shards)
            if zero == 2:
                gather_updates = lambda u, net: _comm.bucketed_gather(
                    u, dims[net], plans[net], axis_name=DATA_AXIS,
                    n_shards=n_shards)
            if zero >= 3 and cfg.comm_overlap == "prefetch":
                gather_params = lambda p, net: _comm.staged_gather(
                    p, lambda nm, _p=p, _net=net: jax.tree_util.tree_map(
                        _gather, _p[nm], dims[_net][nm]))

        zero_hooks = ZeroHooks(reduce_grads=reduce_grads,
                               gather_updates=gather_updates,
                               gather_params=gather_params)

    fns = make_train_step(cfg, axis_name=DATA_AXIS,
                          # the pipelined stages' generator batches are
                          # per-shard inside shard_map (the fused step
                          # derives shapes from its sharded images arg;
                          # these stages have no images arg to read)
                          local_batch=cfg.batch_size // n_shards,
                          zero_hooks=zero_hooks)
    conditional = cfg.model.num_classes > 0
    # The varying-manner checker needs `vma` annotations on every
    # ShapeDtypeStruct a pallas_call emits, which the kernels (written to be
    # backend-agnostic) don't carry — turn static checking off for the fused
    # path; the collective placement is the same either way and is covered by
    # the equivalence tests. ZeRO >= 2 likewise runs unchecked: this
    # container's check_rep tracker has no rule marking tiled
    # psum_scatter/all_gather chains replication-consistent with the
    # sharded out_specs below, and the placement is pinned by the stage
    # 1/2/3 loss-parity tests instead.
    vma = not cfg.model.use_pallas and zero < 2

    def smap(f, in_specs, out_specs):
        # utils/backend.shard_map: the check_vma/check_rep API-graduation
        # compat shim every shard_map site shares — without it this whole
        # backend (and its slow-marked, hence tier-1-invisible, test
        # suite) failed at first use on this container's jax 0.4.37
        from dcgan_tpu.utils.backend import shard_map

        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check=vma)

    rep = replicated(mesh)
    img_spec = P(DATA_AXIS, None, None, None)
    z_spec = P(DATA_AXIS, None)
    lbl_spec = P(DATA_AXIS)
    # state placement: fully replicated at stage 1 (the pre-ZeRO layout,
    # byte-exact — `st` stays the P() prefix the committed fingerprints
    # were traced with); the rule engine's data-sharded tree at stage >= 2,
    # so the per-shard bodies receive local slices of every zero-sharded
    # leaf — exactly what the explicit psum_scatter/all_gather hooks above
    # produce and consume
    if zero >= 2:
        from dcgan_tpu.parallel.sharding import state_shardings

        shardings = state_shardings(state_shapes, mesh, zero_stage=zero)
        st = jax.tree_util.tree_map(lambda s: s.spec, shardings)
    else:
        shardings = None  # derived at the bottom, as before
        st = P()

    def step_body(state, images, key, labels=None):
        # independent z / gradient-penalty draws per shard
        key = jax.random.fold_in(key, lax.axis_index(DATA_AXIS))
        return fns.train_step(state, images, key, labels)

    def sample_body(state, z, labels=None):
        # Gather the shard outputs so sample() returns a replicated array —
        # the ParallelTrain contract ("replicated output for host saving"):
        # on multi-host runs a data-sharded result would not be fully
        # addressable and the trainer's device_get of the grid would fail.
        # Expressed as scatter-into-zeros + psum rather than all_gather
        # because psum's output is statically replicated for the VMA checker
        # (all_gather results are formally still device-varying).
        imgs = fns.sample(state, z, labels)
        per_shard = imgs.shape[0]
        full = jnp.zeros((per_shard * n_shards,) + imgs.shape[1:],
                         imgs.dtype)
        full = lax.dynamic_update_slice_in_dim(
            full, imgs, lax.axis_index(DATA_AXIS) * per_shard, axis=0)
        return lax.psum(full, DATA_AXIS)

    def summarize_body(state, images, key, labels=None):
        # fold like step_body: each shard's generator activations come from
        # an independent z sub-batch, matching the GSPMD backend's single
        # global draw (without folding, all shards would histogram the same
        # batch/n_shards z vectors n_shards times over)
        key = jax.random.fold_in(key, lax.axis_index(DATA_AXIS))
        return fns.summarize(state, images, key, labels)

    if conditional:
        step = jax.jit(
            smap(step_body, (st, img_spec, P(), lbl_spec), (st, P())),
            donate_argnums=(0,))
        sample = jax.jit(
            smap(sample_body, (st, z_spec, lbl_spec), P()))
        # summarize: activation_stats pmaxes min/max before binning and psums
        # the counts (utils/metrics.py), so the per-shard programs emit
        # identical global histograms — replicated outputs.
        summarize = jax.jit(
            smap(summarize_body, (st, img_spec, P(), lbl_spec), P()))
        # eval_losses: per-shard losses pmean'd inside -> replicated metrics
        eval_losses = jax.jit(
            smap(fns.eval_losses, (st, img_spec, z_spec, lbl_spec), P()))
    else:
        step = jax.jit(
            smap(step_body, (st, img_spec, P()), (st, P())),
            donate_argnums=(0,))
        sample = jax.jit(
            smap(sample_body, (st, z_spec), P()))
        summarize = jax.jit(
            smap(summarize_body, (st, img_spec, P()), P()))
        eval_losses = jax.jit(
            smap(fns.eval_losses, (st, img_spec, z_spec), P()))

    # K steps in one per-shard program (see ParallelTrain.multi_step);
    # step_body folds the shard index into each key
    multi_body = make_multi_step_body(step_body)
    scan_img = P(None, *img_spec)
    if conditional:
        multi_step = jax.jit(
            smap(multi_body, (st, scan_img, P(), P(None, *lbl_spec)),
                 (st, P())),
            donate_argnums=(0,))
    else:
        multi_step = jax.jit(
            smap(multi_body, (st, scan_img, P()), (st, P())),
            donate_argnums=(0,))

    init = jax.jit(fns.init,
                   out_shardings=shardings if zero >= 2 else rep)

    # Pipelined stage programs (ISSUE 7): per-shard bodies with the same
    # shard-index key fold as step_body (independent z per shard); the
    # fake stack is batch-sharded on axis 1, slot axis unsharded —
    # exactly what the consuming d_update's fake_spec declares. Traced
    # lazily, so these cost nothing when --pipeline_gd is off.
    fake_spec = P(None, *img_spec)

    def gen_fakes_body(state, key):
        key = jax.random.fold_in(key, lax.axis_index(DATA_AXIS))
        return fns.gen_fakes(state, key)

    def d_update_body(state, images, fakes, key):
        key = jax.random.fold_in(key, lax.axis_index(DATA_AXIS))
        return fns.d_update(state, images, fakes, key)

    def g_update_body(state, key):
        key = jax.random.fold_in(key, lax.axis_index(DATA_AXIS))
        return fns.g_update(state, key)

    gen_fakes = jax.jit(smap(gen_fakes_body, (st, P()), fake_spec))
    d_update = jax.jit(
        # state-only donation: the consumed stack has no same-shaped
        # output to alias onto (see parallel/api.py) — the trainer's
        # buffer manager frees it by reference drop instead
        smap(d_update_body, (st, img_spec, fake_spec, P()), (st, P())),
        donate_argnums=(0,))
    g_update = jax.jit(
        smap(g_update_body, (st, P()), (st, fake_spec, P())),
        donate_argnums=(0,))

    if shardings is None:
        shardings = jax.tree_util.tree_map(
            lambda _: rep, jax.eval_shape(fns.init, jax.random.key(0)))
    return ParallelTrain(mesh=mesh, cfg=cfg, shardings=shardings,
                         init=init, step=step, sample=sample,
                         summarize=summarize, eval_losses=eval_losses,
                         multi_step=multi_step, gen_fakes=gen_fakes,
                         d_update=d_update, g_update=g_update)
