"""Device-mesh construction.

The mesh is the topology abstraction that replaces the reference's
ClusterSpec({"ps": ..., "worker": ...}) (image_train.py:52-55). Axes:

- "data"  — batch sharding; gradient all-reduce rides ICI across it.
- "model" — tensor-parallel axis for the widest weights (latent for DCGAN
  parity — the reference has no TP — but wired end-to-end so larger models
  shard without redesign; SURVEY.md §2.5).

Axis order puts "model" innermost so model-parallel collectives map onto the
fastest ICI links under the default device order.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from dcgan_tpu.config import MeshConfig

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(cfg: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (data, model) Mesh over `devices` (default: all devices)."""
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    data, model = cfg.axis_sizes(len(devices))
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))
