"""Sharded train-step compilation: one jitted SPMD program over the mesh.

What the reference does per step — every worker pulls all weights from the PS
over gRPC, computes an independent update, and pushes it back (image_train.py:
55-67,156-158) — becomes a single compiled program: batch sharded over "data",
params laid out per the sharding rules, gradient all-reduce and synced-BN
moments lowered by GSPMD to ICI collectives, and the whole train state donated
so parameters update in place in HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
from jax.sharding import Mesh

from dcgan_tpu.config import TrainConfig
from dcgan_tpu.parallel.mesh import make_mesh
from dcgan_tpu.parallel.sharding import (
    batch_sharding,
    replicated,
    state_shardings,
)
from dcgan_tpu.train.steps import make_train_step

Pytree = Any

#: `programs`-dict names whose state argument (argnum 0) is donated — in
#: BOTH backends, by construction. The semantic analyzer (DCG007) holds
#: this in both directions against the compiled executables: every donated
#: input of these programs must be realized as an `input_output_aliases`
#: pair (donated-but-unaliased is a silent copy, and under the
#: deserialized-executable guards of DESIGN §6d a latent heap hazard), and
#: no program OUTSIDE this set may donate (an undeclared donor bypasses
#: the trainer's donation-safety discipline). Adding a donating program
#: means adding it here and regenerating analysis/programs.lock.jsonl.
DONATED_PROGRAMS = ("train_step", "multi_step", "d_update", "g_update")


@dataclasses.dataclass(frozen=True)
class ParallelTrain:
    """Compiled, mesh-sharded training surface.

    init(key) -> sharded state
    step(state, images, key)          (unconditional models)
    step(state, images, key, labels)  (conditional models)
    sample(state, z[, labels]) -> images (replicated output for host saving)
    """
    mesh: Mesh
    cfg: TrainConfig
    shardings: Pytree
    init: Callable
    step: Callable
    sample: Callable
    summarize: Callable  # (state, images, key[, labels]) -> activation stats
    eval_losses: Callable  # (state, images, z[, labels]) -> loss metrics
                           # on a held-out batch, no state update
    multi_step: Callable   # (state, images [K,B,...], keys [K][, labels
                           # [K,B]]) -> (state, last step's metrics): K train
                           # steps as ONE compiled lax.scan program — one
                           # host dispatch instead of K (the host round-trip
                           # the reference paid per step, SURVEY.md §2.4 #10,
                           # amortized K-fold)
    # pipelined stage programs (ISSUE 7, --pipeline_gd; unconditional
    # models only — traced lazily, so merely building them for a
    # conditional config is harmless):
    gen_fakes: Callable    # (state, key) -> [n_critic, B, H, W, C] fake
                           # stack — the fill/refill program
    d_update: Callable     # (state, images, fakes, key) -> (state,
                           # metrics): critic update(s) consuming the
                           # provided stack (dead after this dispatch —
                           # the trainer's buffer manager drops it)
    g_update: Callable     # (state, key) -> (state, fakes, metrics):
                           # generator update returning the next step's
                           # d_update input (staleness 1)
    programs: Dict[str, Callable] = dataclasses.field(default_factory=dict)
                           # the same jitted surfaces under stable names
                           # ("init", "train_step", "multi_step", "sampler",
                           # "summarize", "eval_losses", "gen_fakes",
                           # "d_update", "g_update") — the enumeration
                           # the AOT warmup phase (train/warmup.py) lowers
                           # and the per-program perf/compile_ms keys are
                           # reported under; derived from the fields in
                           # __post_init__ so the two backends cannot
                           # drift apart

    def __post_init__(self):
        # thread-discipline tripwire (ISSUE 8): under DCGAN_THREAD_CHECKS=1
        # every program dispatch asserts it runs on the dispatch thread —
        # wrapped BEFORE the programs dict is derived so both surfaces
        # agree; a no-op (nothing wrapped) when the tripwire is off. Both
        # backends construct ParallelTrain, so this one hook covers them.
        from dcgan_tpu.analysis import tripwire

        tripwire.wrap_parallel_train(self)
        if not self.programs:
            object.__setattr__(self, "programs", {
                "init": self.init, "train_step": self.step,
                "multi_step": self.multi_step, "sampler": self.sample,
                "summarize": self.summarize,
                "eval_losses": self.eval_losses,
                "gen_fakes": self.gen_fakes, "d_update": self.d_update,
                "g_update": self.g_update})


def make_multi_step_body(step_fn: Callable) -> Callable:
    """K train steps as one lax.scan over `step_fn`, returning the final
    state and the LAST step's metrics. Shared by both backends so the scan
    carry/metrics semantics cannot diverge.

    Exception: the lazily-computed "r1" metric (TrainConfig.r1_interval > 1)
    reports the window MAX — the last step of a scan window is almost never
    an R1 on-step, so last-step reporting would chart the penalty as zeros.
    """
    def multi_body(state, images, keys, labels=None):
        def body(s, xs):
            if labels is None:
                img, key = xs
                return step_fn(s, img, key)
            img, key, lbl = xs
            return step_fn(s, img, key, lbl)
        xs = (images, keys) if labels is None else (images, keys, labels)
        state, ms = jax.lax.scan(body, state, xs)
        return state, {k: (v.max() if k == "r1" else v[-1])
                       for k, v in ms.items()}
    return multi_body


def make_parallel_train(cfg: TrainConfig,
                        mesh: Optional[Mesh] = None) -> ParallelTrain:
    if cfg.backend == "shard_map":
        from dcgan_tpu.parallel.shard_map_backend import make_shard_map_train

        return make_shard_map_train(cfg, mesh)
    mesh = mesh or make_mesh(cfg.mesh)
    pallas_mesh = None
    if cfg.model.use_pallas and mesh.size > 1:
        # pallas_call is opaque to GSPMD: left alone, the partitioner would
        # replicate activations around every BN — silent collapse of data
        # parallelism. On a pure-DP mesh the fused BN kernels instead run
        # per data-shard inside a shard_map nested in this jit (the ring-
        # attention pattern; ops/norm.py::_pallas_shard_moments) — VERDICT
        # r1 #5. Model/spatial sharding (channel- or height-sharded
        # activations break the kernels' full-channel-vector contract)
        # stays rejected — EXCEPT the spatial + attention case, where the
        # attention already runs in its own explicit shard_map and the
        # flash kernels compose as ring x flash
        # (ops/pallas_attention.py::ring_flash_attention): there, only the
        # BN half of the flag falls back to the jnp path.
        if mesh.shape["model"] > 1 or cfg.mesh.spatial:
            if cfg.mesh.spatial and cfg.model.attn_res:
                # pallas_fused narrows with bn_pallas: the fused conv blocks
                # share the BN kernels' full-channel-vector contract, which
                # height sharding breaks the same way
                cfg = dataclasses.replace(cfg, model=dataclasses.replace(
                    cfg.model, bn_pallas=False, pallas_fused=False))
            else:
                raise ValueError(
                    "use_pallas under the gspmd backend composes with data-"
                    f"parallel meshes only, got mesh={dict(mesh.shape)} "
                    f"(spatial={cfg.mesh.spatial}); the fused kernels need "
                    "full channel vectors per shard")
        else:
            # Pure-DP mesh: BOTH kernel families run per data-shard in
            # nested shard_maps — the fused BN moments via ops/norm.py and
            # (since r5) flash attention via ops/attention.py::attn_apply's
            # pallas_mesh route, so the rev-2 attention presets (flash +
            # XLA BN) scale over data-parallel meshes under the default
            # backend too.
            pallas_mesh = mesh
    spatial = cfg.mesh.spatial
    img_sh = batch_sharding(mesh, 4, spatial=spatial)
    constrain_fake = None
    if spatial:
        # Pin generator outputs to the real-image sharding. Without this the
        # SPMD partitioner can leave the fake branch replicated over "model"
        # while the real branch is height-sharded, and its shared-conv-kernel
        # gradient comes out double-counted (~2x) — see make_train_step.
        constrain_fake = lambda x: jax.lax.with_sharding_constraint(x, img_sh)
    # Under a spatial mesh, attention blocks run as sequence-parallel ring
    # attention over the "model" axis (shard_map nested in the jitted step)
    # instead of letting the partitioner all-gather k/v (ops/attention.py).
    attn_mesh = mesh if (spatial and cfg.model.attn_res) else None
    rep = replicated(mesh)
    z_sh = batch_sharding(mesh, 2)
    lbl_sh = batch_sharding(mesh, 1)

    # scanned-batch shardings: step axis in front, batch sharded on axis 1
    def _scan_sh(base):
        return jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(None, *base.spec))

    constrain_micro = None
    if cfg.grad_accum > 1:
        # same hard requirement the shard_map backend enforces: a microbatch
        # that doesn't divide over the data axis would make GSPMD pad every
        # microbatch to uneven shards — a silent throughput loss, not an
        # error — so reject it here too
        n_data = mesh.shape["data"]
        if (cfg.batch_size // cfg.grad_accum) % n_data:
            raise ValueError(
                f"microbatch {cfg.batch_size // cfg.grad_accum} "
                f"(batch_size/grad_accum) must divide over the {n_data}-way "
                "data axis")
        # Pin the step's (grad_accum, micro, ...) input reshapes to
        # scan-axis-in-front shardings: left alone the partitioner may keep
        # the "data" sharding on the leading (scan) axis after the reshape,
        # which serializes the accumulation loop across the mesh. Rank
        # disambiguates the three step inputs (images 5d / z 3d / labels 2d).
        _micro_sh = {5: _scan_sh(img_sh), 3: _scan_sh(z_sh),
                     2: _scan_sh(lbl_sh)}

        def constrain_micro(x):
            sh = _micro_sh.get(x.ndim)
            return x if sh is None else \
                jax.lax.with_sharding_constraint(x, sh)

    # --- ZeRO-2/3 hooks (ISSUE 13, arXiv:2004.13336) ----------------------
    # Under zero_stage >= 2 the step's gradient/update/forward sites get
    # sharding constraints from the rule engine: grads constrained to the
    # data-sharded ZeRO specs (the partitioner lowers the cross-replica sum
    # as a reduce-scatter), the shard-local Adam updates constrained back
    # to the resident param layout (stage 2: ONE fused all-gather rebuilds
    # replicated params per update; stage 3: identity — params stay
    # resident sharded and forwards gather just in time via gather_params).
    # Under `--comm_overlap` (ISSUE 20, DESIGN §6n) these constraint hooks
    # are already the right shape: the partitioner owns collective
    # placement and combining here, so gspmd's half of the overlap plane
    # is the async-collective XLA scheduler flags the CLI arms before
    # backend init (parallel/comm.py::maybe_apply_xla_overlap_flags) —
    # the explicit bucket/prefetch restructuring lives in the shard_map
    # backend, whose hand-placed collectives the scheduler cannot move.
    zero = cfg.mesh.zero_stage
    zero_hooks = None
    shardings = None
    if zero >= 2:
        from dcgan_tpu.elastic import rules as _rules
        from dcgan_tpu.train.steps import ZeroHooks, init_train_state

        # one init trace + one residency derivation, shared with the jit
        # wiring below (fns.init is the same function, so the shape tree
        # is identical)
        state_shapes = jax.eval_shape(
            lambda k: init_train_state(k, cfg), jax.random.key(0))
        _rules.validate_zero_state(state_shapes, dict(mesh.shape),
                                   zero_stage=zero)
        wsc = jax.lax.with_sharding_constraint
        grad_sh = {net: _rules.grad_shardings(state_shapes["params"][net],
                                              mesh)
                   for net in ("gen", "disc")}
        shardings = state_shardings(state_shapes, mesh, spatial=spatial,
                                    shard_opt=cfg.mesh.shard_opt,
                                    zero_stage=zero)
        resident_sh = shardings["params"]

        def _pin(tree, sh_tree):
            return jax.tree_util.tree_map(lambda x, s: wsc(x, s),
                                          tree, sh_tree)

        if zero >= 3:
            # the stage-1 param layout: what a forward's just-in-time
            # gather rebuilds (stage 2 skips the gather — params are
            # already resident in this layout)
            base_sh = state_shardings(state_shapes, mesh, spatial=spatial,
                                      shard_opt=cfg.mesh.shard_opt
                                      )["params"]
            gather_params = lambda p, net: _pin(p, base_sh[net])
        else:
            gather_params = lambda p, net: p
        zero_hooks = ZeroHooks(
            reduce_grads=lambda g, net: _pin(g, grad_sh[net]),
            gather_updates=lambda u, net: _pin(u, resident_sh[net]),
            gather_params=gather_params)

    fns = make_train_step(cfg, constrain_fake=constrain_fake,
                          constrain_micro=constrain_micro,
                          attn_mesh=attn_mesh, pallas_mesh=pallas_mesh,
                          zero_hooks=zero_hooks)

    if shardings is None:
        state_shapes = jax.eval_shape(fns.init, jax.random.key(0))
        shardings = state_shardings(state_shapes, mesh, spatial=spatial,
                                    shard_opt=cfg.mesh.shard_opt)
    conditional = cfg.model.num_classes > 0

    init = jax.jit(fns.init, out_shardings=shardings)

    multi_body = make_multi_step_body(fns.train_step)

    if conditional:
        step = jax.jit(
            fns.train_step,
            in_shardings=(shardings, img_sh, rep, lbl_sh),
            out_shardings=(shardings, rep),
            donate_argnums=(0,))
        sample = jax.jit(
            fns.sample,
            in_shardings=(shardings, z_sh, lbl_sh),
            out_shardings=rep)
        summarize = jax.jit(
            fns.summarize,
            in_shardings=(shardings, img_sh, rep, lbl_sh),
            out_shardings=rep)
        eval_losses = jax.jit(
            fns.eval_losses,
            in_shardings=(shardings, img_sh, z_sh, lbl_sh),
            out_shardings=rep)
        multi_step = jax.jit(
            multi_body,
            in_shardings=(shardings, _scan_sh(img_sh), rep, _scan_sh(lbl_sh)),
            out_shardings=(shardings, rep),
            donate_argnums=(0,))
    else:
        step = jax.jit(
            fns.train_step,
            in_shardings=(shardings, img_sh, rep),
            out_shardings=(shardings, rep),
            donate_argnums=(0,))
        sample = jax.jit(
            fns.sample,
            in_shardings=(shardings, z_sh),
            out_shardings=rep)
        summarize = jax.jit(
            fns.summarize,
            in_shardings=(shardings, img_sh, rep),
            out_shardings=rep)
        eval_losses = jax.jit(
            fns.eval_losses,
            in_shardings=(shardings, img_sh, z_sh),
            out_shardings=rep)
        multi_step = jax.jit(
            multi_body,
            in_shardings=(shardings, _scan_sh(img_sh), rep),
            out_shardings=(shardings, rep),
            donate_argnums=(0,))

    # Pipelined stage programs (ISSUE 7): the fake stack is image-shaped
    # with the n_critic slot axis in front — the same scan-axis-in-front
    # sharding the multi_step inputs use (batch sharded on axis 1, slot
    # axis unsharded). Only the state is donated: the consumed fake stack
    # is dead after the dispatch too, but d_update has no fake-shaped
    # output to alias it onto, so donating it would be a no-op plus a
    # donation warning per compile — the trainer's buffer manager frees
    # it by dropping its reference instead (gd_pipeline.py).
    fake_sh = _scan_sh(img_sh)
    gen_fakes = jax.jit(fns.gen_fakes,
                        in_shardings=(shardings, rep),
                        out_shardings=fake_sh)
    d_update = jax.jit(fns.d_update,
                       in_shardings=(shardings, img_sh, fake_sh, rep),
                       out_shardings=(shardings, rep),
                       donate_argnums=(0,))
    g_update = jax.jit(fns.g_update,
                       in_shardings=(shardings, rep),
                       out_shardings=(shardings, fake_sh, rep),
                       donate_argnums=(0,))

    return ParallelTrain(mesh=mesh, cfg=cfg, shardings=shardings,
                         init=init, step=step, sample=sample,
                         summarize=summarize, eval_losses=eval_losses,
                         multi_step=multi_step, gen_fakes=gen_fakes,
                         d_update=d_update, g_update=g_update)
