"""Multi-host bring-up over DCN.

Replaces the reference's process topology — explicit ps_hosts/worker_hosts
flags, per-process tf.train.Server, ps processes blocking in server.join()
(image_train.py:27-38,52-63) — with JAX's coordinator-based runtime: every
process is a worker, `jax.distributed.initialize` forms the job over DCN, and
XLA sees one global device set. "Chief" (the reference's task_index==0
Supervisor role, image_train.py:123-129) becomes process_index()==0, which the
trainer uses to gate checkpointing, metrics, and sample grids.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Form the multi-host job. No-ops on single-process runs.

    Args may come from the environment (JAX_COORDINATOR_ADDRESS etc.) the way
    the reference read ps_hosts/worker_hosts/task_index flags.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None and num_processes is None:
        return  # single-process
    if jax.process_count() > 1:
        return  # already initialized (e.g. by the launching harness)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_chief() -> bool:
    """The observability/checkpoint owner (reference: is_chief = task_index==0,
    image_train.py:124)."""
    return jax.process_index() == 0
