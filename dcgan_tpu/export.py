"""Serialized serving artifact: checkpoint -> portable StableHLO sampler.

The reference's ONLY generation surface is the `sampler` node inside its
train graph (SURVEY.md §3.4; image_train.py:179-192) — there is no way to
ship a trained generator anywhere the training process isn't. This module is
the deployment path the reference was missing: `jax.export` bakes the
trained generator weights into ONE serialized StableHLO artifact that

- is platform-retargetable (lowered for cpu AND tpu by default — the same
  bytes serve on a TPU pod or a CPU box),
- has a symbolic batch dimension (any batch size at call time, no retrace),
- needs NOTHING from this framework to serve: any process with jax installed
  can `jax.export.deserialize(blob).call(z)`.

Usage:
    python -m dcgan_tpu.export --checkpoint_dir ckpt --out sampler.jaxexport
    python -m dcgan_tpu.export --checkpoint_dir ckpt/best --use_ema \
        --out sampler.jaxexport --platforms cpu tpu

    # serving side (no dcgan_tpu import needed):
    blob = open("sampler.jaxexport", "rb").read()
    images = jax.export.deserialize(blob).call(z)          # z ~ U(-1,1)
    # conditional checkpoints:    ...call(z, labels)       # labels int32

A JSON sidecar (`<out>.json`) records the calling convention: z_dim,
num_classes, image shape, checkpoint step, weight source (live vs EMA),
plus a `serving` block (ISSUE 9) — weight source and bucket-ladder hint —
so `python -m dcgan_tpu.serve --artifact <out>` can cold-start the
continuous-batching sampler server from the artifact alone, no
checkpoint directory required.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from typing import List, Optional, Sequence

Pytree = dict


def export_sampler(checkpoint_dir: str, out_path: str, *,
                   preset: Optional[str] = None,
                   overrides: Optional[dict] = None,
                   use_ema: bool = False,
                   platforms: Sequence[str] = ("cpu", "tpu"),
                   batch_size: int = 0,
                   max_serve_batch: int = 64,
                   quantize: str = "") -> dict:
    """Bake the checkpoint's generator into a serialized artifact.

    batch_size=0 exports a symbolic batch dimension (serve any batch size);
    a positive value pins it (some embedders prefer static shapes).
    `max_serve_batch` sizes the sidecar's serving bucket-ladder hint (the
    default ladder `dcgan_tpu.serve` AOT-compiles when cold-starting from
    this artifact; a pinned batch_size makes the ladder that one rung).
    Returns the sidecar metadata dict.
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jexport

    from dcgan_tpu.config import TrainConfig, resolve_model_config
    from dcgan_tpu.models.dcgan import sampler_apply
    from dcgan_tpu.parallel import make_mesh, make_parallel_train
    from dcgan_tpu.serve.buckets import build_ladder
    from dcgan_tpu.utils.checkpoint import Checkpointer

    mcfg = resolve_model_config(checkpoint_dir, preset=preset,
                                overrides=overrides)
    # The artifact must be pure StableHLO: pallas_call lowers to a
    # TPU-specific custom call that would pin the bytes to one backend
    # generation, and the kernels are a capability for the long-context
    # path, not the sampler (DESIGN.md §8b). Same image, standard lowering.
    mcfg = dataclasses.replace(mcfg, use_pallas=False)

    cfg = TrainConfig(model=mcfg, batch_size=1, checkpoint_dir=checkpoint_dir)
    pt = make_parallel_train(cfg, make_mesh(cfg.mesh))
    restored = Checkpointer(checkpoint_dir).restore_latest(
        pt.init(jax.random.key(0)))
    if restored is None:
        raise SystemExit(f"no checkpoint under {checkpoint_dir}")
    # Host copies: the weights enter the traced function as constants, so
    # the serialized artifact embeds them and serves with no state of its own.
    state = jax.device_get(restored)
    step = int(state["step"])
    g_params = state["ema_gen"] if use_ema else state["params"]["gen"]
    bn_gen = state["bn"]["gen"]
    quant_report = None
    if quantize == "int8":
        # serving rung of the precision ladder (ISSUE 17): the baked-in
        # weights are post-training int8 quantize-dequantized, and the
        # sidecar records the scheme + measured worst-case weight error so
        # a served artifact is never silently lossy
        from dcgan_tpu.serve.quantize import quantize_dequantize_int8

        g_params, quant_report = quantize_dequantize_int8(g_params)
    elif quantize:
        raise ValueError(f"quantize must be '' or 'int8', got {quantize!r}")

    def sample_fn(z, labels=None):
        return sampler_apply(g_params, bn_gen, z, cfg=mcfg, labels=labels)

    if batch_size > 0:
        b = batch_size
    else:
        (b,) = jexport.symbolic_shape("b")
    z_spec = jax.ShapeDtypeStruct((b, mcfg.z_dim), jnp.float32)
    specs = ((z_spec, jax.ShapeDtypeStruct((b,), jnp.int32))
             if mcfg.num_classes else (z_spec,))

    exported = jexport.export(jax.jit(sample_fn),
                              platforms=tuple(platforms))(*specs)
    blob = exported.serialize()
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "wb") as f:
        f.write(blob)

    meta = {
        "format": "jax.export serialized StableHLO",
        "call": ("(z[b, z_dim] f32, labels[b] i32) -> images"
                 if mcfg.num_classes else "(z[b, z_dim] f32) -> images"),
        "z_dim": mcfg.z_dim,
        "num_classes": mcfg.num_classes or 0,
        "image_shape": [mcfg.output_size, mcfg.output_size, mcfg.c_dim],
        "batch": batch_size if batch_size > 0 else "b (symbolic)",
        "arch": mcfg.arch,
        "step": step,
        "weights": "ema" if use_ema else "live",
        "platforms": list(platforms),
        "bytes": len(blob),
        # serving calling convention (ISSUE 9): everything the sampler
        # server needs to cold-start from this artifact WITHOUT the full
        # checkpoint — which weights the bytes carry, and the bucket
        # ladder its AOT warmup should compile (`python -m
        # dcgan_tpu.serve --artifact <out>` reads this block; explicit
        # --buckets overrides the hint)
        "serving": {
            "source": "ema" if use_ema else "live",
            **({"quantize": quant_report} if quant_report else {}),
            "bucket_ladder": (
                [batch_size] if batch_size > 0
                else list(build_ladder(max_serve_batch).buckets)),
            "z_dist": "uniform(-1,1)",
        },
    }
    with open(out_path + ".json", "w") as f:
        json.dump(meta, f, indent=2)
    return meta


def load_sampler(path: str):
    """Deserialize an exported sampler; returns the `Exported` (use `.call`).

    Provided for symmetry/tests — serving does not need this module
    (`jax.export.deserialize` on the raw bytes is the whole protocol).
    """
    from jax import export as jexport

    with open(path, "rb") as f:
        return jexport.deserialize(f.read())


def build_parser() -> argparse.ArgumentParser:
    from dcgan_tpu.config import add_model_override_flags

    p = argparse.ArgumentParser(
        prog="dcgan_tpu.export",
        description="export a trained sampler as one portable StableHLO "
                    "artifact (weights baked in)")
    p.add_argument("--checkpoint_dir", required=True)
    p.add_argument("--out", default="sampler.jaxexport")
    p.add_argument("--use_ema", action="store_true",
                   help="bake the EMA generator weights instead of the live "
                        "ones")
    p.add_argument("--platforms", nargs="+", default=["cpu", "tpu"],
                   help="XLA backends the artifact is lowered for")
    p.add_argument("--batch_size", type=int, default=0,
                   help="pin the batch dimension (default 0 = symbolic: any "
                        "batch size at call time)")
    p.add_argument("--max_serve_batch", type=int, default=64,
                   help="top rung of the sidecar's serving bucket-ladder "
                        "hint (symbolic-batch artifacts only)")
    p.add_argument("--quantize", default="", choices=["", "int8"],
                   help="post-training quantize the baked-in generator "
                        "weights (int8 symmetric per-channel); the sidecar "
                        "serving block records scheme + measured error")
    p.add_argument("--preset", default=None,
                   help="named config supplying the architecture instead of "
                        "the checkpoint's config.json")
    add_model_override_flags(p)  # same surface as generate/evals
    p.add_argument("--platform", default=None,
                   help="JAX platform to trace/export under (e.g. cpu)")
    return p


def main(argv: Optional[List[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    from dcgan_tpu.config import MODEL_OVERRIDE_FLAGS

    meta = export_sampler(
        args.checkpoint_dir, args.out, preset=args.preset,
        overrides={n: getattr(args, n) for n in MODEL_OVERRIDE_FLAGS},
        use_ema=args.use_ema, platforms=args.platforms,
        batch_size=args.batch_size, max_serve_batch=args.max_serve_batch,
        quantize=args.quantize)
    print(f"[dcgan_tpu.export] step-{meta['step']} {meta['weights']} "
          f"sampler ({meta['arch']}, {meta['bytes']} bytes, "
          f"platforms {','.join(meta['platforms'])}) -> {args.out}")


if __name__ == "__main__":
    main()
