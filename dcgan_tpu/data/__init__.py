"""Input pipeline: TFRecord IO, native threaded loader, device prefetch.

Replaces the reference's queue-runner pipeline (image_input.py) — 16 preprocess
threads feeding tf.train.shuffle_batch whose batches were then round-tripped
device→host→device every step (image_train.py:153,158, SURVEY.md §2.4 #10) —
with a C++ reader/shuffler/batcher feeding sharded jax.Arrays directly, with
prefetch so the TPU never waits on the host.
"""

from dcgan_tpu.data import quarantine  # noqa: F401
from dcgan_tpu.data.pipeline import (  # noqa: F401
    DataConfig,
    make_dataset,
    to_global,
)
from dcgan_tpu.data.synthetic import (  # noqa: F401
    synthetic_batches,
    write_image_tfrecords,
)
from dcgan_tpu.data.tfrecord import read_tfrecords, write_tfrecords  # noqa: F401
