"""ctypes bindings for the native loader, with on-demand compilation.

The shared library is built once from data/native/loader.cc with g++ and
cached next to the source (rebuilt when the source is newer). If no toolchain
is available the pipeline falls back to the pure-Python loader in
pipeline.py — same semantics, slower.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Sequence

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "native", "loader.cc")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "native", "_build")

_lib = None
_lib_lock = threading.Lock()


class NativeLoaderError(RuntimeError):
    pass


def _build_library() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    so_path = os.path.join(_BUILD_DIR, f"libdcgan_loader_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    # per-process tmp name: concurrent builders must not clobber each other's
    # output; os.replace makes the final install atomic either way
    tmp_path = f"{so_path}.{os.getpid()}.tmp"
    cmd = ["g++", "-std=c++17", "-O3", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", tmp_path]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError) as e:
        detail = getattr(e, "stderr", b"")
        raise NativeLoaderError(
            f"native loader build failed: {e}\n"
            f"{detail.decode() if isinstance(detail, bytes) else detail}")
    os.replace(tmp_path, so_path)
    return so_path


def _get_lib():
    global _lib
    with _lib_lock:
        if _lib is None:
            lib = ctypes.CDLL(_build_library())
            lib.dcgan_loader_create.restype = ctypes.c_void_p
            lib.dcgan_loader_create.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_longlong]
            lib.dcgan_loader_next.restype = ctypes.c_int
            lib.dcgan_loader_next.argtypes = [ctypes.c_void_p,
                                              ctypes.POINTER(ctypes.c_float),
                                              ctypes.POINTER(ctypes.c_int32)]
            lib.dcgan_loader_error.restype = ctypes.c_char_p
            lib.dcgan_loader_error.argtypes = [ctypes.c_void_p]
            lib.dcgan_loader_corrupt_count.restype = ctypes.c_longlong
            lib.dcgan_loader_corrupt_count.argtypes = [ctypes.c_void_p]
            lib.dcgan_loader_stop.restype = None
            lib.dcgan_loader_stop.argtypes = [ctypes.c_void_p]
            lib.dcgan_loader_destroy.restype = None
            lib.dcgan_loader_destroy.argtypes = [ctypes.c_void_p]
            _lib = lib
        return _lib


_DTYPE_CODES = {"float64": 0, "float32": 1, "uint8": 2}


class NativeLoader:
    """Threaded shuffle-batch loader over TFRecord shards (see loader.cc)."""

    def __init__(self, paths: Sequence[str], *, batch: int,
                 example_shape: Sequence[int], record_dtype: str = "float64",
                 min_after_dequeue: int = 10_776, n_threads: int = 16,
                 prefetch_batches: int = 4, seed: int = 0,
                 normalize: bool = True, verify_crc: bool = True,
                 loop: bool = True, feature_name: str = "image_raw",
                 label_feature: str = "", max_corrupt_records: int = 0):
        if record_dtype not in _DTYPE_CODES:
            raise ValueError(f"record_dtype must be one of {list(_DTYPE_CODES)}")
        for p in paths:
            if not os.path.exists(p):
                # fail fast like the reference's per-shard existence check
                # (image_input.py:111-113)
                raise FileNotFoundError(f"TFRecord shard not found: {p}")
        self._lib = _get_lib()
        self.batch = int(batch)
        self.example_shape = tuple(int(d) for d in example_shape)
        self.labeled = bool(label_feature)
        self._corrupt_synced = 0   # native count already mirrored into the
        #                            process-wide quarantine tally
        n_floats = int(np.prod(self.example_shape))
        c_paths = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths])
        self._handle = self._lib.dcgan_loader_create(
            c_paths, len(paths), self.batch, n_floats,
            _DTYPE_CODES[record_dtype], int(min_after_dequeue),
            int(n_threads), int(prefetch_batches), int(seed),
            int(bool(normalize)), int(bool(verify_crc)), int(bool(loop)),
            feature_name.encode(), label_feature.encode(),
            int(max_corrupt_records))
        if not self._handle:
            raise NativeLoaderError("loader_create failed")
        self._out = np.empty((self.batch,) + self.example_shape,
                             dtype=np.float32)
        self._out_labels = (np.empty((self.batch,), dtype=np.int32)
                            if self.labeled else None)

    @property
    def corrupt_records(self) -> int:
        """Records the native loader has quarantined so far."""
        if not getattr(self, "_handle", None):
            return self._corrupt_synced
        return int(self._lib.dcgan_loader_corrupt_count(self._handle))

    def _sync_corrupt_count(self) -> None:
        """Mirror the native quarantine count into the process-wide tally
        (data/quarantine.py) so the trainer's data/corrupt_records scalar
        covers both loader implementations."""
        n = self.corrupt_records
        if n > self._corrupt_synced:
            from dcgan_tpu.data import quarantine

            quarantine.add(n - self._corrupt_synced)
            self._corrupt_synced = n

    def next(self):
        """Next float32 [B, ...] batch — or an ([B, ...], int32 [B]) pair for
        labeled configs — or None at end-of-data (loop=False)."""
        rc = self._lib.dcgan_loader_next(
            self._handle,
            self._out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._out_labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
            if self.labeled else None)
        self._sync_corrupt_count()
        if rc == 0:
            if self.labeled:
                return self._out.copy(), self._out_labels.copy()
            return self._out.copy()
        if rc == 1:
            return None
        raise NativeLoaderError(
            self._lib.dcgan_loader_error(self._handle).decode())

    def __iter__(self):
        while True:
            b = self.next()
            if b is None:
                return
            yield b

    def stop(self):
        """Halt the loader's worker threads and unblock any `next()` call
        parked on another thread — WITHOUT freeing the native handle.
        Callers that drive `next()` from their own thread must stop, join
        that thread, then `close()`: destroying the handle while a thread
        is inside `dcgan_loader_next` is a use-after-free."""
        if getattr(self, "_handle", None):
            self._lib.dcgan_loader_stop(self._handle)

    def close(self):
        if getattr(self, "_handle", None):
            try:
                self._sync_corrupt_count()
            except Exception:
                pass
            self._lib.dcgan_loader_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
