// dcgan_tpu native data loader.
//
// TPU-native replacement for the runtime machinery behind the reference's
// input pipeline (image_input.py): the TFRecordReader op, the 16-thread
// queue-runner pool feeding tf.train.shuffle_batch (image_input.py:77-84),
// and the string_input_producer filename queue (image_input.py:115) were all
// TF-internal native components; this file is their standalone equivalent.
//
// Pipeline: reader threads stream TFRecord shards in an endless loop,
// CRC32C-verify frames, parse the tf.train.Example wire format to extract one
// bytes feature (default "image_raw", the reference's single-feature schema,
// image_input.py:42-47), decode float64/float32/uint8 pixels to float32
// (optionally normalizing to [-1,1] — the fix for SURVEY.md §2.4 #1), push
// into a uniform-shuffle reservoir (capacity = min_after_dequeue + 3*batch,
// matching image_input.py:75-76), and assemble contiguous [B,H,W,C] float
// batches into a bounded prefetch queue consumed via the C API below.
//
// Build: g++ -std=c++17 -O3 -shared -fPIC (see native.py); zero dependencies.

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <set>
#include <stdio.h>
#include <string>
#include <utility>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli). Hardware SSE4.2 crc32 instruction when the CPU has it
// (runtime-dispatched; the instruction computes exactly this polynomial),
// byte-table software fallback otherwise. The software path measured 2.8k
// img/s on 64px float64 records vs 14.9k with verification off — CRC was
// eating 5x of loader throughput until this went hardware. The hardware path
// is additionally 3-way interleaved: crc32q has ~3-cycle latency at 1/cycle
// throughput, so a single dependency chain runs the unit at 1/3 utilization;
// three independent chains over three 4 KB sub-chunks recover it, and a
// GF(2) zero-shift operator (the CRC-register evolution for 4096 zero bytes,
// built once by matrix squaring) stitches the three partial CRCs back into
// one stream. Single-chain CRC measured 27% of total loader cost on 64px
// float64 records; interleaving cuts that to roughly a third.
// ---------------------------------------------------------------------------

struct Crc32cTable {
  uint32_t t[256];
  Crc32cTable() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int k = 0; k < 8; ++k)
        crc = (crc & 1) ? (crc >> 1) ^ 0x82F63B78u : crc >> 1;
      t[i] = crc;
    }
  }
};

const Crc32cTable& crc_table() {
  static const Crc32cTable table;
  return table;
}

uint32_t crc32c_sw(const uint8_t* data, size_t n) {
  const Crc32cTable& table = crc_table();
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i)
    crc = table.t[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// GF(2) linear-operator machinery for the 3-way combine. The raw CRC
// register after k zero input bytes is a linear function of the register
// before them; ZERO_CHUNK's operator is built from the one-zero-byte matrix
// by log2(ZERO_CHUNK) squarings.
constexpr size_t ZERO_CHUNK = 4096;  // power of two; 3*4KB blocks

uint32_t gf2_times(const uint32_t mat[32], uint32_t vec) {
  uint32_t sum = 0;
  for (int i = 0; vec; vec >>= 1, ++i)
    if (vec & 1) sum ^= mat[i];
  return sum;
}

struct ZeroShift {
  uint32_t mat[32];  // register-evolution operator for ZERO_CHUNK zero bytes
  ZeroShift() {
    const Crc32cTable& table = crc_table();
    uint32_t m[32], sq[32];
    for (int i = 0; i < 32; ++i) {   // one zero byte: reg' = (reg>>8) ^ T[reg&FF]
      uint32_t reg = 1u << i;
      m[i] = (reg >> 8) ^ table.t[reg & 0xFF];
    }
    int shifts = 0;
    for (size_t c = ZERO_CHUNK; c > 1; c >>= 1) ++shifts;
    for (int s = 0; s < shifts; ++s) {
      for (int i = 0; i < 32; ++i) sq[i] = gf2_times(m, m[i]);
      memcpy(m, sq, sizeof m);
    }
    memcpy(mat, m, sizeof mat);
  }
};

const uint32_t* zero_shift() {
  static const ZeroShift z;
  return z.mat;
}

#if defined(__x86_64__)
__attribute__((target("sse4.2")))
uint32_t crc32c_hw(const uint8_t* data, size_t n) {
  const uint32_t* shift = zero_shift();
  uint32_t reg = 0xFFFFFFFFu;  // raw register; inverted once at the end
  while (n >= 3 * ZERO_CHUNK) {
    // three independent dependency chains over contiguous 4 KB sub-chunks
    uint64_t a = reg, b = 0, c = 0;
    const uint8_t* p0 = data;
    const uint8_t* p1 = data + ZERO_CHUNK;
    const uint8_t* p2 = data + 2 * ZERO_CHUNK;
    for (size_t i = 0; i < ZERO_CHUNK; i += 8) {
      uint64_t x, y, z;
      memcpy(&x, p0 + i, 8);  // unaligned-safe
      memcpy(&y, p1 + i, 8);
      memcpy(&z, p2 + i, 8);
      a = __builtin_ia32_crc32di(a, x);
      b = __builtin_ia32_crc32di(b, y);
      c = __builtin_ia32_crc32di(c, z);
    }
    // crc_raw(reg, c0||c1||c2) = M(M(a) ^ b) ^ c  with M = 4KB zero-shift
    reg = gf2_times(shift, gf2_times(shift, uint32_t(a)) ^ uint32_t(b)) ^
          uint32_t(c);
    data += 3 * ZERO_CHUNK;
    n -= 3 * ZERO_CHUNK;
  }
  uint64_t crc = reg;
  while (n >= 8) {
    uint64_t chunk;
    memcpy(&chunk, data, 8);
    crc = __builtin_ia32_crc32di(crc, chunk);
    data += 8;
    n -= 8;
  }
  uint32_t crc32 = uint32_t(crc);
  while (n--) crc32 = __builtin_ia32_crc32qi(crc32, *data++);
  return ~crc32;
}

uint32_t crc32c(const uint8_t* data, size_t n) {
  static const bool hw = __builtin_cpu_supports("sse4.2");
  return hw ? crc32c_hw(data, n) : crc32c_sw(data, n);
}
#else
uint32_t crc32c(const uint8_t* data, size_t n) { return crc32c_sw(data, n); }
#endif

uint32_t masked_crc32c(const uint8_t* data, size_t n) {
  uint32_t crc = crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

// ---------------------------------------------------------------------------
// Minimal protobuf wire parsing for tf.train.Example
// ---------------------------------------------------------------------------

bool read_varint(const uint8_t* buf, size_t len, size_t* pos, uint64_t* out) {
  uint64_t result = 0;
  int shift = 0;
  while (*pos < len) {
    uint8_t b = buf[(*pos)++];
    result |= uint64_t(b & 0x7F) << shift;
    if (!(b & 0x80)) { *out = result; return true; }
    shift += 7;
    if (shift > 63) return false;
  }
  return false;
}

struct Slice { const uint8_t* p = nullptr; size_t n = 0; };

// Scan a length-delimited submessage for the first field `field_num` with
// wire type 2, returning its payload. Returns false if absent/malformed.
bool find_len_field(Slice msg, uint32_t field_num, Slice* out, size_t* resume) {
  size_t pos = resume ? *resume : 0;
  while (pos < msg.n) {
    uint64_t tag;
    if (!read_varint(msg.p, msg.n, &pos, &tag)) return false;
    uint32_t field = uint32_t(tag >> 3), wt = uint32_t(tag & 7);
    if (wt == 2) {
      uint64_t len;
      if (!read_varint(msg.p, msg.n, &pos, &len) || pos + len > msg.n)
        return false;
      if (field == field_num) {
        *out = {msg.p + pos, size_t(len)};
        if (resume) *resume = pos + len;
        return true;
      }
      pos += len;
    } else if (wt == 0) {
      uint64_t v;
      if (!read_varint(msg.p, msg.n, &pos, &v)) return false;
    } else if (wt == 1) {
      pos += 8;
    } else if (wt == 5) {
      pos += 4;
    } else {
      return false;
    }
  }
  return false;
}

// Example(1) -> the Features submessage holding the feature map. Parsed once
// per record; both feature extractors below then scan this slice.
bool get_features(Slice example, Slice* features) {
  return find_len_field(example, 1, features, nullptr);
}

// Iterate Features' map entries feature(1) {key(1), value(2)}: each call
// yields the next Feature value whose key equals `feature_name` (empty name
// matches every entry). `resume` carries the scan position across calls.
bool next_feature(Slice features, const std::string& feature_name, Slice* out,
                  size_t* resume) {
  Slice entry;
  while (find_len_field(features, 1, &entry, resume)) {
    Slice key{nullptr, 0}, value{nullptr, 0};
    find_len_field(entry, 1, &key, nullptr);
    if (!find_len_field(entry, 2, &value, nullptr)) continue;
    if (!feature_name.empty() &&
        (key.n != feature_name.size() ||
         memcmp(key.p, feature_name.data(), key.n) != 0))
      continue;
    *out = value;
    return true;
  }
  return false;
}

// Feature.bytes_list(1).value(1): first bytes payload of the named feature.
// An empty name matches the first entry that *has* a bytes_list (entries of
// other types — e.g. an int64 label preceding the image in map order — are
// skipped, not errors).
bool extract_bytes_feature(Slice features, const std::string& feature_name,
                           Slice* out) {
  size_t resume = 0;
  Slice value;
  while (next_feature(features, feature_name, &value, &resume)) {
    Slice bytes_list;
    if (!find_len_field(value, 1, &bytes_list, nullptr)) {  // oneof=1
      if (feature_name.empty()) continue;  // wrong-typed entry; keep looking
      return false;
    }
    if (find_len_field(bytes_list, 1, out, nullptr)) return true;
    if (!feature_name.empty()) return false;
  }
  return false;
}

// Feature.int64_list(3).value(1): first int64 of the named feature. The
// value field may be packed (wire type 2, TF's writer) or plain varints.
bool extract_int64_feature(Slice features, const std::string& feature_name,
                           int64_t* out) {
  size_t fresume = 0;
  Slice value;
  if (!next_feature(features, feature_name, &value, &fresume)) return false;
  Slice int64_list;
  if (!find_len_field(value, 3, &int64_list, nullptr)) return false;  // oneof=3
  size_t pos = 0;
  while (pos < int64_list.n) {
    uint64_t tag;
    if (!read_varint(int64_list.p, int64_list.n, &pos, &tag)) return false;
    uint32_t field = uint32_t(tag >> 3), wt = uint32_t(tag & 7);
    if (field == 1 && wt == 0) {
      uint64_t v;
      if (!read_varint(int64_list.p, int64_list.n, &pos, &v)) return false;
      *out = int64_t(v);
      return true;
    }
    if (field == 1 && wt == 2) {
      uint64_t len;
      if (!read_varint(int64_list.p, int64_list.n, &pos, &len) ||
          pos + len > int64_list.n)
        return false;
      if (len == 0) { continue; }
      size_t p2 = pos;
      uint64_t v;
      if (!read_varint(int64_list.p, pos + size_t(len), &p2, &v)) return false;
      *out = int64_t(v);
      return true;
    }
    if (wt == 0) {
      uint64_t v;
      if (!read_varint(int64_list.p, int64_list.n, &pos, &v)) return false;
    } else if (wt == 2) {
      uint64_t len;
      if (!read_varint(int64_list.p, int64_list.n, &pos, &len)) return false;
      pos += len;
    } else if (wt == 1) {
      pos += 8;
    } else if (wt == 5) {
      pos += 4;
    } else {
      return false;
    }
  }
  return false;
}

// ---------------------------------------------------------------------------
// Loader
// ---------------------------------------------------------------------------

enum RecordDtype { DT_F64 = 0, DT_F32 = 1, DT_U8 = 2 };

struct LoaderConfig {
  std::vector<std::string> paths;
  int batch = 64;
  size_t example_floats = 0;   // h*w*c
  RecordDtype dtype = DT_F64;
  int min_after_dequeue = 10776;  // 10% of epoch, image_input.py:134-136
  int n_threads = 16;             // image_input.py:77
  int prefetch_batches = 4;
  uint64_t seed = 0;
  bool normalize = true;          // x/127.5 - 1
  bool verify_crc = true;
  int64_t max_corrupt = 0;        // >0: quarantine (skip + count) up to this
                                  // many corrupt records before failing the
                                  // stream; 0 = fail-fast (seed behavior)
  std::string feature_name = "image_raw";
  std::string label_feature;      // non-empty: also read an int64 label per
                                  // example (the feature the reference's
                                  // pipeline comments out, image_input.py:44)
  bool loop = true;               // endless epochs (queue-runner semantics)

  bool labeled() const { return !label_feature.empty(); }
  // pooled examples carry the label as one trailing float so the shuffle
  // pool / batcher stay image-vs-labeled agnostic
  size_t stride() const { return example_floats + (labeled() ? 1 : 0); }
};

class Loader {
 public:
  explicit Loader(LoaderConfig cfg) : cfg_(std::move(cfg)), rng_(cfg_.seed) {
    capacity_ = size_t(cfg_.min_after_dequeue) + 3 * size_t(cfg_.batch);
    int n = std::max(1, std::min<int>(cfg_.n_threads, int(cfg_.paths.size())));
    // n_readers_ must be written BEFORE any reader starts: the completion
    // check below compares readers_done_ against it, and readers_.size()
    // is NOT safe to read from the reader threads (emplace_back's size
    // update is unsynchronized with the thread it spawns — a reader that
    // finished a tiny shard quickly could read a stale size, never set
    // done_, and deadlock Next() forever).
    n_readers_ = n;
    readers_.reserve(n);
    for (int t = 0; t < n; ++t)
      readers_.emplace_back(&Loader::ReaderLoop, this, t, n);
    batcher_ = std::thread(&Loader::BatcherLoop, this);
  }

  ~Loader() {
    Stop();
    for (auto& t : readers_) t.join();
    batcher_.join();
  }

  // Halt the worker threads and unblock any Next() caller WITHOUT
  // releasing the handle. Consumers that drive Next() from their own
  // thread (data/pipeline.py's DevicePrefetcher) must call this, join
  // their thread, and only then destroy: deleting the Loader while a
  // thread is parked in Next()'s condvar wait tears the mutex/cv down
  // under it — a use-after-free that surfaced as a rare segfault on
  // prefetcher close.
  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    pool_cv_.notify_all();
    space_cv_.notify_all();
    batch_cv_.notify_all();
  }

  // 0 = ok; 1 = end of data (non-loop mode); -1 = error (see error()).
  // out_labels may be null for unlabeled configs.
  int Next(float* out, int32_t* out_labels) {
    std::unique_lock<std::mutex> lk(mu_);
    // End-of-data only when the pool can no longer fill a batch AND the
    // batcher is not mid-assembly (batching_): it drains the pool under the
    // lock but publishes to batches_ later — without the flag a consumer
    // waking in that window would report EOF and drop the final batch.
    while (!batch_cv_.wait_for(lk, std::chrono::seconds(5), [&] {
      return !batches_.empty() ||
             (done_ && !batching_ && pool_.size() < size_t(cfg_.batch))
             || !error_.empty() || stop_;
    })) {
      if (getenv("DCGAN_LOADER_DEBUG")) {
        fprintf(stderr,
                "[loader] Next waiting: batches=%zu pool=%zu done=%d "
                "readers_done=%d/%d batching=%d stop=%d err='%s'\n",
                batches_.size(), pool_.size(), int(done_),
                readers_done_, n_readers_, int(batching_), int(stop_),
                error_.c_str());
      }
    }
    if (!error_.empty()) return -1;
    if (batches_.empty()) return 1;
    std::vector<float> b = std::move(batches_.front());
    batches_.pop_front();
    lk.unlock();
    space_cv_.notify_one();
    batch_cv_.notify_all();  // the batcher waits for prefetch space on this cv
    if (!cfg_.labeled()) {
      memcpy(out, b.data(), b.size() * sizeof(float));
      return 0;
    }
    const size_t ex_n = cfg_.example_floats, stride = cfg_.stride();
    for (int i = 0; i < cfg_.batch; ++i) {
      const float* src = b.data() + size_t(i) * stride;
      memcpy(out + size_t(i) * ex_n, src, ex_n * sizeof(float));
      if (out_labels) out_labels[i] = int32_t(src[ex_n]);
    }
    return 0;
  }

  const char* error() {
    std::lock_guard<std::mutex> lk(mu_);
    return error_.c_str();
  }

  int64_t corrupt_count() const { return corrupt_count_.load(); }

 private:
  void Fail(const std::string& msg) {
    std::lock_guard<std::mutex> lk(mu_);
    if (error_.empty()) error_ = msg;
    batch_cv_.notify_all();
  }

  // Corrupt-record quarantine (--max_corrupt_records): true = the record is
  // counted and the caller skips what it safely can; false = quarantine is
  // off (seed fail-fast) or the budget is exhausted — the stream is failed
  // and the caller must stop. The file+offset log line is what the operator
  // repairs from. Looping datasets re-encounter the same bad record every
  // epoch: repeats are skipped silently (counted and logged once), so the
  // budget bounds DISTINCT corrupt records, not epochs survived.
  bool Quarantine(const std::string& what, const std::string& path,
                  long offset) {
    if (cfg_.max_corrupt <= 0) {
      // fail-fast (seed behavior): the record is not quarantined, so it
      // does not count as one
      Fail(what + " in " + path);
      return false;
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (!quarantined_.insert({path, offset}).second) return true;
    }
    int64_t seen = ++corrupt_count_;
    if (seen > cfg_.max_corrupt) {
      Fail(what + " in " + path + " (corrupt-record budget " +
           std::to_string(cfg_.max_corrupt) + " exhausted)");
      return false;
    }
    fprintf(stderr,
            "[dcgan_loader] quarantined corrupt record: %s (%s @ byte %ld; "
            "%lld/%lld of budget)\n",
            what.c_str(), path.c_str(), offset, (long long)seen,
            (long long)cfg_.max_corrupt);
    return true;
  }

  bool DecodeExample(Slice payload, std::vector<float>* out) {
    // Normalization (raw pixel scale [0,255] -> tanh range [-1,1], the cast
    // the reference's trainer comments out, image_train.py:70) is fused into
    // the dtype-conversion loop — one pass over the example, not two.
    const size_t n = cfg_.example_floats;
    const bool norm = cfg_.normalize;
    const float s = 1.0f / 127.5f;
    out->resize(cfg_.stride());
    float* dst = out->data();
    // Every normalize=false branch is a plain cast/copy (no *1+0, which is
    // not foldable — it would flip -0.0 to +0.0 and cost a FMA per element
    // on the strict-parity path).
    if (cfg_.dtype == DT_F64) {
      if (payload.n != n * 8) return false;
      const double* src = reinterpret_cast<const double*>(payload.p);
      if (norm) {
        for (size_t i = 0; i < n; ++i) dst[i] = float(src[i]) * s - 1.0f;
      } else {
        for (size_t i = 0; i < n; ++i) dst[i] = float(src[i]);
      }
    } else if (cfg_.dtype == DT_F32) {
      if (payload.n != n * 4) return false;
      if (norm) {
        const float* src = reinterpret_cast<const float*>(payload.p);
        for (size_t i = 0; i < n; ++i) dst[i] = src[i] * s - 1.0f;
      } else {
        memcpy(dst, payload.p, n * 4);
      }
    } else {
      if (payload.n != n) return false;
      if (norm) {
        for (size_t i = 0; i < n; ++i) dst[i] = float(payload.p[i]) * s - 1.0f;
      } else {
        for (size_t i = 0; i < n; ++i) dst[i] = float(payload.p[i]);
      }
    }
    return true;
  }

  void PushExample(std::vector<float> ex) {
    std::unique_lock<std::mutex> lk(mu_);
    space_cv_.wait(lk, [&] { return pool_.size() < capacity_ || stop_; });
    if (stop_) return;
    pool_.push_back(std::move(ex));
    if (pool_.size() >= size_t(cfg_.min_after_dequeue) ||
        (done_ && pool_.size() >= size_t(cfg_.batch)))
      pool_cv_.notify_one();
  }

  void ReaderLoop(int tid, int n_threads) {
    std::vector<uint8_t> buf;
    bool first_pass = true;
    while (true) {
      bool read_any = false;
      for (size_t fi = tid; fi < cfg_.paths.size(); fi += n_threads) {
        {
          std::lock_guard<std::mutex> lk(mu_);
          if (stop_) return;
        }
        FILE* f = fopen(cfg_.paths[fi].c_str(), "rb");
        if (!f) {
          Fail("cannot open shard: " + cfg_.paths[fi]);
          return;
        }
        // Per-record failure routing: a data-CRC/parse failure quarantines
        // just that record (framing intact — skip and continue); a length-
        // CRC mismatch or short read leaves no trusted resync point, so the
        // rest of the file is abandoned. Quarantine() returning false means
        // the stream has been failed (budget off or exhausted): stop.
        bool give_up = false;       // stream failed — thread exits
        uint8_t header[12];
        long rec_off;
        while (rec_off = ftell(f), fread(header, 1, 12, f) == 12) {
          uint64_t len;
          memcpy(&len, header, 8);
          if (cfg_.verify_crc) {
            uint32_t lcrc;
            memcpy(&lcrc, header + 8, 4);
            if (masked_crc32c(header, 8) != lcrc) {
              give_up = !Quarantine("length CRC mismatch", cfg_.paths[fi],
                                    rec_off);
              break;  // length untrusted: abandon the rest of this file
            }
          }
          buf.resize(len + 4);
          if (fread(buf.data(), 1, len + 4, f) != len + 4) {
            give_up = !Quarantine("truncated record", cfg_.paths[fi],
                                  rec_off);
            break;
          }
          if (cfg_.verify_crc) {
            uint32_t dcrc;
            memcpy(&dcrc, buf.data() + len, 4);
            if (masked_crc32c(buf.data(), len) != dcrc) {
              if (Quarantine("data CRC mismatch", cfg_.paths[fi], rec_off))
                continue;  // framing intact: skip just this record
              give_up = true;
              break;
            }
          }
          Slice features;
          Slice payload;
          std::vector<float> ex;
          std::string why;
          if (!get_features({buf.data(), size_t(len)}, &features)) {
            why = "malformed Example";
          } else if (!extract_bytes_feature(features, cfg_.feature_name,
                                            &payload)) {
            why = "record missing feature '" + cfg_.feature_name + "'";
          } else if (!DecodeExample(payload, &ex)) {
            why = "bad example payload size";
          } else if (cfg_.labeled()) {
            int64_t label = 0;
            if (!extract_int64_feature(features, cfg_.label_feature,
                                       &label)) {
              why = "record missing int64 feature '" + cfg_.label_feature +
                    "'";
            } else if (label < 0 || label > (int64_t(1) << 24)) {
              // labels ride a float32 pool slot; beyond 2^24 that
              // representation is lossy, so reject rather than silently
              // corrupt class ids
              why = "label " + std::to_string(label) +
                    " out of range [0, 2^24]";
            } else {
              ex[cfg_.example_floats] = float(label);
            }
          }
          if (!why.empty()) {
            if (Quarantine(why, cfg_.paths[fi], rec_off))
              continue;  // skip just this record
            give_up = true;
            break;
          }
          read_any = true;
          PushExample(std::move(ex));
          {
            std::lock_guard<std::mutex> lk(mu_);
            if (stop_) { fclose(f); return; }
          }
        }
        fclose(f);
        if (give_up) return;
      }
      if (first_pass && !read_any && tid == 0 && cfg_.paths.empty()) {
        Fail("no shards given");
        return;
      }
      first_pass = false;
      if (!cfg_.loop) break;
      if (!read_any) break;  // all assigned shards empty: avoid a spin loop
    }
    // non-loop mode: signal completion when the last reader exits
    std::lock_guard<std::mutex> lk(mu_);
    if (++readers_done_ == n_readers_) {
      done_ = true;
      pool_cv_.notify_all();
      batch_cv_.notify_all();
    }
  }

  void BatcherLoop() {
    const size_t ex_n = cfg_.stride();
    while (true) {
      std::vector<std::vector<float>> picked;
      {
        std::unique_lock<std::mutex> lk(mu_);
        pool_cv_.wait(lk, [&] {
          return stop_ || !error_.empty() ||
                 pool_.size() >= size_t(cfg_.min_after_dequeue) + size_t(cfg_.batch) ||
                 (done_ && pool_.size() >= size_t(cfg_.batch));
        });
        if (stop_ || !error_.empty()) return;
        // uniform shuffle: swap a random element to the back, pop it —
        // the dequeue-many semantics of tf.train.shuffle_batch
        for (int i = 0; i < cfg_.batch; ++i) {
          size_t j = std::uniform_int_distribution<size_t>(
              0, pool_.size() - 1)(rng_);
          std::swap(pool_[j], pool_.back());
          picked.push_back(std::move(pool_.back()));
          pool_.pop_back();
        }
        batching_ = true;  // a batch is in flight until published below
      }
      space_cv_.notify_all();
      std::vector<float> batch(size_t(cfg_.batch) * ex_n);
      for (int i = 0; i < cfg_.batch; ++i)
        memcpy(batch.data() + size_t(i) * ex_n, picked[i].data(),
               ex_n * sizeof(float));
      {
        std::unique_lock<std::mutex> lk(mu_);
        batch_cv_.wait(lk, [&] {
          return batches_.size() < size_t(cfg_.prefetch_batches) || stop_;
        });
        if (stop_) return;
        batches_.push_back(std::move(batch));
        batching_ = false;
      }
      batch_cv_.notify_all();
    }
  }

  LoaderConfig cfg_;
  size_t capacity_;
  std::mt19937_64 rng_;

  std::mutex mu_;
  std::condition_variable pool_cv_, space_cv_, batch_cv_;
  std::vector<std::vector<float>> pool_;
  std::deque<std::vector<float>> batches_;
  std::string error_;
  std::atomic<int64_t> corrupt_count_{0};
  std::set<std::pair<std::string, long>> quarantined_;  // (shard, offset)
  bool stop_ = false;
  bool done_ = false;
  bool batching_ = false;   // batcher holds picked examples not yet published
  int readers_done_ = 0;
  int n_readers_ = 0;       // written before threads start; readers_.size()
                            // is not safely readable from reader threads

  std::vector<std::thread> readers_;
  std::thread batcher_;
};

}  // namespace

// ---------------------------------------------------------------------------
// C API (ctypes)
// ---------------------------------------------------------------------------

extern "C" {

void* dcgan_loader_create(const char** paths, int n_paths, int batch,
                          int example_floats, int record_dtype,
                          int min_after_dequeue, int n_threads,
                          int prefetch_batches, uint64_t seed, int normalize,
                          int verify_crc, int loop, const char* feature_name,
                          const char* label_feature, long long max_corrupt) {
  LoaderConfig cfg;
  for (int i = 0; i < n_paths; ++i) cfg.paths.emplace_back(paths[i]);
  cfg.batch = batch;
  cfg.example_floats = size_t(example_floats);
  cfg.dtype = RecordDtype(record_dtype);
  cfg.min_after_dequeue = min_after_dequeue;
  cfg.n_threads = n_threads;
  cfg.prefetch_batches = prefetch_batches;
  cfg.seed = seed;
  cfg.normalize = normalize != 0;
  cfg.verify_crc = verify_crc != 0;
  cfg.loop = loop != 0;
  if (feature_name) cfg.feature_name = feature_name;
  if (label_feature) cfg.label_feature = label_feature;
  cfg.max_corrupt = int64_t(max_corrupt);
  return new Loader(std::move(cfg));
}

// out_labels: int32[batch] when the loader was created with a label_feature;
// pass null for unlabeled configs.
int dcgan_loader_next(void* handle, float* out, int32_t* out_labels) {
  return static_cast<Loader*>(handle)->Next(out, out_labels);
}

const char* dcgan_loader_error(void* handle) {
  return static_cast<Loader*>(handle)->error();
}

// Records quarantined (skipped) so far under max_corrupt > 0; also counts
// the final budget-exhausting record once the stream has failed.
long long dcgan_loader_corrupt_count(void* handle) {
  return static_cast<Loader*>(handle)->corrupt_count();
}

// Non-destructive stop: unblocks a Next() parked on another thread so the
// caller can join it before dcgan_loader_destroy (see Loader::Stop).
void dcgan_loader_stop(void* handle) {
  static_cast<Loader*>(handle)->Stop();
}

void dcgan_loader_destroy(void* handle) {
  delete static_cast<Loader*>(handle);
}

}  // extern "C"
