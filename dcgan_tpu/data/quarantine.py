"""Corrupt-record quarantine: skip-and-count instead of die-on-first.

The seed's policy was fail-fast everywhere: one CRC mismatch raised IOError
out of data/tfrecord.py, the native loader Fail()ed its whole stream, and a
multi-day run died over one flipped bit in one shard. This module is the
shared accounting for the opt-in alternative (`--max_corrupt_records` > 0):
readers SKIP a bad record, log file+offset so the operator can repair or
re-prepare the shard, and count it here — bounded, so systemic corruption
(a truncated dataset, a wrong record_dtype) still hard-fails instead of
silently quarantining the whole corpus.

The counter is process-global on purpose: corruption totals cross loader
instances (train + sample pipelines) and both loader implementations (the
pure-Python readers and the native C++ loader, whose count the ctypes bridge
mirrors in here), and the trainer surfaces one `data/corrupt_records` scalar
per process through utils/metrics.py.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_count = 0


class CorruptRecordError(IOError):
    """The corrupt-record budget was exhausted (or quarantine is off and a
    corrupt record was seen by a quarantine-aware reader)."""


def record(path: str, offset: int, reason: str, *,
           budget: int = 0, seen: int = 1) -> None:
    """Log + count one quarantined record; raise when `seen` (the calling
    loader's own running count, budget-scoped) exceeds `budget`."""
    global _count
    with _lock:
        _count += 1
    print(f"[dcgan_tpu] quarantined corrupt record: {reason} "
          f"({path} @ byte {offset}; {seen}/{budget} of budget)", flush=True)
    if seen > budget:
        raise CorruptRecordError(
            f"corrupt-record budget exhausted: {seen} corrupt record(s) "
            f"with --max_corrupt_records={budget}; last was {reason} in "
            f"{path} @ byte {offset} — repair or re-prepare the shards")


def add(n: int) -> None:
    """Fold externally-counted quarantines (the native loader's) into the
    process total."""
    global _count
    if n > 0:
        with _lock:
            _count += n


def count() -> int:
    """Total records quarantined by this process so far."""
    with _lock:
        return _count


def reset() -> None:
    """Zero the counter — tests."""
    global _count
    with _lock:
        _count = 0
