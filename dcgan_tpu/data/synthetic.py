"""Synthetic data + TFRecord dataset writer (tests, smoke runs, tools).

The writer produces shards in the reference's on-disk schema — one bytes
feature `image_raw` holding raw [H,W,C] pixels, float64 by default
(image_input.py:42-51) — so the loader path is exercised against the real
format without needing CelebA on disk.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence

import numpy as np

from dcgan_tpu.data.example_proto import serialize_example
from dcgan_tpu.data.tfrecord import write_tfrecords


def write_image_tfrecords(out_dir: str, *, num_examples: int,
                          image_size: int = 64, channels: int = 3,
                          num_shards: int = 2, record_dtype: str = "float64",
                          seed: int = 0,
                          feature_name: str = "image_raw",
                          num_classes: int = 0,
                          label_feature: str = "label") -> List[str]:
    """Write `num_examples` random images (pixel scale [0,255]) across shards.

    num_classes > 0 also writes an int64 `label_feature` per example (the
    schema the reference's pipeline comments out, image_input.py:44), for
    conditional-model runs. Returns the shard paths.
    """
    rng = np.random.default_rng(seed)
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    per_shard = (num_examples + num_shards - 1) // num_shards
    written = 0
    for s in range(num_shards):
        n = min(per_shard, num_examples - written)
        if n <= 0:
            break

        def records() -> Iterator[bytes]:
            for _ in range(n):
                img = rng.uniform(0, 255,
                                  size=(image_size, image_size, channels))
                raw = img.astype(record_dtype).tobytes()
                feats = {feature_name: [raw]}
                if num_classes:
                    feats[label_feature] = [int(rng.integers(num_classes))]
                yield serialize_example(feats)

        path = os.path.join(out_dir, f"shard-{s:05d}.tfrecord")
        write_tfrecords(path, records())
        paths.append(path)
        written += n
    return paths


def synthetic_batches(batch_size: int, image_size: int = 64, channels: int = 3,
                      seed: int = 0, num_classes: int = 0) -> Iterator:
    """Endless stream of [-1,1] float32 batches (no disk involved).

    num_classes > 0 yields (images, int32 labels) pairs instead.
    """
    rng = np.random.default_rng(seed)
    while True:
        imgs = np.tanh(rng.normal(
            size=(batch_size, image_size, image_size, channels))
        ).astype(np.float32)
        if num_classes:
            yield imgs, rng.integers(num_classes, size=(batch_size,),
                                     dtype=np.int32)
        else:
            yield imgs
