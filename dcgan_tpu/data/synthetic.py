"""Synthetic data + TFRecord dataset writer (tests, smoke runs, tools).

The writer produces shards in the reference's on-disk schema — one bytes
feature `image_raw` holding raw [H,W,C] pixels, float64 by default
(image_input.py:42-51) — so the loader path is exercised against the real
format without needing CelebA on disk.
"""

from __future__ import annotations

import os
from typing import Iterator, List

import numpy as np

from dcgan_tpu.data.example_proto import serialize_example
from dcgan_tpu.data.tfrecord import write_tfrecords


def write_image_tfrecords(out_dir: str, *, num_examples: int,
                          image_size: int = 64, channels: int = 3,
                          num_shards: int = 2, record_dtype: str = "float64",
                          seed: int = 0,
                          feature_name: str = "image_raw",
                          num_classes: int = 0,
                          label_feature: str = "label") -> List[str]:
    """Write `num_examples` random images (pixel scale [0,255]) across shards.

    num_classes > 0 also writes an int64 `label_feature` per example (the
    schema the reference's pipeline comments out, image_input.py:44), for
    conditional-model runs. Returns the shard paths.
    """
    rng = np.random.default_rng(seed)
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    per_shard = (num_examples + num_shards - 1) // num_shards
    written = 0
    for s in range(num_shards):
        n = min(per_shard, num_examples - written)
        if n <= 0:
            break

        def records() -> Iterator[bytes]:
            for _ in range(n):
                img = rng.uniform(0, 255,
                                  size=(image_size, image_size, channels))
                raw = img.astype(record_dtype).tobytes()
                feats = {feature_name: [raw]}
                if num_classes:
                    feats[label_feature] = [int(rng.integers(num_classes))]
                yield serialize_example(feats)

        path = os.path.join(out_dir, f"shard-{s:05d}.tfrecord")
        write_tfrecords(path, records())
        paths.append(path)
        written += n
    return paths


def synthetic_batches(batch_size: int, image_size: int = 64, channels: int = 3,
                      seed: int = 0, num_classes: int = 0,
                      pool: int = 64) -> Iterator:
    """Endless stream of [-1,1] float32 batches (no disk involved).

    num_classes > 0 yields (images, int32 labels) pairs instead.

    The first `pool` batches are freshly drawn, then the stream cycles them
    (pool=0 disables; every batch fresh — REQUIRED when the stream feeds
    statistics, e.g. the evals CLI's synthetic real side, where duplicated
    samples would bias FID/KID). Synthetic data exists to exercise the
    training machinery, not to be learned from — and drawing batch*H*W*C
    gaussians per step in numpy can be slower than the training step it
    feeds on a small host (measured: a 1-core host generates ~3 MB batches
    at well under the ~65 MB/s a v5e chip consumes at DCGAN-64 throughput).
    Cycling keeps smoke runs device-bound while every batch within an
    epoch-of-`pool` stays distinct. The cache is additionally capped at
    ~256 MB whatever the batch geometry.
    """
    if pool < 0:
        raise ValueError(f"pool must be >= 0, got {pool}")
    rng = np.random.default_rng(seed)
    if pool:
        # 0 when one batch alone exceeds the budget: fall back to fresh
        # batches rather than silently repeating a single giant one
        batch_bytes = 4 * batch_size * image_size * image_size * channels
        pool = min(pool, (256 << 20) // max(1, batch_bytes))
    cache = []
    while True:
        if pool and len(cache) >= pool:
            for item in cache:
                yield item
            continue
        imgs = np.tanh(rng.normal(
            size=(batch_size, image_size, image_size, channels))
        ).astype(np.float32)
        if num_classes:
            item = (imgs, rng.integers(num_classes, size=(batch_size,),
                                       dtype=np.int32))
        else:
            item = imgs
        if pool:
            cache.append(item)
        yield item
