"""TFRecord container format: pure-Python reader/writer.

The on-disk format the reference consumes via TF's TFRecordReader op
(image_input.py:40-41). Implemented from the public spec — each record is:

    uint64 length (little-endian)
    uint32 masked_crc32c(length_bytes)
    byte   data[length]
    uint32 masked_crc32c(data)

with CRC32C (Castagnoli polynomial 0x1EDC6F41, reflected 0x82F63B78) and the
mask rot(crc, 15) + 0xa282ead8. This module is the slow-but-dependency-free
path (tests, tools, fallback); the hot path is the C++ loader in
data/native/ which implements the same format with SSE4.2 crc32 when
available.
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, Iterator, List

_MASK_DELTA = 0xA282EAD8
_U32 = 0xFFFFFFFF


def _make_crc32c_table() -> List[int]:
    poly = 0x82F63B78  # reflected Castagnoli
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return table


_TABLE = _make_crc32c_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    crc = ~crc & _U32
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return ~crc & _U32


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + _MASK_DELTA) & _U32


def write_tfrecords(path: str, records: Iterable[bytes]) -> int:
    """Write serialized records to `path`. Returns the record count."""
    n = 0
    with open(path, "wb") as f:
        for rec in records:
            length = struct.pack("<Q", len(rec))
            f.write(length)
            f.write(struct.pack("<I", masked_crc32c(length)))
            f.write(rec)
            f.write(struct.pack("<I", masked_crc32c(rec)))
            n += 1
    return n


def read_tfrecords(path: str, *, verify_crc: bool = False,
                   on_corrupt=None,
                   with_offsets: bool = False) -> Iterator[bytes]:
    """Yield serialized records from a TFRecord file.

    CRC verification is off by default in this Python path (the C++ loader
    verifies cheaply with hardware crc32); pass verify_crc=True for tools.

    `on_corrupt(offset, reason)`, when given, switches corruption handling
    from raise to quarantine: the callback is invoked (it may itself raise —
    that is how data/quarantine.py enforces its budget) and the reader then
    SKIPS what it safely can. A data-CRC mismatch skips that one record (the
    framing is intact — length and both CRC fields read fine — only the
    payload bytes are bad); a bad length CRC or a truncated tail abandons
    the rest of the file (the length field itself is untrusted, so there is
    no safe resync point).

    `with_offsets=True` yields (file_offset, record) pairs instead of bare
    records, so callers quarantining at the PARSE layer can still log the
    byte position of the record they skipped.
    """
    if not os.path.exists(path):
        # the reference existence-checks every shard up front
        # (image_input.py:111-113); we fail per-file at open
        raise FileNotFoundError(f"TFRecord shard not found: {path}")

    def _corrupt(offset: int, reason: str) -> bool:
        """True = quarantined (caller skips); False-path raises."""
        if on_corrupt is None:
            raise IOError(f"{reason} in {path}")
        on_corrupt(offset, reason)
        return True

    with open(path, "rb") as f:
        while True:
            offset = f.tell()
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                _corrupt(offset, "truncated record header")
                return
            (length,) = struct.unpack("<Q", header[:8])
            if verify_crc:
                (lcrc,) = struct.unpack("<I", header[8:12])
                if masked_crc32c(header[:8]) != lcrc:
                    # the length itself is untrusted — no resync possible
                    _corrupt(offset, "length CRC mismatch")
                    return
            data = f.read(length)
            if len(data) < length:
                _corrupt(offset, "truncated record body")
                return
            tail = f.read(4)
            if len(tail) < 4:
                _corrupt(offset, "truncated record CRC")
                return
            if verify_crc:
                (dcrc,) = struct.unpack("<I", tail)
                if masked_crc32c(data) != dcrc:
                    # framing intact: skip just this record
                    _corrupt(offset, "data CRC mismatch")
                    continue
            yield (offset, data) if with_offsets else data
