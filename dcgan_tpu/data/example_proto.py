"""Minimal tf.train.Example wire-format codec (no protobuf dependency).

The reference's records each hold one bytes feature `image_raw` parsed by
tf.parse_single_example (image_input.py:42-47). This module speaks exactly the
protobuf wire format needed for that schema family:

    Example  { Features features = 1; }
    Features { map<string, Feature> feature = 1; }
    Feature  { oneof { BytesList bytes_list = 1;
                       FloatList float_list = 2;
                       Int64List int64_list = 3; } }
    BytesList{ repeated bytes value = 1; }
    FloatList{ repeated float value = 1 [packed]; }
    Int64List{ repeated int64 value = 1 [packed]; }

Hand-rolled varint/length-delimited parsing — tiny, and the same logic is
mirrored in C++ in data/native/loader.cc for the hot path.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Union

FeatureValue = Union[List[bytes], List[float], List[int]]

_WT_VARINT = 0
_WT_I64 = 1
_WT_LEN = 2
_WT_I32 = 5


def _read_varint(buf: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _write_varint(out: bytearray, value: int) -> None:
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _skip_field(buf: bytes, pos: int, wire_type: int) -> int:
    if wire_type == _WT_VARINT:
        _, pos = _read_varint(buf, pos)
    elif wire_type == _WT_I64:
        pos += 8
    elif wire_type == _WT_LEN:
        n, pos = _read_varint(buf, pos)
        pos += n
    elif wire_type == _WT_I32:
        pos += 4
    else:
        raise ValueError(f"unsupported wire type {wire_type}")
    return pos


def _iter_fields(buf: bytes):
    pos = 0
    end = len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field, wire_type = tag >> 3, tag & 7
        if wire_type == _WT_LEN:
            n, pos = _read_varint(buf, pos)
            yield field, wire_type, buf[pos:pos + n]
            pos += n
        elif wire_type == _WT_VARINT:
            v, pos = _read_varint(buf, pos)
            yield field, wire_type, v
        else:
            start = pos
            pos = _skip_field(buf, pos, wire_type)
            yield field, wire_type, buf[start:pos]


def _parse_float_list(buf: bytes) -> List[float]:
    vals: List[float] = []
    for field, wt, payload in _iter_fields(buf):
        if field == 1 and wt == _WT_LEN:  # packed
            vals.extend(struct.unpack(f"<{len(payload) // 4}f", payload))
        elif field == 1 and wt == _WT_I32:
            vals.append(struct.unpack("<f", payload)[0])
    return vals


def _parse_int64_list(buf: bytes) -> List[int]:
    vals: List[int] = []
    for field, wt, payload in _iter_fields(buf):
        if field == 1 and wt == _WT_LEN:  # packed
            pos = 0
            while pos < len(payload):
                v, pos = _read_varint(payload, pos)
                vals.append(v - (1 << 64) if v >= (1 << 63) else v)
        elif field == 1 and wt == _WT_VARINT:
            vals.append(payload - (1 << 64) if payload >= (1 << 63) else payload)
    return vals


def _parse_feature(buf: bytes) -> FeatureValue:
    for field, wt, payload in _iter_fields(buf):
        if wt != _WT_LEN:
            continue
        if field == 1:    # BytesList
            return [p for f, w, p in _iter_fields(payload)
                    if f == 1 and w == _WT_LEN]
        if field == 2:    # FloatList
            return _parse_float_list(payload)
        if field == 3:    # Int64List
            return _parse_int64_list(payload)
    return []


def parse_example(serialized: bytes) -> Dict[str, FeatureValue]:
    """serialized Example -> {feature name: list of bytes/float/int}."""
    features: Dict[str, FeatureValue] = {}
    for field, wt, payload in _iter_fields(serialized):
        if field != 1 or wt != _WT_LEN:
            continue
        # payload is Features; its field 1 entries are map entries
        for f2, w2, entry in _iter_fields(payload):
            if f2 != 1 or w2 != _WT_LEN:
                continue
            name = b""
            feat: FeatureValue = []
            for f3, w3, p3 in _iter_fields(entry):
                if f3 == 1 and w3 == _WT_LEN:
                    name = p3
                elif f3 == 2 and w3 == _WT_LEN:
                    feat = _parse_feature(p3)
            features[name.decode("utf-8")] = feat
    return features


# ---------------------------------------------------------------------------
# serialization (tools/tests)
# ---------------------------------------------------------------------------

def _len_delimited(out: bytearray, field: int, payload: bytes) -> None:
    _write_varint(out, (field << 3) | _WT_LEN)
    _write_varint(out, len(payload))
    out.extend(payload)


def _encode_feature(value: FeatureValue) -> bytes:
    inner = bytearray()
    if value and isinstance(value[0], (bytes, bytearray)):
        blist = bytearray()
        for v in value:
            _len_delimited(blist, 1, bytes(v))
        _len_delimited(inner, 1, bytes(blist))          # bytes_list = 1
    elif value and isinstance(value[0], float):
        packed = struct.pack(f"<{len(value)}f", *value)
        flist = bytearray()
        _len_delimited(flist, 1, packed)                # packed floats
        _len_delimited(inner, 2, bytes(flist))          # float_list = 2
    else:
        packed = bytearray()
        for v in value:
            _write_varint(packed, v & ((1 << 64) - 1))
        ilist = bytearray()
        _len_delimited(ilist, 1, bytes(packed))
        _len_delimited(inner, 3, bytes(ilist))          # int64_list = 3
    return bytes(inner)


def serialize_example(features: Dict[str, FeatureValue]) -> bytes:
    fmap = bytearray()
    for name, value in features.items():
        entry = bytearray()
        _len_delimited(entry, 1, name.encode("utf-8"))
        _len_delimited(entry, 2, _encode_feature(value))
        _len_delimited(fmap, 1, bytes(entry))
    out = bytearray()
    _len_delimited(out, 1, bytes(fmap))
    return bytes(out)
