"""Dataset preparation CLI: image folder -> TFRecord shards.

The reference assumes pre-built TFRecords (one bytes feature `image_raw` of
raw float64 [64,64,3] pixels, image_input.py:42-51) and carries dead knobs for
the preprocessing that was supposed to produce them: `image_size=108` (the
crop source size, image_train.py:17) and the commented-out
crop/resize/augmentation block (image_input.py:123-132). This tool is that
missing producer, implemented as the reference *intended*: center-crop to
`crop_size`, resize to `image_size`, serialize in the exact schema the input
pipeline (and its C++ loader) consumes.

Default wire format is uint8, not the reference's float64: this repo's own
measurements (BASELINE.md) put the one-core float64 decode ceiling at
~14-18k img/s against a ~21.5k img/s chip consumption rate — the parity
format is input-bound at chip rates by construction (8 bytes/pixel for
values that carry 8 bits). `--record_dtype float64` keeps the strict-parity
byte format available, and the pipeline warns when it meets a chip-rate
consumer (data/pipeline.py).

    python -m dcgan_tpu.data.prepare --input_dir photos/ --output_dir train/
    python -m dcgan_tpu.data.prepare --input_dir cifar/ --output_dir recs/ \
        --labeled --image_size 32 --crop_size 0   # labels from subdir names

--labeled maps each immediate subdirectory of input_dir to a class id
(sorted order) and writes the int64 `label` feature conditional models read.
"""

from __future__ import annotations

import argparse
import json
import os
import random
from typing import List, Optional, Sequence, Tuple

import numpy as np

from dcgan_tpu.data.example_proto import serialize_example
from dcgan_tpu.data.pipeline import MANIFEST_NAME
from dcgan_tpu.data.tfrecord import write_tfrecords

_IMAGE_EXTS = {".png", ".jpg", ".jpeg", ".bmp", ".webp"}


def list_images(input_dir: str, labeled: bool
                ) -> Tuple[List[Tuple[str, int]], List[str]]:
    """[(path, label)], [class names]. Unlabeled: label is always 0."""
    if labeled:
        classes = sorted(
            d for d in os.listdir(input_dir)
            if os.path.isdir(os.path.join(input_dir, d)))
        if not classes:
            raise ValueError(f"--labeled needs class subdirectories under "
                             f"{input_dir}")
        pairs = []
        for idx, cls in enumerate(classes):
            cdir = os.path.join(input_dir, cls)
            for name in sorted(os.listdir(cdir)):
                if os.path.splitext(name)[1].lower() in _IMAGE_EXTS:
                    pairs.append((os.path.join(cdir, name), idx))
        return pairs, classes
    pairs = [(os.path.join(input_dir, name), 0)
             for name in sorted(os.listdir(input_dir))
             if os.path.splitext(name)[1].lower() in _IMAGE_EXTS]
    return pairs, []


def load_and_preprocess(path: str, *, image_size: int, crop_size: int,
                        channels: int = 3) -> np.ndarray:
    """Decode -> optional center-crop to crop_size -> resize to image_size.

    Returns [image_size, image_size, channels] float64 in [0, 255] — the
    pixel scale and dtype of the reference's records (image_input.py:48).
    """
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB" if channels == 3 else "L")
        if crop_size:
            w, h = im.size
            if min(w, h) < crop_size:
                # upscale the short side first so the crop is always valid
                scale = crop_size / min(w, h)
                im = im.resize((max(crop_size, int(round(w * scale))),
                                max(crop_size, int(round(h * scale)))),
                               Image.BILINEAR)
                w, h = im.size
            left = (w - crop_size) // 2
            top = (h - crop_size) // 2
            im = im.crop((left, top, left + crop_size, top + crop_size))
        if im.size != (image_size, image_size):
            im = im.resize((image_size, image_size), Image.BILINEAR)
        arr = np.asarray(im, dtype=np.float64)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def _clear_stale_shards(output_dir: str, overwrite: bool) -> None:
    """Refuse (or, with overwrite, remove) shards from a previous run: the
    pipeline treats every file as a shard, so leftovers would silently mix
    into the dataset."""
    stale = sorted(
        f for f in os.listdir(output_dir)
        if f.startswith("shard-") and f.endswith(".tfrecord"))
    if not stale:
        return
    if not overwrite:
        raise ValueError(
            f"{output_dir} already holds {len(stale)} shard(s); pass "
            "--overwrite to replace them")
    for f in stale:
        os.remove(os.path.join(output_dir, f))
    manifest_path = os.path.join(output_dir, MANIFEST_NAME)
    if os.path.exists(manifest_path):
        os.remove(manifest_path)


def _write_shards(output_dir: str, items: list, record_fn,
                  num_shards: int, manifest: dict) -> List[str]:
    """Split shuffled `items` into contiguous chunks, serialize each via
    `record_fn(item) -> bytes` into shard-NNNNN.tfrecord, and write the
    dataset.json manifest. Shared by every converter so sharding and
    manifest behavior cannot diverge between dataset formats."""
    num_shards = max(1, min(num_shards, len(items)))
    paths: List[str] = []
    bounds = np.linspace(0, len(items), num_shards + 1, dtype=int)
    for s in range(num_shards):
        chunk = items[bounds[s]:bounds[s + 1]]
        shard = os.path.join(output_dir, f"shard-{s:05d}.tfrecord")
        write_tfrecords(shard, (record_fn(item) for item in chunk))
        paths.append(shard)
    with open(os.path.join(output_dir, MANIFEST_NAME), "w") as f:
        json.dump({**manifest, "num_shards": len(paths)}, f, indent=2)
    return paths


def convert(input_dir: str, output_dir: str, *, image_size: int = 64,
            crop_size: int = 108, channels: int = 3, num_shards: int = 8,
            record_dtype: str = "uint8", labeled: bool = False,
            feature_name: str = "image_raw",
            label_feature: str = "label", seed: int = 0,
            overwrite: bool = False) -> List[str]:
    """Convert an image folder to TFRecord shards; returns shard paths.

    Examples are shuffled (seeded) before sharding so shards — and therefore
    per-host shard assignments — are class- and order-balanced. Refuses an
    output_dir that already holds shards unless overwrite=True (stale shards
    from a previous run would otherwise silently mix into the dataset, since
    the pipeline treats every file as a shard). Writes a dataset.json
    manifest (counts, classes, knobs) alongside — metadata the reference
    hard-coded as module constants (NUM_EXAMPLES_PER_EPOCH...,
    image_input.py:11-16) — which make_dataset validates DataConfig against.
    """
    pairs, classes = list_images(input_dir, labeled)
    if not pairs:
        raise ValueError(f"no images found under {input_dir}")
    os.makedirs(output_dir, exist_ok=True)
    _clear_stale_shards(output_dir, overwrite)
    random.Random(seed).shuffle(pairs)

    def record_fn(pair) -> bytes:
        path, label = pair
        arr = load_and_preprocess(path, image_size=image_size,
                                  crop_size=crop_size, channels=channels)
        feats = {feature_name: [arr.astype(record_dtype).tobytes()]}
        if labeled:
            feats[label_feature] = [label]
        return serialize_example(feats)

    return _write_shards(output_dir, pairs, record_fn, num_shards, {
        "num_examples": len(pairs),
        "image_size": image_size,
        "crop_size": crop_size,
        "channels": channels,
        "record_dtype": record_dtype,
        "classes": classes,
        "feature_name": feature_name,
        "label_feature": label_feature if labeled else "",
    })


_CIFAR10_CLASSES = ["airplane", "automobile", "bird", "cat", "deer",
                    "dog", "frog", "horse", "ship", "truck"]


def convert_cifar10(input_dir: str, output_dir: str, *,
                    split: str = "train", image_size: int = 32,
                    num_shards: int = 8, record_dtype: str = "uint8",
                    feature_name: str = "image_raw",
                    label_feature: str = "label", seed: int = 0,
                    overwrite: bool = False) -> List[str]:
    """CIFAR-10 python-version batches -> labeled TFRecord shards.

    Reads the standard `cifar-10-batches-py` pickles (data_batch_1..5 for
    train, test_batch for test): each holds N x 3072 uint8 rows in
    R,G,B-plane order plus a labels list. Feeds the `cifar10-cond` preset
    (class-conditional DCGAN — the config activating the reference's dead
    `y` argument, distriubted_model.py:83).
    """
    import pickle

    names = ([f"data_batch_{i}" for i in range(1, 6)] if split == "train"
             else ["test_batch"])
    xs, ys = [], []
    for name in names:
        path = os.path.join(input_dir, name)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"{path} not found — expected the cifar-10-batches-py "
                "layout")
        with open(path, "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        xs.append(np.asarray(batch[b"data"], dtype=np.uint8))
        ys.extend(int(v) for v in batch[b"labels"])
    # N x 3072 plane-order rows -> NHWC
    images = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)

    os.makedirs(output_dir, exist_ok=True)
    _clear_stale_shards(output_dir, overwrite)
    order = list(range(len(images)))
    random.Random(seed).shuffle(order)

    def record_fn(idx) -> bytes:
        arr = images[idx].astype(np.float64)
        if image_size != 32:
            from PIL import Image

            arr = np.asarray(
                Image.fromarray(images[idx]).resize(
                    (image_size, image_size), Image.BILINEAR),
                dtype=np.float64)
        return serialize_example({
            feature_name: [arr.astype(record_dtype).tobytes()],
            label_feature: [ys[idx]],
        })

    return _write_shards(output_dir, order, record_fn, num_shards, {
        "num_examples": len(order),
        "image_size": image_size,
        "crop_size": 0,
        "channels": 3,
        "record_dtype": record_dtype,
        "classes": _CIFAR10_CLASSES,
        "feature_name": feature_name,
        "label_feature": label_feature,
    })


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dcgan_tpu.data.prepare",
        description="Convert an image folder to the TFRecord schema the "
                    "training pipeline reads.")
    p.add_argument("--input_dir", required=True)
    p.add_argument("--output_dir", required=True)
    p.add_argument("--image_size", type=int, default=None,
                   help="output resolution (default 64; 32 with --cifar10)")
    p.add_argument("--crop_size", type=int, default=108,
                   help="center-crop source size before resizing; 0 disables "
                        "(the reference's intended image_size=108 crop, "
                        "image_train.py:17)")
    p.add_argument("--channels", type=int, default=3)
    p.add_argument("--num_shards", type=int, default=8)
    p.add_argument("--record_dtype", default=None,
                   choices=["float64", "float32", "uint8"],
                   help="on-disk pixel dtype; default uint8 (8x smaller, "
                        "and the only wire format whose one-core decode "
                        "ceiling clears the chip's measured consumption "
                        "rate — BASELINE.md); pass float64 for strict "
                        "parity with the reference (image_input.py:48)")
    p.add_argument("--labeled", action="store_true",
                   help="class subdirectories -> int64 label feature")
    p.add_argument("--cifar10", action="store_true",
                   help="input_dir is a cifar-10-batches-py directory; "
                        "writes labeled 32x32 records (cifar10-cond preset)")
    p.add_argument("--split", choices=["train", "test"], default="train",
                   help="CIFAR-10 split (with --cifar10)")
    p.add_argument("--seed", type=int, default=0,
                   help="shuffle seed for example-to-shard assignment")
    p.add_argument("--overwrite", action="store_true",
                   help="replace shards already present in output_dir")
    return p


def main(argv: Optional[Sequence[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    if args.cifar10:
        paths = convert_cifar10(
            args.input_dir, args.output_dir, split=args.split,
            image_size=args.image_size or 32,
            num_shards=args.num_shards,
            record_dtype=args.record_dtype or "uint8",
            seed=args.seed, overwrite=args.overwrite)
    else:
        paths = convert(args.input_dir, args.output_dir,
                        image_size=args.image_size or 64,
                        crop_size=args.crop_size,
                        channels=args.channels, num_shards=args.num_shards,
                        record_dtype=args.record_dtype or "uint8",
                        labeled=args.labeled,
                        seed=args.seed, overwrite=args.overwrite)
    print(f"wrote {len(paths)} shards to {args.output_dir}")


if __name__ == "__main__":
    main()
