"""High-level input pipeline: shards -> shuffled batches -> sharded device
arrays with prefetch.

The reference's `distorted_inputs(data_dir, batch_size)` (image_input.py:98)
returned a dequeue op whose batches the trainer then pulled to host and fed
back per step (image_train.py:153-158 — the device round-trip defect,
SURVEY.md §2.4 #10). `make_dataset` instead yields jax.Arrays already laid
out with the training step's batch sharding, one batch ahead (double
buffering), so the step consumes device-resident data.

Per-host file sharding replaces the reference's "every worker reads every
file" (image_input.py:107): process i owns shards i, i+P, i+2P, ...
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import queue
import random
import struct
import threading
from typing import Iterator, List, Optional, Sequence

import numpy as np

from dcgan_tpu.data.example_proto import parse_example
from dcgan_tpu.data.tfrecord import read_tfrecords


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Input knobs (reference: image_input.py:11-16,75-84 and trainer flags)."""
    data_dir: str = "train"
    image_size: int = 64
    channels: int = 3
    batch_size: int = 64            # per-process batch
    record_dtype: str = "float64"   # on-disk pixel dtype (image_input.py:48)
    min_after_dequeue: int = 10_776  # 10% of epoch (image_input.py:134-136)
    n_threads: int = 16             # (image_input.py:77)
    prefetch_batches: int = 8       # measured best on a 1-core host (+3-15%
                                    # vs 4 — smooths bursty consumers like
                                    # the scanned multi-step dispatch)
    prefetch_device_batches: int = 2  # depth of the DEVICE-side feed queue:
                                    # a background thread assembles host
                                    # batches and starts their H2D transfer,
                                    # keeping up to N already-sharded device
                                    # batches ready ahead of the consumer —
                                    # host batch assembly + transfer overlap
                                    # device compute instead of alternating
                                    # with it. 0 = the legacy single-slot
                                    # double buffer on the consumer thread
    seed: int = 0
    normalize: bool = True          # [-1,1]; False = strict reference parity
    feature_name: str = "image_raw"
    label_feature: str = ""         # non-empty: also read an int64 label per
                                    # example (the feature the reference
                                    # comments out, image_input.py:44) and
                                    # yield (images, labels) batches
    num_classes: int = 0            # >0: validate every label < num_classes
                                    # host-side before transfer. On device an
                                    # out-of-range label fails SILENTLY two
                                    # different ways (one_hot -> zeros; the
                                    # cBN table gather -> clamped index), so
                                    # the pipeline is where it must be caught
    max_corrupt_records: int = 0    # >0: CRC/parse failures QUARANTINE the
                                    # record (skip + log file/offset + count,
                                    # data/quarantine.py) up to this many
                                    # before hard-failing; 0 = any corrupt
                                    # record is fatal (seed behavior). The
                                    # pure-Python loader verifies CRCs only
                                    # when quarantine is on (detection needs
                                    # verification; the native loader always
                                    # verifies, in hardware)
    use_native: bool = True         # C++ loader; False = pure-Python fallback
    loop: bool = True


# Sidecar manifest prepare.py writes next to its shards; the one filename
# list_shards exempts from "every file is a shard".
MANIFEST_NAME = "dataset.json"


def list_shards(data_dir: str) -> List[str]:
    """Every regular file in data_dir is a shard, as the reference assumes
    (image_input.py:107) — except the dataset.json manifest prepare.py
    writes next to its shards."""
    paths = sorted(p for p in glob.glob(os.path.join(data_dir, "*"))
                   if os.path.isfile(p)
                   and os.path.basename(p) != MANIFEST_NAME)
    if not paths:
        raise FileNotFoundError(f"no TFRecord shards in {data_dir}")
    return paths


def read_manifest(data_dir: str) -> dict:
    """The dataset.json sidecar prepare.py writes, or {} when absent —
    lets read-only consumers (evals, trajectory tools) adopt the recorded
    wire format instead of requiring the user to re-specify it."""
    path = os.path.join(data_dir, MANIFEST_NAME)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def check_manifest(data_dir: str, cfg: "DataConfig") -> None:
    """Validate DataConfig against the dataset.json manifest, if present.

    prepare.py records the knobs the records were written with; a mismatched
    DataConfig otherwise fails deep in the loader ("example has N values,
    expected M") or, for byte-coincidental sizes, silently misreads pixels.
    """
    manifest = read_manifest(data_dir)
    if not manifest:
        return
    checks = [
        ("image_size", cfg.image_size),
        ("channels", cfg.channels),
        ("record_dtype", cfg.record_dtype),
        ("feature_name", cfg.feature_name),
    ]
    problems = [
        f"{key}: dataset was prepared with {manifest[key]!r}, "
        f"config says {got!r}"
        for key, got in checks
        if key in manifest and manifest[key] != got
    ]
    if cfg.label_feature and manifest.get("label_feature", "") and \
            manifest["label_feature"] != cfg.label_feature:
        problems.append(
            f"label_feature: dataset has {manifest['label_feature']!r}, "
            f"config says {cfg.label_feature!r}")
    if cfg.label_feature and "label_feature" in manifest and \
            not manifest["label_feature"]:
        problems.append(
            "config requests labels but the dataset was prepared unlabeled")
    if problems:
        raise ValueError(
            f"DataConfig disagrees with "
            f"{os.path.join(data_dir, MANIFEST_NAME)}:\n  "
            + "\n  ".join(problems))


def shard_for_process(paths: Sequence[str], process_index: int,
                      process_count: int) -> List[str]:
    mine = [p for i, p in enumerate(paths)
            if i % process_count == process_index]
    # fewer shards than processes: everyone reads everything, seeds differ
    return mine or list(paths)


def process_local_box(sharding, global_shape, *, devices=None):
    """Bounding box (tuple of index slices) of THIS process's addressable
    shards of a global array under `sharding`.

    `devices`: override the "addressable" set (default: devices whose
    process_index is this process's) — lets single-process tests exercise
    the multi-process geometry.

    `make_array_from_process_local_data` requires local data shaped like the
    process-local portion of the global array — which is "batch/process_count
    x everything else" ONLY when each process's devices cover whole rows of
    every non-batch sharded axis. Under a spatial mesh whose "model" axis
    spans processes (e.g. 4 processes x 2 devices with a 4-way height axis),
    a process owns a batch-slice x height-slice BLOCK instead; feeding it
    the naive per-process batch silently mis-assembles the global array
    (observed as a doubled height dim at trace time). This helper computes
    the true block from the sharding itself, so data sources can produce
    exactly the addressable portion for ANY (data, model) layout.
    """
    import jax

    idx_map = sharding.devices_indices_map(tuple(global_shape))
    if devices is not None:
        owned = set(devices)
        mine = [idx for d, idx in idx_map.items() if d in owned]
    else:
        mine = [idx for d, idx in idx_map.items()
                if d.process_index == jax.process_index()]
    if not mine:  # no addressable shard (shouldn't happen in practice)
        raise ValueError("sharding has no addressable shards here")
    ndim = len(global_shape)
    lo = [min(s.indices(global_shape[a])[0] for s in (idx[a] for idx in mine))
          for a in range(ndim)]
    hi = [max(s.indices(global_shape[a])[1] for s in (idx[a] for idx in mine))
          for a in range(ndim)]
    # the union of this process's shards must tile the bounding box exactly
    # (true for any mesh-aligned NamedSharding; guards pathological cases)
    distinct = {tuple((s.indices(global_shape[a])[:2])
                      for a, s in enumerate(idx)) for idx in mine}
    box_vol = 1
    for a in range(ndim):
        box_vol *= hi[a] - lo[a]
    tiled = sum(
        int(np.prod([e - b for b, e in idx])) for idx in distinct)
    if tiled != box_vol:
        raise ValueError(
            f"process-local shards do not tile a box: {sorted(distinct)}")
    return tuple(slice(lo[a], hi[a]) for a in range(ndim))


# ---------------------------------------------------------------------------
# Pure-Python loader (fallback / reference implementation for tests)
# ---------------------------------------------------------------------------

class PythonLoader:
    """Same contract as native.NativeLoader, implemented with Python threads.

    Reader threads parse shards into a shuffle pool; a batcher assembles
    batches into a bounded queue.
    """

    def __init__(self, paths: Sequence[str], *, batch: int,
                 example_shape: Sequence[int], record_dtype: str = "float64",
                 min_after_dequeue: int = 1024, n_threads: int = 4,
                 prefetch_batches: int = 4, seed: int = 0,
                 normalize: bool = True, loop: bool = True,
                 feature_name: str = "image_raw", label_feature: str = "",
                 verify_crc: bool = False, max_corrupt_records: int = 0):
        self.batch = batch
        self.example_shape = tuple(example_shape)
        self.labeled = bool(label_feature)
        self._paths = list(paths)
        self._dtype = np.dtype(record_dtype)
        self._mad = min_after_dequeue
        # same capacity bound as the native loader (and the reference's queue,
        # image_input.py:75-76): readers block when the pool is full
        self._capacity = min_after_dequeue + 3 * batch
        self._normalize = normalize
        self._loop = loop
        self._feature = feature_name
        self._label_feature = label_feature
        self._rng = random.Random(seed)
        self._verify_crc = verify_crc
        self._max_corrupt = max_corrupt_records
        self._corrupt = 0            # DISTINCT records quarantined
        self._quarantined: set = set()   # (path, offset) already counted
        self._pool: List[np.ndarray] = []
        self._pool_lock = threading.Condition()
        self._batches: "queue.Queue" = queue.Queue(maxsize=prefetch_batches)
        self._stop = False
        self._error: Optional[str] = None
        self._readers_done = 0
        n = max(1, min(n_threads, len(self._paths)))
        self._n_readers = n
        self._threads = [
            threading.Thread(target=self._read_loop, args=(t, n), daemon=True)
            for t in range(n)]
        self._threads.append(
            threading.Thread(target=self._batch_loop, daemon=True))
        for t in self._threads:
            t.start()

    def _decode(self, payload: bytes) -> np.ndarray:
        n = int(np.prod(self.example_shape))
        arr = np.frombuffer(payload, dtype=self._dtype)
        if arr.size != n:
            raise ValueError(
                f"example has {arr.size} values, expected {n}")
        x = arr.astype(np.float32).reshape(self.example_shape)
        if self._normalize:
            x = x / 127.5 - 1.0
        return x

    @property
    def corrupt_records(self) -> int:
        """Records this loader has quarantined so far."""
        return self._corrupt

    def _quarantine(self, path: str, offset: int, reason: str) -> None:
        """Count one skipped record; raises CorruptRecordError past the
        budget (data/quarantine.py owns the log line and the process-wide
        tally the trainer surfaces as data/corrupt_records). A looping
        dataset re-encounters the same bad record every epoch — repeats are
        skipped silently, so the budget bounds DISTINCT corrupt records,
        not epochs survived."""
        from dcgan_tpu.data import quarantine

        with self._pool_lock:
            if (path, offset) in self._quarantined:
                return
            self._quarantined.add((path, offset))
            self._corrupt += 1
            seen = self._corrupt
        quarantine.record(path, offset, reason,
                          budget=self._max_corrupt, seen=seen)

    def _read_loop(self, tid: int, n_threads: int) -> None:
        quarantining = self._max_corrupt > 0
        try:
            while not self._stop:
                read_any = False
                for i in range(tid, len(self._paths), n_threads):
                    path = self._paths[i]
                    on_corrupt = (
                        (lambda off, why, p=path: self._quarantine(p, off,
                                                                   why))
                        if quarantining else None)
                    for off, rec in read_tfrecords(
                            path, verify_crc=self._verify_crc,
                            on_corrupt=on_corrupt, with_offsets=True):
                        try:
                            feats = parse_example(rec)
                            if self._feature not in feats:
                                raise ValueError(
                                    "record missing feature "
                                    f"{self._feature!r}")
                            x = self._decode(feats[self._feature][0])
                            if self.labeled:
                                lab = feats.get(self._label_feature)
                                if not lab:
                                    raise ValueError(
                                        "record missing int64 feature "
                                        f"{self._label_feature!r}")
                                # same bound as the native loader: reject
                                # rather than silently wrap/round class ids
                                if not 0 <= int(lab[0]) <= (1 << 24):
                                    raise ValueError(
                                        f"label {int(lab[0])} out of range "
                                        "[0, 2^24]")
                                x = (x, np.int32(lab[0]))
                        except (ValueError, IndexError, KeyError,
                                struct.error) as e:
                            # parse-layer corruption: quarantine the record
                            # like a CRC failure, or fail-fast when off.
                            # parse_example surfaces malformed proto bytes
                            # as struct.error/IndexError, not just
                            # ValueError — all of them are data faults here
                            if not quarantining:
                                raise
                            self._quarantine(path, off,
                                             f"{type(e).__name__}: {e}")
                            continue
                        read_any = True
                        with self._pool_lock:
                            self._pool_lock.wait_for(
                                lambda: len(self._pool) < self._capacity
                                or self._stop)
                            if self._stop:
                                return
                            self._pool.append(x)
                            self._pool_lock.notify_all()
                if not self._loop or not read_any:
                    break
        except Exception as e:  # surface errors to the consumer
            self._error = str(e)
        finally:
            with self._pool_lock:
                self._readers_done += 1
                self._pool_lock.notify_all()

    def _batch_loop(self) -> None:
        while not self._stop:
            with self._pool_lock:
                def ready():
                    done = self._readers_done == self._n_readers
                    return (self._stop or self._error or
                            len(self._pool) >= self._mad + self.batch or
                            (done and len(self._pool) >= self.batch) or
                            (done and not self._loop))
                self._pool_lock.wait_for(ready)
                if self._stop or self._error:
                    self._batches.put(None)
                    return
                if len(self._pool) < self.batch:
                    self._batches.put(None)  # end of data
                    return
                picked = []
                for _ in range(self.batch):
                    j = self._rng.randrange(len(self._pool))
                    self._pool[j], self._pool[-1] = (self._pool[-1],
                                                     self._pool[j])
                    picked.append(self._pool.pop())
                self._pool_lock.notify_all()  # wake readers waiting for space
            if self.labeled:
                self._batches.put((np.stack([p[0] for p in picked]),
                                   np.asarray([p[1] for p in picked],
                                              dtype=np.int32)))
            else:
                self._batches.put(np.stack(picked))

    def next(self):
        """Next [B, ...] batch — an (images, int32 labels) pair when labeled —
        or None at end-of-data."""
        b = self._batches.get()
        if b is None and self._error:
            raise RuntimeError(self._error)
        return b

    def __iter__(self):
        while True:
            b = self.next()
            if b is None:
                return
            yield b

    def close(self):
        self._stop = True
        with self._pool_lock:
            self._pool_lock.notify_all()
        try:
            while True:
                self._batches.get_nowait()
        except queue.Empty:
            pass


# ---------------------------------------------------------------------------
# Device pipeline
# ---------------------------------------------------------------------------

def _make_loader(cfg: DataConfig, paths: Sequence[str], seed: int):
    shape = (cfg.image_size, cfg.image_size, cfg.channels)
    kwargs = dict(batch=cfg.batch_size, example_shape=shape,
                  record_dtype=cfg.record_dtype,
                  min_after_dequeue=cfg.min_after_dequeue,
                  n_threads=cfg.n_threads,
                  prefetch_batches=cfg.prefetch_batches, seed=seed,
                  normalize=cfg.normalize, loop=cfg.loop,
                  feature_name=cfg.feature_name,
                  label_feature=cfg.label_feature,
                  max_corrupt_records=cfg.max_corrupt_records)
    if cfg.use_native:
        try:
            from dcgan_tpu.data.native import NativeLoader
            return NativeLoader(paths, **kwargs)
        except Exception as e:
            import warnings
            warnings.warn(f"native loader unavailable ({e}); "
                          "using pure-Python loader")
    # the pure-Python CRC pass is a per-byte Python loop — too slow to run
    # unconditionally on the fallback path, but quarantine without
    # verification cannot DETECT a payload flip, so opting in turns it on
    return PythonLoader(paths, verify_crc=cfg.max_corrupt_records > 0,
                        **kwargs)


def to_global(batch, sharding, label_sharding=None):
    """Host batch — or an (images, labels) pair — to global sharded arrays."""
    import jax

    if isinstance(batch, tuple):
        imgs, labels = batch
        if label_sharding is None:
            raise ValueError("labeled dataset needs label_sharding")
        return (jax.make_array_from_process_local_data(sharding, imgs),
                jax.make_array_from_process_local_data(label_sharding, labels))
    return jax.make_array_from_process_local_data(sharding, batch)


def _check_labels(batch, num_classes: int):
    """Host-side label-range gate (see DataConfig.num_classes) — shared by
    the inline and prefetch-thread feed paths."""
    labels = batch[1]
    bad = int(labels.max(initial=0))
    if bad >= num_classes or int(labels.min(initial=0)) < 0:
        raise ValueError(
            f"label {bad} out of range for num_classes="
            f"{num_classes} (dataset/config mismatch; on device "
            "this would silently one-hot to zeros or clamp the cBN "
            "table gather)")


class DevicePrefetcher:
    """Background device-feed thread: host batches -> a bounded queue of
    already-sharded global device arrays.

    The single-slot double buffer this replaces still ran batch assembly
    and the H2D transfer start on the CONSUMER's thread — the trainer's
    dispatch thread alternated between feeding and dispatching (the stall
    class ParaGAN's congestion-aware pipeline attacks, PAPERS.md
    arxiv 2411.03999). Here one producer thread pulls `host_iter`,
    validates labels, and calls `to_global` (which starts the transfer),
    so up to `depth` device batches sit ready while the device computes.

    Order is the host iterator's order (single producer, FIFO queue).
    Producer exceptions re-raise on the consumer thread at the next
    `__next__`. `close()` is idempotent, safe mid-epoch, unblocks a
    producer stuck on a full queue, and closes `owner` (the underlying
    loader) when given.
    """

    _SENTINEL = object()

    def __init__(self, host_iter: Iterator, sharding, label_sharding=None, *,
                 depth: int = 2, num_classes: int = 0, owner=None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._host_iter = host_iter
        self._sharding = sharding
        self._label_sharding = label_sharding
        self._num_classes = num_classes
        self._owner = owner
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._produce, name="dcgan-device-feed", daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Queue-put that stays interruptible by close()."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            for batch in self._host_iter:
                if self._stop.is_set():
                    return
                if self._num_classes and isinstance(batch, tuple):
                    _check_labels(batch, self._num_classes)
                arr = to_global(batch, self._sharding, self._label_sharding)
                if not self._put(arr):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised on consumer
            self._error = e
        finally:
            self._put(self._SENTINEL)

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self):
        while True:
            if self._stop.is_set():
                raise StopIteration
            try:
                item = self._queue.get(timeout=0.5)
            except queue.Empty:
                # producer still filling (or wedged on a slow loader) —
                # keep waiting unless it died with an error
                if self._error is not None and not self._thread.is_alive():
                    self._raise()
                continue
            if item is self._SENTINEL:
                if self._error is not None:
                    self._raise()
                raise StopIteration
            return item

    def _raise(self):
        err = self._error
        self._error = None
        self.close()
        # re-raise the producer's exception with its original type and
        # traceback — consumers match on the loader's own error classes
        raise err

    def close(self) -> None:
        """Stop the producer and release the loader. Mid-epoch safe: any
        queued device batches are discarded. A producer parked in the
        loader's untimed batch get() must be unblocked by the loader
        itself, not our stop flag — but RELEASING the loader while the
        producer is still inside it is a use-after-free (the native
        loader's destroy tears the handle down under a thread parked in
        `dcgan_loader_next`; segfault chased on prefetcher close). So the
        order is: non-destructive owner `stop()` (unblocks the producer),
        join, THEN destroy. Owners without a `stop()` (the pure-Python
        loaders) keep the old unblock path — their `close()` is the
        sentinel put and frees no native state."""
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        owner, self._owner = self._owner, None
        stop = getattr(owner, "stop", None)
        if callable(stop):
            stop()
        elif owner is not None and hasattr(owner, "close"):
            owner.close()
            owner = None
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        if owner is not None and hasattr(owner, "close"):
            owner.close()

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def make_dataset(cfg: DataConfig, sharding=None,
                 label_sharding=None) -> Iterator:
    """Endless (or one-epoch, cfg.loop=False) iterator of device batches.

    With `sharding` (a NamedSharding over the mesh's data axis), each yielded
    array is a global array assembled from this process's local batch —
    cfg.batch_size is the PER-PROCESS batch, and the global batch is
    batch_size * process_count. Without `sharding`, yields host numpy.

    With cfg.label_feature set, yields (images, labels) pairs; labels use
    `label_sharding` (required alongside `sharding` for labeled configs).

    With cfg.prefetch_device_batches > 0 (the default) the returned
    iterator is a DevicePrefetcher — a background thread keeps that many
    sharded device batches queued ahead of the consumer; call `.close()`
    (or exhaust it) to release the feed thread and the loader. 0 keeps the
    legacy consumer-thread double buffer.
    """
    import jax

    check_manifest(cfg.data_dir, cfg)
    if cfg.record_dtype == "float64" and any(
            d.platform not in ("cpu",) for d in jax.devices()):
        # The parity wire format is input-bound at accelerator rates by this
        # repo's own measurements (BASELINE.md: ~14-18k img/s one-core
        # float64 decode ceiling vs ~21.5k img/s chip consumption). Warn,
        # don't fail: short runs and parity experiments are legitimate.
        import warnings

        warnings.warn(
            "float64 TFRecords feeding an accelerator: the float64 decode "
            "ceiling (~14-18k img/s/core) is below the chip's measured "
            "consumption rate — re-prepare with --record_dtype uint8 "
            "(the default) unless byte-exact reference parity is the goal",
            RuntimeWarning, stacklevel=2)
    paths = shard_for_process(list_shards(cfg.data_dir),
                              jax.process_index(), jax.process_count())
    loader = _make_loader(cfg, paths, cfg.seed + jax.process_index())
    labeled = bool(cfg.label_feature)

    if sharding is None:
        return iter(loader)
    if labeled and label_sharding is None:
        raise ValueError("labeled dataset needs label_sharding")
    if cfg.prefetch_device_batches > 0:
        return DevicePrefetcher(
            iter(loader), sharding, label_sharding,
            depth=cfg.prefetch_device_batches,
            num_classes=cfg.num_classes if labeled else 0,
            owner=loader)
    return _double_buffer(cfg, loader, sharding, label_sharding,
                          labeled=labeled)


def _double_buffer(cfg: DataConfig, loader, sharding, label_sharding, *,
                   labeled: bool) -> Iterator:
    """Legacy consumer-thread feed (prefetch_device_batches=0): keep one
    device transfer in flight ahead of the consumer."""
    pending = None
    for batch in iter(loader):
        if labeled and cfg.num_classes:
            _check_labels(batch, cfg.num_classes)
        nxt = to_global(batch, sharding, label_sharding)
        if pending is not None:
            yield pending
        pending = nxt
    if pending is not None:
        yield pending
