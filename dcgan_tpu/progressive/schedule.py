"""Progressive-resolution schedule: the phase table as data (ISSUE 15).

ParaGAN (PAPERS.md) frames large-scale GAN training as a SCHEDULE of
differently-shaped compiled programs rather than one fixed graph; the
pjit-on-TPUv4 work (arXiv:2204.06514) shows shape-bucketed AOT plans are
what make shape changes free. This module is the declarative half of
that composition for tpu-dcgan: `--progressive "64:2000,128:2000,256:*"`
parses into an ordered tuple of phases, each resolving to a
(resolution, steps, batch) triple validated against the model stack and
the dispatch granule, plus an optional linear fade-in alpha for the
steps right after each switch.

Spec grammar (one string, config + CLI):

    spec   := phase ("," phase)*
    phase  := RES ":" STEPS [":" BATCH]
    STEPS  := positive int | "*"       ("*" = the rest of the run;
                                        REQUIRED on the last phase —
                                        the run length stays max_steps'
                                        business, never the schedule's)

Resolutions must be strictly ascending powers-of-two sites of the model
stack (base_size * 2^k), and the LAST phase's resolution must equal
`ModelConfig.output_size` — the base config always describes the final
model, earlier phases are its shallower variants. Per-phase BATCH
defaults to the run's batch_size (higher-resolution phases typically
shrink it); every phase batch must keep the grad_accum microbatch
divisibility, and `validate_mesh` re-checks each phase against the live
mesh (data-axis granule, spatial height divisibility) once devices are
known.

This module is import-light (no jax): config.py validates the spec at
dataclass construction, and the analyzers load it on every pass.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Phase:
    """One row of the phase table."""

    resolution: int
    steps: Optional[int]    # None = "*" (runs to the end of the run)
    batch_size: int


@dataclasses.dataclass(frozen=True)
class ProgressiveSchedule:
    """The parsed, validated phase table plus the fade knob."""

    phases: Tuple[Phase, ...]
    fade_steps: int = 0

    # -- phase arithmetic ---------------------------------------------------
    #
    # All step math is in COMPLETED-step space (the trainer's step_num):
    # phase i covers dispatches of steps [start_i, start_i + steps_i). A
    # state saved at exactly a boundary step S was produced by the OLD
    # phase (the switch happens before the first new-phase dispatch), so
    # `index_for_state` and `index_for_dispatch` differ at boundaries —
    # the restore template needs the former, the switch check the latter.

    def starts(self, total_steps: int) -> List[int]:
        """Start step of each phase, clipped to the run length — phases
        whose start lands at/after total_steps never run."""
        out, at = [], 0
        for ph in self.phases:
            out.append(at)
            at += ph.steps if ph.steps is not None else max(
                0, total_steps - at)
        return out

    def index_for_dispatch(self, step: int, total_steps: int) -> int:
        """The phase whose program dispatches step number `step`."""
        starts = self.starts(total_steps)
        i = 0
        for j, s in enumerate(starts):
            if s <= step and s < max(total_steps, 1):
                i = j
        return i

    def index_for_state(self, step: int, total_steps: int) -> int:
        """The phase that PRODUCED a state at completed-step `step` — the
        restore-template phase (a boundary-step checkpoint carries the
        pre-switch tree; see the trainer's switch ordering)."""
        return self.index_for_dispatch(max(int(step) - 1, 0), total_steps)

    def alpha_at(self, step: int, total_steps: int) -> float:
        """The fade-in alpha for dispatching step `step`: a linear ramp
        over the first `fade_steps` steps of every phase after the first
        ((t+1)/fade_steps, capped at 1.0); 1.0 always for the first phase
        or with fading off."""
        if not self.fade_steps:
            return 1.0
        i = self.index_for_dispatch(step, total_steps)
        if i == 0:
            return 1.0
        t = step - self.starts(total_steps)[i]
        return min(1.0, (t + 1) / float(self.fade_steps))

    def config_for(self, cfg, index: int):
        """The phase's TrainConfig: the base config with the model rebuilt
        at the phase resolution and the phase batch size. Everything else
        (optimizer, loss, cadences, mesh) is shared across phases."""
        ph = self.phases[index]
        return dataclasses.replace(
            cfg,
            progressive="",  # the phase config is single-shape by definition
            progressive_fade_steps=0,
            batch_size=ph.batch_size,
            model=dataclasses.replace(cfg.model,
                                      output_size=ph.resolution))

    def validate_mesh(self, mesh_shape: dict, *, spatial: bool,
                      grad_accum: int = 1) -> None:
        """Granule/divisibility checks that need the LIVE mesh: every
        phase's batch (and microbatch) must divide over the data axis, and
        under a spatial mesh every phase resolution must divide over the
        height-sharding 'model' axis. Raises ValueError naming the phase."""
        data = int(mesh_shape.get("data", 1))
        model = int(mesh_shape.get("model", 1))
        for i, ph in enumerate(self.phases):
            if ph.batch_size % data:
                raise ValueError(
                    f"progressive phase {i} (r{ph.resolution}): batch "
                    f"{ph.batch_size} does not divide over the {data}-way "
                    "data axis")
            if (ph.batch_size // grad_accum) % data:
                raise ValueError(
                    f"progressive phase {i} (r{ph.resolution}): microbatch "
                    f"{ph.batch_size // grad_accum} (batch/grad_accum) does "
                    f"not divide over the {data}-way data axis")
            if spatial and ph.resolution % model:
                raise ValueError(
                    f"progressive phase {i}: resolution {ph.resolution} "
                    f"does not divide over the {model}-way spatial height "
                    "axis")


def parse_schedule(spec: str, *, model, batch_size: int, max_steps: int,
                   steps_per_call: int = 1, grad_accum: int = 1,
                   fade_steps: int = 0) -> ProgressiveSchedule:
    """Parse + validate a `--progressive` spec against the run config.

    `model` is the run's ModelConfig (the FINAL phase's architecture);
    raises ValueError with the offending phase named on any violation.
    """
    if not spec:
        raise ValueError("empty progressive spec")
    phases: List[Phase] = []
    items = [s.strip() for s in spec.split(",") if s.strip()]
    if not items:
        raise ValueError(f"progressive spec {spec!r} has no phases")
    for i, item in enumerate(items):
        parts = item.split(":")
        if len(parts) not in (2, 3):
            raise ValueError(
                f"progressive phase {i} ({item!r}): want RES:STEPS or "
                "RES:STEPS:BATCH")
        try:
            res = int(parts[0])
        except ValueError:
            raise ValueError(
                f"progressive phase {i} ({item!r}): resolution "
                f"{parts[0]!r} is not an integer") from None
        if parts[1] == "*":
            steps: Optional[int] = None
        else:
            try:
                steps = int(parts[1])
            except ValueError:
                raise ValueError(
                    f"progressive phase {i} ({item!r}): steps {parts[1]!r} "
                    "is not an integer or '*'") from None
            if steps <= 0:
                raise ValueError(
                    f"progressive phase {i} ({item!r}): steps must be > 0")
            if steps % steps_per_call:
                raise ValueError(
                    f"progressive phase {i} ({item!r}): steps {steps} must "
                    f"be a multiple of steps_per_call={steps_per_call} so "
                    "the switch lands on a dispatch boundary")
        if len(parts) == 3:
            try:
                batch = int(parts[2])
            except ValueError:
                raise ValueError(
                    f"progressive phase {i} ({item!r}): batch {parts[2]!r} "
                    "is not an integer") from None
        else:
            batch = batch_size
        if batch <= 0:
            raise ValueError(
                f"progressive phase {i} ({item!r}): batch must be > 0")
        if batch % grad_accum:
            raise ValueError(
                f"progressive phase {i} ({item!r}): batch {batch} must be "
                f"a multiple of grad_accum={grad_accum}")
        phases.append(Phase(resolution=res, steps=steps, batch_size=batch))

    for i, ph in enumerate(phases):
        k = math.log2(ph.resolution / model.base_size) \
            if ph.resolution > 0 else -1
        if ph.resolution <= 0 or k < 1 or k != int(k):
            raise ValueError(
                f"progressive phase {i}: resolution {ph.resolution} is not "
                f"a model-stack site (base_size={model.base_size} * 2^k, "
                "k >= 1)")
        if i and ph.resolution <= phases[i - 1].resolution:
            raise ValueError(
                f"progressive phase {i}: resolutions must be strictly "
                f"ascending ({phases[i - 1].resolution} -> {ph.resolution})")
        if ph.steps is None and i != len(phases) - 1:
            raise ValueError(
                f"progressive phase {i}: '*' steps are only valid on the "
                "last phase")
    if phases[-1].steps is not None:
        raise ValueError(
            "the last progressive phase must use '*' steps (the run length "
            "is max_steps' business; a fixed final count would silently "
            "truncate or extend it)")
    if phases[-1].resolution != model.output_size:
        raise ValueError(
            f"the last progressive phase's resolution "
            f"({phases[-1].resolution}) must equal model.output_size "
            f"({model.output_size}) — the base config describes the final "
            "model; earlier phases are its shallower variants")
    fixed = sum(ph.steps for ph in phases[:-1])
    if fixed >= max_steps:
        raise ValueError(
            f"progressive fixed phases cover {fixed} steps but max_steps is "
            f"{max_steps} — the final '*' phase would never run")
    if fade_steps < 0:
        raise ValueError(f"progressive_fade_steps must be >= 0, got "
                         f"{fade_steps}")
    if fade_steps:
        if steps_per_call != 1:
            raise ValueError(
                "progressive_fade_steps > 0 requires steps_per_call=1 (the "
                "fade blend is a per-step dispatch with a per-step alpha)")
        for i, ph in enumerate(phases[1:], start=1):
            if ph.steps is not None and fade_steps > ph.steps:
                raise ValueError(
                    f"progressive_fade_steps={fade_steps} exceeds phase "
                    f"{i}'s {ph.steps} steps — the fade would never "
                    "complete inside the phase")
    return ProgressiveSchedule(phases=tuple(phases), fade_steps=fade_steps)
