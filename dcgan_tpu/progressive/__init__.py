"""Progressive-resolution training plane (ISSUE 15, ROADMAP item 5).

Resolution as a scheduled, checkpointable training dimension:

- `schedule.py` — the declarative phase table
  (`--progressive "64:2000,128:2000,256:*"`), parsed + validated against
  the model stack, the dispatch granule, and the live mesh; optional
  linear fade-in alpha per phase.
- `phases.py` — per-phase `ParallelTrain` surfaces whose programs all
  join the PR 5 AOT warmup plan up front (`@r<res>` rows) and are
  PRIMED with one throwaway dispatch each, so a mid-run resolution
  switch dispatches only already-executed programs (zero compile
  requests after warmup); cross-phase state carry (new leaves init
  fresh, carried leaves transfer, elastic reshard path when specs move)
  and the checkpoint sidecar's phase tag.
- `rebucket.py` — mid-run data-pipeline re-bucketing: loaders close and
  re-open at the new decode resolution behind the services drain
  barrier, with the process-global quarantine tally carried across.

The trainer's phase-boundary step (train/trainer.py) composes these with
the PR 5 swap mechanics: drain services, drain the G/D pipeline, swap
surface + loaders, refresh the rollback snapshot, re-arm the watchdog's
`compiled_ks`. DESIGN.md §6j documents the phase model and the switch
sequence.
"""

from dcgan_tpu.progressive.phases import PhaseRuntime, carry_path, carry_state
from dcgan_tpu.progressive.rebucket import Rebucketer, phase_data_cfg
from dcgan_tpu.progressive.schedule import (
    Phase,
    ProgressiveSchedule,
    parse_schedule,
)

__all__ = [
    "Phase",
    "PhaseRuntime",
    "ProgressiveSchedule",
    "Rebucketer",
    "carry_path",
    "carry_state",
    "parse_schedule",
    "phase_data_cfg",
]
