"""Mid-run data-pipeline re-bucketing (ISSUE 15).

A phase switch changes the decode resolution (and possibly the batch),
which every loader implementation bakes into its threads and buffers at
construction — so the rebucket move is close-and-reopen, never mutate:
the trainer drains its services queue (the drain barrier — queued
telemetry referencing old-phase arrays must land before their buffers
die), closes the old iterators (stopping the prefetcher/loader threads),
and opens fresh ones from the phase config. All three loader
implementations (PythonLoader, tfrecord, native) come along for free
because re-opening goes through the same `_data_iterator` factory the
trainer booted with.

Quarantine continuity: the corrupt-record tally (data/quarantine.py) is
process-global BY DESIGN — it spans loader implementations and
re-opens — so a phase switch carries it verbatim; the trainer's
`corrupt_base` delta accounting is untouched and the budget
(`max_corrupt_records`) keeps bounding the whole RUN, not each phase.
`Rebucketer.reopen` records the tally at each switch so the invariant is
observable (and test-pinned).

Real-data runs: the on-disk record size must match each phase's decode
resolution, so `--data_dir`/`--sample_image_dir` may embed a literal
`{res}` that resolves per phase (`train_{res}` -> train_64, train_128,
...; `python -m dcgan_tpu.data.prepare` once per resolution). Dirs
without the placeholder are used as-is (the manifest check will reject a
size mismatch loudly). Synthetic runs need nothing.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, Optional, Tuple

RES_PLACEHOLDER = "{res}"


def phase_data_cfg(phase_cfg):
    """The phase config with `{res}` data-dir placeholders resolved to
    the phase resolution."""
    res = str(phase_cfg.model.output_size)
    repl = {}
    if RES_PLACEHOLDER in phase_cfg.data_dir:
        repl["data_dir"] = phase_cfg.data_dir.replace(RES_PLACEHOLDER, res)
    if RES_PLACEHOLDER in phase_cfg.sample_image_dir:
        repl["sample_image_dir"] = phase_cfg.sample_image_dir.replace(
            RES_PLACEHOLDER, res)
    return dataclasses.replace(phase_cfg, **repl) if repl else phase_cfg


def close_iterators(*iterators) -> None:
    """Stop loader/prefetcher threads; None and close-less iterators are
    fine. Propagates close errors — a loader that cannot release its
    threads is a real leak, not a cleanup nit."""
    for it in iterators:
        if it is not None and hasattr(it, "close"):
            it.close()


class Rebucketer:
    """Owns the progressive run's (train, sample) iterators across phase
    switches. `open_fn(phase_cfg) -> (data, sample_data)` is the
    trainer's factory (its `_data_iterator`/`_sample_data_iterator`
    closures, pinned to the live mesh); the rebucketer adds the
    close-before-open ordering and the quarantine-carry bookkeeping."""

    def __init__(self, open_fn: Callable[[Any], Tuple[Iterator,
                                                      Optional[Iterator]]]):
        self._open = open_fn
        self.data: Optional[Iterator] = None
        self.sample_data: Optional[Iterator] = None
        self.reopens = 0
        self.last_tally: int = 0   # quarantine tally at the last (re)open

    def open(self, phase_cfg) -> Tuple[Iterator, Optional[Iterator]]:
        from dcgan_tpu.data import quarantine

        self.data, self.sample_data = self._open(phase_data_cfg(phase_cfg))
        self.last_tally = quarantine.count()
        return self.data, self.sample_data

    def reopen(self, phase_cfg) -> Tuple[Iterator, Optional[Iterator]]:
        """Close the old phase's loaders, open the new phase's. The
        process-global quarantine tally rides across untouched (recorded
        in `last_tally` so the carry is observable); the caller runs the
        services drain barrier BEFORE calling this."""
        from dcgan_tpu.data import quarantine

        before = quarantine.count()
        close_iterators(self.data, self.sample_data)
        self.data, self.sample_data = self._open(phase_data_cfg(phase_cfg))
        after = quarantine.count()
        assert after >= before, \
            "quarantine tally went backwards across a loader re-open"
        self.last_tally = after
        self.reopens += 1
        return self.data, self.sample_data

    def close(self) -> None:
        close_iterators(self.data, self.sample_data)
        self.data = self.sample_data = None
