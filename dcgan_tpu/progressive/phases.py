"""Per-phase training surfaces + cross-phase state carry (ISSUE 15).

`PhaseRuntime` owns the progressive run's compiled-surface table: one
`ParallelTrain` per schedule phase (built against the ONE shared mesh),
the per-phase AOT warmup plans whose rows join the trainer's plan under
`@r<resolution>` suffixes, the priming dispatches that make a mid-run
resolution switch dispatch only already-executed programs (zero compile
requests after warmup — the PR 9 serve-plane mechanism: an AOT-compiled
program's first __call__ still re-traces and, with host-fed args, builds
an input transfer program, so warmup runs ONE throwaway dispatch per
program per phase to absorb both), and the state carry that moves a live
train state across a model-surface change.

State carry rules (DESIGN.md §6j):

- Leaves are matched by PATH after a per-family rename, then guarded by
  SHAPE+DTYPE equality: a matched leaf with equal shape transfers, every
  other leaf keeps its fresh per-phase init.
- dcgan indexes generator stages from the TOP (deconv1 is the widest),
  so growing the stack by d stages renames old `deconv{i}` ->
  `deconv{i+d}` and `bn{i}` -> `bn{i+d}` (i >= 1) inside every
  gen-rooted subtree (params/bn/SN state, ema_gen, and the Adam moments
  that mirror them) — the whole old generator minus its z-side top
  (proj/bn0, which are new-at-this-phase) carries. The discriminator
  indexes from the INPUT, so its early convs carry under the identity
  map and only the new top conv + head init fresh.
- resnet/stylegan carry by plain name+shape matching (their per-stage
  trees don't index-shift the same way; whatever matches transfers).
- Carried leaves keep their device buffers when the old and new
  shardings are equivalent (the common case — one mesh, one rule table,
  same path+shape => same spec, so ZeRO-2/3 resident shards carry
  without movement); a spec change reshards through the elastic host
  path (`elastic/reshard.put_host_tree` per leaf), and any host-staged
  leaf forces a donation-safety rebase of the merged tree when the
  persistent compile cache is active (DESIGN §6d).
"""

from __future__ import annotations

import re
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from dcgan_tpu.progressive.schedule import ProgressiveSchedule

Pytree = Any

#: gen-rooted path prefixes whose stage names index-shift when the dcgan
#: stack grows (the Adam moments mirror params/gen under opt/gen/...)
_GEN_ROOTS = ("params/gen/", "bn/gen/", "ema_gen/", "opt/gen/")

_GEN_STAGE_RE = re.compile(r"^(deconv|bn|sn_deconv)(\d+)$")


def _rename_gen_segment(seg: str, shift: int) -> Optional[str]:
    """dcgan generator stage rename old->new for a stack grown by `shift`
    stages; None = the old leaf has no home in the new tree (proj/bn0 —
    the z-side top is new at each phase)."""
    m = _GEN_STAGE_RE.match(seg)
    if m is None:
        return seg
    kind, idx = m.group(1), int(m.group(2))
    if kind == "bn" and idx == 0:
        return None  # the top BN is new-at-this-phase (top_ch changed)
    return f"{kind}{idx + shift}"


def carry_path(path: str, *, arch: str, shift: int) -> Optional[str]:
    """Where an OLD-phase leaf lands in the NEW tree (path string, "/"
    separated — elastic/rules.path_str form), or None when it has no
    home. Identity for non-dcgan families and for shift == 0."""
    if arch != "dcgan" or shift == 0 \
            or not path.startswith(_GEN_ROOTS):
        return path
    segs = path.split("/")
    out = []
    for seg in segs:
        if seg == "proj" and path.startswith(_GEN_ROOTS):
            return None  # z-side projection: shape follows top_ch, new
        new = _rename_gen_segment(seg, shift)
        if new is None:
            return None
        out.append(new)
    return "/".join(out)


def carry_state(old_state: Pytree, new_state: Pytree, *, arch: str,
                shift: int) -> Tuple[Pytree, int, bool]:
    """Merge an old phase's live state into a fresh new-phase init.

    Returns (merged tree, carried-leaf count, host_staged) — host_staged
    is True when any carried leaf crossed shardings through the elastic
    host path (the caller rebases the merged tree onto XLA buffers when
    the persistent cache is active, DESIGN §6d).
    """
    import jax

    from dcgan_tpu.elastic.rules import path_str

    old_by_path: Dict[str, Any] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(old_state)[0]:
        new_home = carry_path(path_str(path), arch=arch, shift=shift)
        if new_home is not None:
            old_by_path[new_home] = leaf

    staged = False
    carried = 0

    def merge(path, fresh):
        nonlocal staged, carried
        old = old_by_path.get(path_str(path))
        if old is None:
            return fresh
        if tuple(getattr(old, "shape", ())) \
                != tuple(getattr(fresh, "shape", ())) \
                or getattr(old, "dtype", None) != getattr(fresh, "dtype",
                                                          None):
            return fresh  # shape guard: a renamed leaf that no longer fits
        carried += 1
        old_sh = getattr(old, "sharding", None)
        new_sh = getattr(fresh, "sharding", None)
        if old_sh is None or new_sh is None \
                or old_sh.is_equivalent_to(new_sh, len(old.shape)):
            return old  # same placement: the live buffers carry verbatim
        # spec changed across phases (rare — one mesh, one rule table):
        # reshard through the elastic host path, per-shard upload
        from dcgan_tpu.elastic.reshard import put_host_tree

        staged = True
        return put_host_tree(jax.device_get(old), fresh)

    merged = jax.tree_util.tree_map_with_path(merge, new_state)
    return merged, carried, staged


class PhaseRuntime:
    """The trainer's progressive-run companion: current phase index, the
    per-phase compiled surfaces, warmup/priming, and the switch's state
    carry. Built once after the mesh; `start()` picks the resume phase
    from the latest checkpoint step."""

    def __init__(self, cfg, mesh, schedule: ProgressiveSchedule,
                 total_steps: int,
                 make_pt: Optional[Callable] = None):
        self.base_cfg = cfg
        self.mesh = mesh
        self.schedule = schedule
        self.total_steps = int(total_steps)
        if make_pt is None:
            from dcgan_tpu.parallel import make_parallel_train

            make_pt = make_parallel_train
        self._make_pt = make_pt
        schedule.validate_mesh(dict(mesh.shape), spatial=cfg.mesh.spatial,
                               grad_accum=cfg.grad_accum)
        self.starts = schedule.starts(self.total_steps)
        # phases that actually run under this run length
        self.n_phases = sum(1 for s in self.starts
                            if s < self.total_steps) or 1
        self.index: int = 0
        self._surfaces: Dict[int, Tuple[Any, Any]] = {}  # i -> (cfg_i, pt_i)
        self._fade: Dict[int, Any] = {}
        self.primed = False
        self.last_switch_ms: float = 0.0
        self.last_carried: int = 0

    # -- per-phase surfaces --------------------------------------------------

    def phase_cfg(self, i: int):
        return self.surface(i)[0]

    def surface(self, i: int) -> Tuple[Any, Any]:
        """(phase TrainConfig, ParallelTrain) for phase i, built lazily
        and kept — the switch must swap to an already-built surface."""
        if i not in self._surfaces:
            cfg_i = self.schedule.config_for(self.base_cfg, i)
            self._surfaces[i] = (cfg_i, self._make_pt(cfg_i, self.mesh))
        return self._surfaces[i]

    @property
    def cfg(self):
        return self.surface(self.index)[0]

    @property
    def pt(self):
        return self.surface(self.index)[1]

    @property
    def resolution(self) -> int:
        return self.schedule.phases[self.index].resolution

    def tag(self) -> Dict[str, int]:
        """The sidecar phase tag (elastic/sidecar.py payload extension):
        which phase's tree a checkpoint carries."""
        return {"phase": int(self.index), "resolution": int(self.resolution)}

    # -- lifecycle -----------------------------------------------------------

    def start(self, latest_step: Optional[int]) -> int:
        """Pick the starting phase: 0 for a fresh run, else the phase that
        PRODUCED the latest checkpoint (its tree is the restore
        template; a boundary-step checkpoint resumes pre-switch and the
        loop switches immediately after restore)."""
        self.index = 0 if latest_step is None else min(
            self.schedule.index_for_state(int(latest_step),
                                          self.total_steps),
            self.n_phases - 1)
        self.surface(self.index)
        return self.index

    def check_resume_tag(self, payload_tag: Optional[dict],
                         latest_step: int) -> None:
        """Cross-check the checkpoint sidecar's phase tag against the
        schedule-derived resume phase — a schedule edited between runs
        must fail loudly here, not as an Orbax tree mismatch."""
        if not payload_tag:
            return
        saved = int(payload_tag.get("phase", -1))
        saved_res = int(payload_tag.get("resolution", -1))
        if saved != self.index or saved_res != self.resolution:
            raise ValueError(
                f"checkpoint at step {latest_step} was saved in progressive "
                f"phase {saved} (r{saved_res}) but the current schedule "
                f"resolves that step to phase {self.index} "
                f"(r{self.resolution}) — the --progressive spec changed "
                "between runs; restore with the saving schedule or point at "
                "a fresh checkpoint_dir")

    def switch_due(self, step: int) -> bool:
        nxt = self.index + 1
        return nxt < self.n_phases and step >= self.starts[nxt]

    def advance(self, state: Pytree) -> Pytree:
        """The switch's state half: build/enter the next phase's surface
        and carry the live state across the model-surface growth. New
        leaves init fresh from the phase seed; carried leaves transfer
        (elastic reshard path when their spec moved). Times itself into
        `last_switch_ms` (the data/loader half is the trainer's —
        rebucket.py — and adds its own time on top)."""
        import jax

        t0 = time.perf_counter()
        old_cfg = self.cfg
        self.index += 1
        cfg_i, pt_i = self.surface(self.index)
        shift = cfg_i.model.num_up_layers - old_cfg.model.num_up_layers
        fresh = pt_i.init(jax.random.key(
            self.base_cfg.seed + 1000 + self.index))
        merged, carried, staged = carry_state(
            state, fresh, arch=cfg_i.model.arch, shift=shift)
        if staged:
            from dcgan_tpu.utils.checkpoint import persistent_cache_active

            if persistent_cache_active():
                # host-staged leaves must not be donated into deserialized
                # executables (DESIGN §6d) — one identity pass rebases the
                # whole merged tree onto XLA-owned buffers
                from dcgan_tpu.train.rollback import device_copy

                merged = device_copy(merged)
        self.last_carried = carried
        self.last_switch_ms = (time.perf_counter() - t0) * 1e3
        return merged

    # -- fade ----------------------------------------------------------------

    def alpha(self, step: int) -> float:
        return self.schedule.alpha_at(step, self.total_steps)

    def fade_program(self, i: Optional[int] = None):
        """The phase's jitted image-space fade blend
        `(images, alpha) -> images`: alpha * x + (1 - alpha) *
        up(down(x)) — D's real distribution ramps from
        previous-resolution content to full detail over the fade window
        (alpha is a traced f32 scalar, one compile per phase). Only built
        when the schedule fades."""
        i = self.index if i is None else i
        if i not in self._fade:
            self._fade[i] = _make_fade(self.surface(i)[0], self.mesh)
        return self._fade[i]

    def fade_images(self, images, step: int):
        """Apply the fade blend when inside a fade window; identity (no
        dispatch) otherwise."""
        a = self.alpha(step)
        if a >= 1.0:
            return images
        import numpy as np

        return self.fade_program()(images, np.float32(a))

    # -- scalar-row extras (event keys gated "progressive schedule") ---------

    def scalar_extras(self, step: int) -> Dict[str, float]:
        if len(self.schedule.phases) == 1:
            # a single-phase schedule IS the existing trainer (the parity
            # A/B pins its JSONL byte-identical) — no progressive keys
            return {}
        out = {
            "progressive/phase": float(self.index),
            "progressive/resolution": float(self.resolution),
        }
        if self.schedule.fade_steps:
            a = self.alpha(max(step - 1, 0))
            if a < 1.0:
                out["progressive/alpha"] = float(a)
        return out

    # -- warmup + priming ----------------------------------------------------

    def build_warmup_plan(self, state: Pytree, *, sample_z=None,
                          sample_labels=None
                          ) -> List[Tuple[str, Callable, tuple]]:
        """Every program every phase can dispatch, as warmup-plan rows
        suffixed `@r<resolution>` (the current phase's rows keep their
        plain names so the existing per-program perf/compile_ms keys and
        coverage pins read unchanged). `state` is the CURRENT phase's
        live/template state; other phases lower against eval_shape
        templates (warmup.state_example — nothing allocates)."""
        import jax
        import jax.numpy as jnp

        from dcgan_tpu.train import warmup

        plan: List[Tuple[str, Callable, tuple]] = []
        for i in range(self.n_phases):
            cfg_i, pt_i = self.surface(i)
            st = state if i == self.index else warmup.state_example(pt_i)
            eval_z = jnp.resize(
                jnp.zeros((1, cfg_i.model.z_dim), jnp.float32),
                (cfg_i.batch_size, cfg_i.model.z_dim)) \
                if cfg_i.sample_every_steps else None
            rows, _bk = warmup.build_warmup_plan(
                cfg_i, pt_i, st,
                sample_z=sample_z if cfg_i.sample_every_steps else None,
                sample_labels=sample_labels, eval_z=eval_z,
                make_backoff_pt=None)
            rows = [("init", pt_i.programs["init"],
                     (jax.random.key(0),))] + list(rows)
            if self.schedule.fade_steps and i > 0:
                img_sds = _image_sds(cfg_i, self.mesh)
                rows.append(("fade", self.fade_program(i),
                             (img_sds, jnp.float32(0.5))))
            suffix = "" if i == self.index \
                else f"@r{self.schedule.phases[i].resolution}"
            plan += [(n + suffix, f, a) for n, f, a in rows]
        return plan

    def prime(self, *, sample_z=None, sample_labels=None) -> Dict[str, float]:
        """One throwaway dispatch per program per phase, making
        zero-compile-requests-after-warmup LITERAL (the PR 9 serve-plane
        mechanism): the jit dispatch caches populate here — with the
        persistent cache active each priming compile deserializes the
        entry `aot_compile` just wrote — so a later phase switch (and the
        current phase's first live steps) re-trace nothing. Returns
        {phase label: prime_ms}. Dispatch-thread only (mesh programs)."""
        import jax
        import numpy as np

        from dcgan_tpu.train.rollback import device_copy

        timings: Dict[str, float] = {}
        for i in range(self.n_phases):
            t0 = time.perf_counter()
            cfg_i, pt_i = self.surface(i)
            key = jax.random.key(0)
            st = pt_i.init(jax.random.fold_in(key, 7))
            imgs = _zero_images(cfg_i, self.mesh)
            lbls = ()
            if cfg_i.model.num_classes:
                lbls = (_zero_labels(cfg_i, self.mesh),)
            if cfg_i.pipeline_gd:
                fakes = pt_i.gen_fakes(st, key)
                st, m = pt_i.d_update(st, imgs, fakes, key)
                st, _fakes, m = pt_i.g_update(st, key)
            else:
                st, m = pt_i.step(st, imgs, key, *lbls)
            k = cfg_i.steps_per_call
            if k > 1:
                import jax.numpy as jnp

                keys = jax.vmap(jax.random.fold_in, (None, 0))(
                    key, jnp.arange(k))
                imgs_k = jnp.broadcast_to(imgs, (k,) + imgs.shape)
                lbls_k = tuple(jnp.broadcast_to(x, (k,) + x.shape)
                               for x in lbls)
                st, m = pt_i.multi_step(st, imgs_k, keys, *lbls_k)
            if cfg_i.sample_every_steps and sample_z is not None:
                s_lbls = (sample_labels,) if sample_labels is not None else ()
                pt_i.sample(st, sample_z, *s_lbls)
                import jax.numpy as jnp

                eval_z = jnp.resize(sample_z,
                                    (cfg_i.batch_size, cfg_i.model.z_dim))
                pt_i.eval_losses(st, imgs, eval_z, *lbls)
            if cfg_i.activation_summary_steps:
                pt_i.summarize(st, imgs, key, *lbls)
            # the identity-copy signatures the run dispatches later: the
            # switch's donation rebase (full state) and the single-process
            # histogram snapshot (params subtree)
            st = device_copy(st)
            device_copy(st["params"])
            if self.schedule.fade_steps and i > 0:
                self.fade_program(i)(imgs, np.float32(0.5))
            # sync on whatever the last dispatch returned (the pipelined
            # branch's final metrics carry g_loss only)
            jax.block_until_ready(jax.tree_util.tree_leaves(m))
            del st
            timings[f"phase{i}@r{self.schedule.phases[i].resolution}"] = \
                (time.perf_counter() - t0) * 1e3
        self.primed = True
        return timings


def _image_sds(cfg, mesh):
    import jax
    import jax.numpy as jnp

    from dcgan_tpu.parallel import batch_sharding

    size = cfg.model.output_size
    return jax.ShapeDtypeStruct(
        (cfg.batch_size, size, size, cfg.model.c_dim), jnp.float32,
        sharding=batch_sharding(mesh, 4, spatial=cfg.mesh.spatial))


def _zero_images(cfg, mesh):
    """A concrete all-zero image batch with the phase's live sharding,
    assembled per-process (multi-host safe: each device uploads only its
    shard)."""
    import jax
    import numpy as np

    sds = _image_sds(cfg, mesh)
    return jax.make_array_from_callback(
        sds.shape, sds.sharding,
        lambda idx: np.zeros([len(range(*s.indices(sds.shape[d])))
                              for d, s in enumerate(idx)], np.float32))


def _zero_labels(cfg, mesh):
    import jax
    import numpy as np

    from dcgan_tpu.parallel import batch_sharding

    sh = batch_sharding(mesh, 1)
    return jax.make_array_from_callback(
        (cfg.batch_size,), sh,
        lambda idx: np.zeros(
            len(range(*idx[0].indices(cfg.batch_size))), np.int32))


def _make_fade(cfg, mesh):
    """The phase's fade-blend program: images -> alpha * images +
    (1 - alpha) * upsample(downsample(images)). Down is a 2x2 mean pool,
    up a nearest repeat — previous-resolution content at the phase's
    size. alpha is a traced f32 scalar argument (one compile covers the
    whole ramp). No donation (not in DONATED_PROGRAMS by design)."""
    import jax
    import jax.numpy as jnp

    from dcgan_tpu.parallel import batch_sharding
    from dcgan_tpu.parallel.sharding import replicated

    img_sh = batch_sharding(mesh, 4, spatial=cfg.mesh.spatial)

    def fade(images, alpha):
        b, h, w, c = images.shape
        low = images.reshape(b, h // 2, 2, w // 2, 2, c).mean(axis=(2, 4))
        up = jnp.repeat(jnp.repeat(low, 2, axis=1), 2, axis=2)
        return alpha * images + (1.0 - alpha) * up

    return jax.jit(fade, in_shardings=(img_sh, replicated(mesh)),
                   out_shardings=img_sh)
