"""Self-attention over the spatial sequence, with two sequence-parallel
execution strategies (ring and all-to-all/Ulysses).

The reference has no attention anywhere — it is a pure-conv DCGAN whose
largest spatial extent is 64x64 (distriubted_model.py:7,83-128), and SURVEY.md
§2.5 records sequence/context parallelism as structurally absent. This module
is the framework's first-class long-context machinery anyway: images flatten
to a sequence of H*W spatial positions, a SAGAN-style self-attention block
(Zhang et al. 2018, arXiv:1805.08318, optionally multi-head) attends over
that sequence, and when the sequence is sharded over a mesh axis the
attention runs in one of two explicit-collective forms:

- **ring** (`ring_attention`, arXiv:2310.01889): each device keeps its query
  block resident and rotates key/value blocks around the axis with
  `lax.ppermute`, folding each incoming block into a numerically stable
  online softmax. n-1 neighbor hops on ICI; peak memory O(S_local^2); no
  device ever materializes the full sequence; any head count.
- **ulysses** (`ulysses_attention`, arXiv:2309.14509): one `lax.all_to_all`
  trades sequence sharding for head sharding, each device runs ordinary (or
  flash) attention over the FULL sequence for its share of heads, a second
  all_to_all trades back. Two collectives total; needs num_heads divisible
  by the axis size; per-device memory is bounded by the flash path, not the
  strategy.

Design notes:
- `attn_apply` is identity at initialization: the residual gate `gamma` starts
  at 0 (the SAGAN recipe), so inserting the block into a DCGAN stack does not
  perturb the reference dynamics until training moves gamma.
- Projections are 1x1 convs expressed as channel matmuls: query/key to C/8,
  value to C/2, output back to C — the SAGAN channel plan. Heads are an
  apply-time split of the same projections (checkpoint-compatible).
- Logits are scaled by 1/sqrt(d_head) (standard scaled dot-product; SAGAN's
  paper omits the scale — documented divergence, it only re-scales what
  gamma=0 already gates) and accumulated in float32 regardless of compute
  dtype.
- Both strategies are exact: equivalence against dense attention (and each
  other) is asserted to f32 tolerance in tests/test_attention.py on an
  8-virtual-device mesh, gradients included (ppermute, all_to_all, and the
  scan recurrence are differentiable as-is).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dcgan_tpu.ops.layers import linear_apply, linear_init
from dcgan_tpu.utils.backend import shard_map

Pytree = dict

# Measurement generation of the DENSE attention path (full_attention and
# the ring fold below) — the counterpart of pallas_attention.ATTN_GEN for
# configs that never execute the flash kernels. bench.py stamps whichever
# generation matches the config's execution form, so a flash-only change
# (tile retune, block layout) never retires the capture history of dense
# configs whose code is byte-identical. Gen 2 = the shared bf16-operand /
# f32-accumulation precision policy (it changed BOTH forms).
DENSE_ATTN_GEN = 2


def attn_init(key, ch: int, *, dtype=jnp.float32) -> Pytree:
    """Parameters for one self-attention block over `ch`-channel feature maps.

    SAGAN channel plan: query/key project to ch//8, value to ch//2, output
    back to ch; `gamma` (the residual gate) starts at 0 so the block is the
    identity at init.
    """
    if ch < 8:
        raise ValueError(f"attention needs >= 8 channels, got {ch}")
    kq, kk, kv, ko = jax.random.split(key, 4)
    return {
        "query": linear_init(kq, ch, ch // 8, dtype=dtype),
        "key": linear_init(kk, ch, ch // 8, dtype=dtype),
        "value": linear_init(kv, ch, ch // 2, dtype=dtype),
        "out": linear_init(ko, ch // 2, ch, dtype=dtype),
        "gamma": jnp.zeros((), dtype),
    }


def full_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   *, scale: float) -> jax.Array:
    """softmax(q k^T * scale) v over the whole sequence. [B,S,d] each.

    Precision policy (all execution forms share it): matmul OPERANDS keep
    their input dtype — bf16 rides the MXU fast path instead of being
    upcast into 4x-slower f32 matmuls — while scores/softmax/accumulation
    are float32 via `preferred_element_type`. The probability matrix is
    cast back to the value dtype for the PV matmul (the flash-attention
    recipe, arXiv:2205.14135 §3.1). float32 inputs take the exact float32
    path unchanged — the policy is dtype-gated, not a global downcast.
    """
    s = jnp.einsum("bqd,bkd->bqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkv->bqv", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str, n_shards: int, scale: float) -> jax.Array:
    """Exact attention over a sequence sharded along `axis_name`.

    Per-device blocks q,k,v: [B, S_local, d]. The device keeps q resident and
    receives each of the `n_shards` k/v blocks in turn over a `ppermute` ring,
    maintaining the online-softmax statistics (running max m, normalizer l,
    unnormalized accumulator acc) so the result equals full softmax attention
    over the global sequence (arXiv:2310.01889's blockwise recurrence).

    Communication: exactly n_shards-1 neighbor exchanges of the local k/v
    blocks — O(S_local * d) per hop on ICI; nothing ever all-gathers. The
    resident block folds before the scan, so no hop's result is discarded.
    """
    if n_shards == 1:
        return full_attention(q, k, v, scale=scale)
    fwd = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def fold(k_blk, v_blk, m, l, acc):
        # same precision policy as full_attention: operands in input dtype,
        # scores/stats/accumulator f32 via preferred_element_type
        s = jnp.einsum("bqd,bkd->bqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # exp(-inf - -inf) cannot occur: m_new is finite from the first fold
        # on, and there m = -inf only on the correction side
        # (corr = exp(-inf - finite) = 0, which correctly discards the empty
        # accumulator).
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bqk,bkv->bqv", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    # Build the accumulators out of q/v arithmetic (not jnp.zeros) so they
    # inherit the operands' device-varying axes — the scan carry then
    # type-checks under shard_map's VMA tracking over ANY enclosing mesh
    # (the ring axis alone, or ring + a batch axis).
    zero_q = q[..., 0].astype(jnp.float32) * 0.0    # [B, S]
    m, l, acc = fold(k, v, zero_q - jnp.inf, zero_q,
                     zero_q[..., None] * v[:, :1, :].astype(jnp.float32))

    def body(carry, _):
        k_blk, v_blk, m, l, acc = carry
        k_blk = lax.ppermute(k_blk, axis_name, perm=fwd)
        v_blk = lax.ppermute(v_blk, axis_name, perm=fwd)
        m, l, acc = fold(k_blk, v_blk, m, l, acc)
        return (k_blk, v_blk, m, l, acc), None

    (_, _, _, l, acc), _ = lax.scan(
        body, (k, v, m, l, acc), None, length=n_shards - 1)
    return acc / l[..., None]


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis_name: str, n_shards: int, num_heads: int,
                      scale: float, use_pallas: bool = False) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses, arXiv:2309.14509).

    Per-device blocks q,k,v: [B, S_local, h*d] sharded on the sequence. One
    `all_to_all` re-shards from sequence-split to head-split — each device
    then holds the FULL sequence for h/n_shards heads and runs ordinary
    attention locally — and a second all_to_all restores sequence sharding.
    Two collectives total, each moving the activations once, vs the ring's
    n-1 k/v hops: better when heads divide nicely and the fabric does fast
    all-to-alls; the ring wins when h < n or per-hop overlap matters. Both
    are exact; tests pin them against dense attention and each other.
    """
    if num_heads % n_shards:
        raise ValueError(
            f"ulysses needs num_heads ({num_heads}) divisible by the "
            f"sequence-parallel axis ({n_shards}); use the ring strategy "
            "or adjust attn_heads")
    B, S_loc, _ = q.shape

    def to_heads(t):
        # [B, S_loc, h, d] --all_to_all--> [B, S_loc*n, h/n, d]
        t = t.reshape(B, S_loc, num_heads, t.shape[-1] // num_heads)
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    S = S_loc * n_shards
    h_loc = num_heads // n_shards

    def fold(t):  # heads into batch for the local attention
        return t.transpose(0, 2, 1, 3).reshape(B * h_loc, S, t.shape[-1])

    if use_pallas:
        # local attention over the full sequence is exactly the regime the
        # flash kernels exist for (no [S, S] score matrix per device)
        from dcgan_tpu.ops.pallas_attention import flash_attention

        out = flash_attention(fold(qh), fold(kh), fold(vh), scale)
    else:
        out = full_attention(fold(qh), fold(kh), fold(vh), scale=scale)
    # downcast BEFORE the return collective: the f32 accumulation is local,
    # and shipping f32 under a bf16 compute dtype would double the bytes of
    # one of the strategy's two activation moves
    out = out.astype(v.dtype)
    out = out.reshape(B, h_loc, S, -1).transpose(0, 2, 1, 3)
    # [B, S, h/n, dv] --all_to_all--> [B, S_loc, h, dv], heads re-merged
    out = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                         tiled=True)
    return out.reshape(B, S_loc, -1)


def _project(params: Pytree, x: jax.Array, cdt) -> Tuple[jax.Array, ...]:
    q = linear_apply(params["query"], x, compute_dtype=cdt)
    k = linear_apply(params["key"], x, compute_dtype=cdt)
    v = linear_apply(params["value"], x, compute_dtype=cdt)
    return q, k, v


def attn_apply(params: Pytree, x: jax.Array, *, compute_dtype=None,
               num_heads: int = 1, seq_mesh=None, seq_axis: str = "model",
               batch_axis: str = "data", seq_strategy: str = "ring",
               use_pallas: bool = False, pallas_mesh=None) -> jax.Array:
    """x [B,H,W,C] -> x + gamma * attention(x) (same shape/dtype).

    pallas_mesh: a pure data-parallel Mesh the CALLER's jit partitions
    over. pallas_call is opaque to the GSPMD partitioner, so on such a
    mesh the flash path runs per data-shard inside a nested shard_map
    (the ops/norm.py::_pallas_shard_moments pattern) — attention is
    batch-local, so the wrapper needs no collectives. Ignored unless
    use_pallas is set and no sequence mesh applies.

    num_heads > 1 splits the existing query/key/value projections into heads
    (folded into the batch dim around the attention proper, so every
    execution form below — dense, flash, ring — is head-agnostic). Head
    count is an apply-time knob: parameter shapes do not change, so the same
    checkpoint serves any divisor head count.

    seq_mesh=None: attention over the full flattened H*W sequence (under a
    data-parallel jit the batch dim shards and nothing else changes).
    use_pallas=True routes this dense path through the flash-attention Pallas
    kernels (ops/pallas_attention.py) — O(S) HBM traffic, no [S, S] score
    matrix ever materialized.

    seq_mesh=<Mesh>: sequence-parallel execution — the flattened sequence is
    sharded over `seq_axis` (the mesh layout MeshConfig.spatial produces:
    batch over "data", image height over "model") and attention runs as an
    explicit `shard_map` nested inside the caller's jit. The surrounding
    convs stay under the GSPMD partitioner (halo exchanges); only the
    attention — whose all-to-all token mixing the partitioner would
    otherwise lower to a full k/v all-gather — is written by hand, in one of
    two strategies (`seq_strategy`):

    - "ring": ppermute k/v around the axis with an online-softmax fold
      (`ring_attention`) — any head count, n-1 neighbor hops.
    - "ulysses": one all_to_all to head sharding, local full attention, one
      all_to_all back (`ulysses_attention`) — needs num_heads divisible by
      the axis size.
    """
    B, H, W, C = x.shape
    cdt = compute_dtype
    seq = x.reshape(B, H * W, C)
    q, k, v = _project(params, seq, cdt)
    if num_heads > 1 and (q.shape[-1] % num_heads
                          or v.shape[-1] % num_heads):
        raise ValueError(
            f"num_heads={num_heads} does not divide the projection dims "
            f"(qk {q.shape[-1]}, v {v.shape[-1]})")
    scale = 1.0 / ((q.shape[-1] // num_heads) ** 0.5)

    seq_parallel = seq_mesh is not None and seq_mesh.shape[seq_axis] > 1
    if seq_parallel:
        n = seq_mesh.shape[seq_axis]
        if (H * W) % n:
            raise ValueError(
                f"sequence {H}x{W} does not shard over {n} devices")
        if seq_strategy not in ("ring", "ulysses"):
            raise ValueError(f"unknown seq_strategy {seq_strategy!r}")
        spec = P(batch_axis, seq_axis, None)

    if seq_parallel and seq_strategy == "ulysses":
        # heads stay unfolded: the all_to_all itself is the head split.
        # check_vma only without pallas: pallas_call outputs carry no vma
        # annotations (same constraint as ops/norm.py / shard_map_backend)
        f = shard_map(
            functools.partial(ulysses_attention, axis_name=seq_axis,
                              n_shards=n, num_heads=num_heads, scale=scale,
                              use_pallas=use_pallas),
            mesh=seq_mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check=not use_pallas)
        out = f(q, k, v)
    else:
        if num_heads > 1:
            q, k, v = (_split_heads(t, num_heads) for t in (q, k, v))
        if seq_parallel:
            if use_pallas:
                # ring x flash: the per-hop fold runs the flash kernels, so
                # no device ever materializes even its LOCAL
                # [S_local, S_local] score block — the composition for
                # sequences whose shards are themselves long
                # (ops/pallas_attention.py::ring_flash_attention)
                from dcgan_tpu.ops.pallas_attention import (
                    ring_flash_attention,
                )

                ring_fn = functools.partial(
                    ring_flash_attention, scale=scale, axis_name=seq_axis,
                    n_shards=n)
            else:
                ring_fn = functools.partial(
                    ring_attention, axis_name=seq_axis, n_shards=n,
                    scale=scale)
            ring = shard_map(
                ring_fn, mesh=seq_mesh, in_specs=(spec, spec, spec),
                out_specs=spec, check=not use_pallas)
            out = ring(q, k, v)
        elif use_pallas:
            from dcgan_tpu.ops.pallas_attention import flash_attention

            if pallas_mesh is not None and \
                    pallas_mesh.shape.get(batch_axis, 1) > 1:
                # data-parallel gspmd mesh: run the kernels per batch
                # shard inside a nested shard_map. Heads ride the batch
                # dim batch-major, so when B divides the data-axis size
                # each shard holds whole batches' head groups — but
                # correctness does NOT depend on that alignment: every
                # [b, head] row is independent in flash_attention, so a
                # split that lands mid-head-group is merely a layout, not
                # a semantics, difference. check_vma off: pallas outputs
                # carry no vma annotations (same constraint as
                # ops/norm.py).
                spec = P(batch_axis, None, None)
                out = shard_map(
                    # scale closed over: custom_vjp nondiff args must stay
                    # positional
                    lambda qs, ks, vs: flash_attention(qs, ks, vs, scale),
                    mesh=pallas_mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check=False)(q, k, v)
            else:
                out = flash_attention(q, k, v, scale)
        else:
            out = full_attention(q, k, v, scale=scale)
        if num_heads > 1:
            out = _merge_heads(out, num_heads)

    out = linear_apply(params["out"], out.astype(v.dtype), compute_dtype=cdt)
    gamma = params["gamma"].astype(x.dtype)
    return x + gamma * out.reshape(B, H, W, C).astype(x.dtype)


def _split_heads(t: jax.Array, h: int) -> jax.Array:
    """[B, S, h*d] -> [B*h, S, d] (heads ride the batch dim)."""
    B, S, D = t.shape
    return t.reshape(B, S, h, D // h).transpose(0, 2, 1, 3) \
        .reshape(B * h, S, D // h)


def _merge_heads(t: jax.Array, h: int) -> jax.Array:
    """[B*h, S, d] -> [B, S, h*d]."""
    Bh, S, d = t.shape
    return t.reshape(Bh // h, h, S, d).transpose(0, 2, 1, 3) \
        .reshape(Bh // h, S, h * d)
