"""Fused Pallas conv/deconv building blocks for the G/D stacks (ISSUE 17).

ops/pallas_kernels.py fuses the HBM-bound tail AROUND BatchNorm (moments +
normalize/act epilogue) but leaves the conv itself with XLA, so under
`use_pallas` each stage still writes its conv output to HBM once for the
moments pass and once more for the epilogue. These kernels pull the GEMM
into the same pass: each D stage (`conv ⊕ bias ⊕ BN-moments`, then the
shared `scale_shift_act` epilogue) and G stage (`deconv ⊕ bias ⊕ ...`)
becomes Pallas end to end, so a stage's activation tensor crosses HBM once
per direction — the program-interior win PR 6's trace digest located
(14.25 ms compute vs 43.6 ms idle) and ParaGAN (arXiv:2411.03999) frames.

Formulation: im2col. Patch extraction stays with XLA
(`lax.conv_general_dilated_patches` — differentiable, so JAX transposes it
into the dx scatter for free), producing [M, Cin*kh*kw] rows whose GEMM
against the [Cin*kh*kw, Cout] reshaped kernel IS the conv; a transposed
conv is the identical GEMM over `lhs_dilation`-expanded patches (verified
bit-exact against `lax.conv_transpose` — the JAX default does NOT flip the
kernel taps, tests/test_pallas_fused.py). The Pallas kernel then fuses
GEMM + bias + the per-channel moment reduction (train) or the whole
BN-affine + activation epilogue (inference, stats known) into one VMEM-
resident pass, accumulating in float32 over a (row-block, k-block) grid —
the TPU grid is sequential, so in-place accumulation into the resident
output block is safe (same idiom as `_moments_kernel`).

VJP strategy: forward is the fused Pallas pass; backward's GEMMs
(dpatches = du @ w2d.T, dw2d = patches.T @ du) stay with XLA — it already
tiles transposed matmuls optimally (the pallas_kernels.py philosophy), and
the moments/epilogue cotangent is a broadcastwise expression XLA fuses
into them. Cross-shard moment reduction happens OUTSIDE the kernel
(lax.pmean under an axis_name, or per data-shard inside a nested
shard_map under the gspmd backend's `pallas_mesh` — pallas_call is opaque
to GSPMD, the ops/norm.py pattern), so both parallel backends pick the
blocks up without touching step structure.

Everything degrades to `interpret=True` off-TPU: tier-1 pins numerical
parity (forward AND gradients) against the unfused conv+BN reference on
the CPU mesh without a TPU.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from dcgan_tpu.ops.activations import ACTS, LEAK
from dcgan_tpu.ops.activations import act_fwd as _act_fwd
from dcgan_tpu.ops.activations import act_grad as _act_grad
from dcgan_tpu.ops.pallas_kernels import _interpret, _row_tile

Pytree = dict

_CONV_DIMS = ("NHWC", "HWIO", "NHWC")


def _k_tile(n: int) -> int:
    """Largest contraction-block <= 512 dividing n. The contraction dim is
    Cin*kh*kw (e.g. 1600..12800 at the 128/256px stages) — streaming it in
    blocks keeps the weight tile (tk x Cout) VMEM-resident instead of the
    whole [K, Cout] matrix (13 MiB f32 at the deepest 256px stage)."""
    tile = min(n, 512)
    while n % tile:
        tile -= 1
    return tile


def w_to_gemm(w: jax.Array) -> jax.Array:
    """[kh, kw, Cin, Cout] HWIO kernel -> [Cin*kh*kw, Cout] GEMM operand.
    conv_general_dilated_patches orders the patch features channel-major
    (Cin slowest, then kh, kw) — hence the (2, 0, 1, 3) transpose."""
    kh, kw, cin, cout = w.shape
    return jnp.transpose(w, (2, 0, 1, 3)).reshape(kh * kw * cin, cout)


def _transpose_pads(k: int, s: int) -> Tuple[int, int]:
    # lax.conv_transpose's SAME padding arithmetic (jax.lax internal), so
    # the dilated-patch formulation matches it exactly (tests pin 0 error)
    pad_len = k + s - 2
    pad_a = k - 1 if s > k - 1 else int(np.ceil(pad_len / 2))
    return pad_a, pad_len - pad_a


def conv_patches(x: jax.Array, kernel: int, stride: int,
                 transpose: bool) -> Tuple[jax.Array, Tuple[int, int, int]]:
    """im2col rows for a strided (or transposed) SAME conv.

    Returns (patches2d [N*Ho*Wo, Cin*k*k], (N, Ho, Wo))."""
    if transpose:
        pads = [_transpose_pads(kernel, stride)] * 2
        p = lax.conv_general_dilated_patches(
            x, (kernel, kernel), (1, 1), pads,
            lhs_dilation=(stride, stride), dimension_numbers=_CONV_DIMS)
    else:
        p = lax.conv_general_dilated_patches(
            x, (kernel, kernel), (stride, stride), "SAME",
            dimension_numbers=_CONV_DIMS)
    n, ho, wo, f = p.shape
    return p.reshape(n * ho * wo, f), (n, ho, wo)


# ---------------------------------------------------------------------------
# Kernel 1: GEMM + bias + per-channel moments (train-path forward)
# ---------------------------------------------------------------------------

def _gemm_bias_moments_kernel(p_ref, w_ref, b_ref, y_ref, sum_ref,
                              sumsq_ref, *, k_blocks, out_dtype):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _():
        sum_ref[:] = jnp.zeros_like(sum_ref)
        sumsq_ref[:] = jnp.zeros_like(sumsq_ref)

    @pl.when(j == 0)
    def _():
        y_ref[:] = jnp.zeros_like(y_ref)

    y_ref[:] += jnp.dot(p_ref[:], w_ref[:],
                        preferred_element_type=jnp.float32)

    @pl.when(j == k_blocks - 1)
    def _():
        u = y_ref[:] + b_ref[:]
        y_ref[:] = u
        # moments of the value the model will actually SEE (the conv output
        # after its cast to compute dtype) — bit-parity with the unfused
        # path, which reduces the stored activation
        uc = u.astype(out_dtype).astype(jnp.float32)
        sum_ref[:] += jnp.sum(uc, axis=0, keepdims=True)
        sumsq_ref[:] += jnp.sum(uc * uc, axis=0, keepdims=True)


def _gbm_impl(p2d, w2d, b, out_dtype):
    m, k = p2d.shape
    c = w2d.shape[1]
    tm, tk = _row_tile(m), _k_tile(k)
    acc_spec = pl.BlockSpec((1, c), lambda i, j: (0, 0))
    y, sums, sumsqs = pl.pallas_call(
        functools.partial(_gemm_bias_moments_kernel, k_blocks=k // tk,
                          out_dtype=jnp.dtype(out_dtype)),
        grid=(m // tm, k // tk),
        in_specs=[pl.BlockSpec((tm, tk), lambda i, j: (i, j)),
                  pl.BlockSpec((tk, c), lambda i, j: (j, 0)),
                  acc_spec],
        out_specs=(pl.BlockSpec((tm, c), lambda i, j: (i, 0)),
                   acc_spec, acc_spec),
        out_shape=(jax.ShapeDtypeStruct((m, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)),
        interpret=_interpret(),
    )(p2d, w2d, b.reshape(1, c).astype(jnp.float32))
    inv_m = 1.0 / m
    return y, sums[0] * inv_m, sumsqs[0] * inv_m


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def gemm_bias_moments(p2d: jax.Array, w2d: jax.Array, b: jax.Array,
                      out_dtype=jnp.float32):
    """One fused pass: u = p2d @ w2d + b (f32 accumulation) together with
    the per-channel (E[u], E[u^2]) the BN train path needs. Returns
    (u [M, C] float32, mean [C], mean_sq [C]); callers cast u to their
    compute dtype (the moments already describe the cast value)."""
    return _gbm_impl(p2d, w2d, b, out_dtype)


def _gbm_vjp_fwd(p2d, w2d, b, out_dtype):
    out = _gbm_impl(p2d, w2d, b, out_dtype)
    return out, (p2d, w2d, b, out[0])


def _gbm_vjp_bwd(out_dtype, res, g):
    # d mean/du = 1/M, d mean_sq/du = 2u/M — folded into the GEMM
    # cotangent so backward stays two XLA matmuls + one fused epilogue
    p2d, w2d, b, u = res
    gu, g_mean, g_msq = g
    m = u.shape[0]
    du = gu.astype(jnp.float32) + (g_mean[None, :]
                                   + 2.0 * u * g_msq[None, :]) / m
    dp = jnp.dot(du, w2d.astype(jnp.float32).T,
                 preferred_element_type=jnp.float32)
    dw = jnp.dot(p2d.astype(jnp.float32).T, du,
                 preferred_element_type=jnp.float32)
    # db cast to the bias's own dtype: a f32 cotangent for a bf16 param
    # would promote its Adam nu leaf to f32 across the step, breaking
    # state-carry dtype invariance (and with it donation aliasing)
    db = jnp.sum(du, axis=0)
    return (dp.astype(p2d.dtype), dw.astype(w2d.dtype), db.astype(b.dtype))


gemm_bias_moments.defvjp(_gbm_vjp_fwd, _gbm_vjp_bwd)


# ---------------------------------------------------------------------------
# Kernel 2: GEMM + bias + BN affine + activation (inference-path forward —
# running stats are known, so the whole stage fuses into ONE kernel)
# ---------------------------------------------------------------------------

def _gemm_bias_scale_act_kernel(p_ref, w_ref, b_ref, scale_ref, shift_ref,
                                y_ref, acc_ref, *, k_blocks, act, leak):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(p_ref[:], w_ref[:],
                          preferred_element_type=jnp.float32)

    @pl.when(j == k_blocks - 1)
    def _():
        u = acc_ref[:] + b_ref[:]
        v = u * scale_ref[:] + shift_ref[:]
        y_ref[:] = _act_fwd(v, act, leak).astype(y_ref.dtype)


def _gbsa_impl(p2d, w2d, b, scale, shift, act, leak, out_dtype):
    if act not in ACTS:
        raise ValueError(f"unknown act {act!r}")
    m, k = p2d.shape
    c = w2d.shape[1]
    tm, tk = _row_tile(m), _k_tile(k)
    vec_spec = pl.BlockSpec((1, c), lambda i, j: (0, 0))
    y, _ = pl.pallas_call(
        functools.partial(_gemm_bias_scale_act_kernel, k_blocks=k // tk,
                          act=act, leak=leak),
        grid=(m // tm, k // tk),
        in_specs=[pl.BlockSpec((tm, tk), lambda i, j: (i, j)),
                  pl.BlockSpec((tk, c), lambda i, j: (j, 0)),
                  vec_spec, vec_spec, vec_spec],
        out_specs=(pl.BlockSpec((tm, c), lambda i, j: (i, 0)),
                   pl.BlockSpec((tm, c), lambda i, j: (i, 0))),
        out_shape=(jax.ShapeDtypeStruct((m, c), jnp.dtype(out_dtype)),
                   # f32 accumulator rides as a second output block (grid-
                   # resident across the k sweep; discarded) so the kernel
                   # needs no scratch allocation in interpret mode
                   jax.ShapeDtypeStruct((m, c), jnp.float32)),
        interpret=_interpret(),
    )(p2d, w2d, b.reshape(1, c).astype(jnp.float32),
      scale.reshape(1, c).astype(jnp.float32),
      shift.reshape(1, c).astype(jnp.float32))
    return y


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def gemm_bias_scale_act(p2d: jax.Array, w2d: jax.Array, b: jax.Array,
                        scale: jax.Array, shift: jax.Array,
                        act: str = "none", leak: float = LEAK,
                        out_dtype=jnp.float32) -> jax.Array:
    """Fully fused inference stage: act((p2d @ w2d + b) * scale + shift)
    in one Pallas pass. Differentiable — the R1/WGAN-GP penalty critics run
    with train=False BN and ARE differentiated — with an XLA backward that
    recomputes u (one matmul) instead of storing it."""
    return _gbsa_impl(p2d, w2d, b, scale, shift, act, leak, out_dtype)


def _gbsa_vjp_fwd(p2d, w2d, b, scale, shift, act, leak, out_dtype):
    y = _gbsa_impl(p2d, w2d, b, scale, shift, act, leak, out_dtype)
    return y, (p2d, w2d, b, scale, shift)


def _gbsa_vjp_bwd(act, leak, out_dtype, res, g):
    p2d, w2d, b, scale, shift = res
    sf = scale.astype(jnp.float32)
    u = jnp.dot(p2d.astype(jnp.float32), w2d.astype(jnp.float32),
                preferred_element_type=jnp.float32) \
        + b.astype(jnp.float32)[None, :]
    v = u * sf[None, :] + shift.astype(jnp.float32)[None, :]
    dv = g.astype(jnp.float32) * _act_grad(v, act, leak)
    du = dv * sf[None, :]
    dscale = jnp.sum(dv * u, axis=0)
    dshift = jnp.sum(dv, axis=0)
    dp = jnp.dot(du, w2d.astype(jnp.float32).T,
                 preferred_element_type=jnp.float32)
    dw = jnp.dot(p2d.astype(jnp.float32).T, du,
                 preferred_element_type=jnp.float32)
    db = jnp.sum(du, axis=0)
    return (dp.astype(p2d.dtype), dw.astype(w2d.dtype), db.astype(b.dtype),
            dscale.astype(scale.dtype), dshift.astype(shift.dtype))


gemm_bias_scale_act.defvjp(_gbsa_vjp_fwd, _gbsa_vjp_bwd)


# ---------------------------------------------------------------------------
# fp8 ladder rung (TrainConfig.precision="fp8", ISSUE 17): simulated-
# quantization matmul/conv operands — amax-scaled float8_e4m3fn round-trip,
# so the CPU mesh exercises the numerics without fp8 MXU support. Shared by
# the unfused layers (ops/layers.py) and the fused blocks below.
# ---------------------------------------------------------------------------

from dcgan_tpu.ops.layers import _fake_quant_fp8 as fake_quant_fp8  # noqa: E402
# (one definition, in ops/layers.py — the import-light home the unfused
# conv/deconv paths share; re-exported here for the fused blocks and tests)


# ---------------------------------------------------------------------------
# The fused stage: conv/deconv ⊕ bias ⊕ BN ⊕ act, both-backend routing
# ---------------------------------------------------------------------------

def _shard_gemm_moments(p2d, w2d, b, out_dtype, mesh):
    """gemm_bias_moments per data-shard + pmean under the gspmd backend's
    pallas_mesh (pallas_call is opaque to GSPMD — the ops/norm.py
    `_pallas_shard_moments` pattern, check_vma=False for the same reason)."""
    from jax.sharding import PartitionSpec as P

    from dcgan_tpu.utils.backend import shard_map

    def _body(pl_, w_, b_):
        u, mean, msq = gemm_bias_moments(pl_, w_, b_, out_dtype)
        return u, lax.pmean(mean, "data"), lax.pmean(msq, "data")

    return shard_map(_body, mesh=mesh,
                     in_specs=(P("data", None), P(), P()),
                     out_specs=(P("data", None), P(), P()),
                     check=False)(p2d, w2d, b)


def _shard_gemm_scale_act(p2d, w2d, b, scale, shift, act, leak, out_dtype,
                          mesh):
    from jax.sharding import PartitionSpec as P

    from dcgan_tpu.utils.backend import shard_map

    def _body(pl_, w_, b_, s_, t_):
        return gemm_bias_scale_act(pl_, w_, b_, s_, t_, act, leak,
                                   out_dtype)

    return shard_map(_body, mesh=mesh,
                     in_specs=(P("data", None), P(), P(), P(), P()),
                     out_specs=P("data", None),
                     check=False)(p2d, w2d, b, scale, shift)


def fused_conv_bn_act(conv_params: Pytree, bn_params: Pytree,
                      bn_state: Pytree, x: jax.Array, *, transpose: bool,
                      kernel: int, stride: int = 2, train: bool,
                      momentum: float = 0.9, eps: float = 1e-5,
                      act: str, leak: float = LEAK,
                      axis_name: Optional[str] = None, pallas_mesh=None,
                      compute_dtype=None,
                      quant: str = "") -> Tuple[jax.Array, Pytree]:
    """One G/D stage as fused Pallas passes: conv (transpose=False, the D
    `conv⊕BN⊕lrelu` block) or deconv (transpose=True, the G
    `deconv⊕BN⊕relu` block), returning (y, new_bn_state) with exactly
    `batch_norm_apply`'s state contract so the model loops swap it in
    behind ModelConfig.pallas_fused without touching step structure.

    train=True : pass 1 fuses GEMM+bias+moments; the cross-shard pmean and
    BN's EMA/var arithmetic run between passes (they are [C]-sized); pass 2
    is the shared `scale_shift_act` epilogue kernel.
    train=False: the running stats are known ahead of the GEMM, so the
    whole stage collapses into the single gemm_bias_scale_act kernel.
    """
    from dcgan_tpu.ops.norm import finish_batch_moments
    from dcgan_tpu.ops.pallas_kernels import scale_shift_act

    cdt = jnp.dtype(compute_dtype) if compute_dtype is not None else x.dtype
    w, b = conv_params["w"], conv_params["b"]
    x = x.astype(cdt)
    w2d = w_to_gemm(w.astype(cdt))
    p2d, (n, ho, wo) = conv_patches(x, kernel, stride, transpose)
    if quant == "fp8":
        p2d, w2d = fake_quant_fp8(p2d), fake_quant_fp8(w2d)
    c = w2d.shape[1]
    gamma, beta = bn_params["scale"], bn_params["bias"]

    if train:
        if pallas_mesh is not None:
            u, mean, mean_sq = _shard_gemm_moments(p2d, w2d, b, cdt,
                                                   pallas_mesh)
        else:
            u, mean, mean_sq = gemm_bias_moments(p2d, w2d, b, cdt)
            if axis_name is not None:
                mean = lax.pmean(mean, axis_name)
                mean_sq = lax.pmean(mean_sq, axis_name)
        mean, var, new_state = finish_batch_moments(
            bn_state, mean, mean_sq, momentum=momentum)
        inv = lax.rsqrt(var + jnp.float32(eps))
        scale = gamma.astype(jnp.float32) * inv
        shift = beta.astype(jnp.float32) - mean * scale
        u = u.astype(cdt)
        if pallas_mesh is not None:
            from dcgan_tpu.ops.norm import _pallas_shard_epilogue

            # reuse the BN epilogue's per-shard wrapper (elementwise over
            # rows; shard_map transpose inserts the replicated-grad psums)
            y2d = _pallas_shard_epilogue(
                u, gamma, beta, mean, var, eps=eps, act=act, leak=leak,
                mesh=pallas_mesh)
        else:
            y2d = scale_shift_act(u, scale, shift, act, leak)
        return y2d.reshape(n, ho, wo, c), new_state

    mean = bn_state["mean"].astype(jnp.float32)
    var = bn_state["var"].astype(jnp.float32)
    inv = lax.rsqrt(var + jnp.float32(eps))
    scale = gamma.astype(jnp.float32) * inv
    shift = beta.astype(jnp.float32) - mean * scale
    if pallas_mesh is not None:
        y2d = _shard_gemm_scale_act(p2d, w2d, b, scale, shift, act, leak,
                                    cdt, pallas_mesh)
    else:
        y2d = gemm_bias_scale_act(p2d, w2d, b, scale, shift, act, leak, cdt)
    return y2d.reshape(n, ho, wo, c), bn_state


# ---------------------------------------------------------------------------
# Analytic cost model (tools/step_profile.py PALLAS_FUSED=1 rows)
# ---------------------------------------------------------------------------

def fused_sites(cfg, batch: int):
    """The fused-block launches of one G forward + one D forward at `cfg`
    (plain-dcgan arch): one descriptor per interior stage, exactly the
    model loops' gating (G stages 1..k-1, D stages 1..k-1; the boundary
    stages stay unfused). A site's kernel is the GEMM [M, K] @ [K, C]
    with M = batch * out_res**2 patch rows and K = in_ch * kernel**2 —
    the same formulation `conv_patches`/`w_to_gemm` lower, so the
    analytic rows below decompose the program that actually runs."""
    k = cfg.num_up_layers
    ks = cfg.kernel_size
    sites = []
    for i in range(1, k):
        out_res = cfg.base_size * (2 ** i)
        in_ch = cfg.gf_dim * (2 ** (k - i))
        sites.append({"name": f"gen/deconv{i}", "transpose": True,
                      "act": "relu", "in_res": cfg.base_size * 2 ** (i - 1),
                      "out_res": out_res, "in_ch": in_ch,
                      "m": batch * out_res * out_res,
                      "k": in_ch * ks * ks,
                      "c": cfg.gf_dim * (2 ** (k - 1 - i))})
    for i in range(1, k):
        out_res = cfg.output_size >> (i + 1)
        in_ch = cfg.df_dim * (2 ** (i - 1))
        sites.append({"name": f"disc/conv{i}", "transpose": False,
                      "act": "lrelu", "in_res": cfg.output_size >> i,
                      "out_res": out_res, "in_ch": in_ch,
                      "m": batch * out_res * out_res,
                      "k": in_ch * ks * ks, "c": cfg.df_dim * (2 ** i)})
    return sites


def kernel_cost(m: int, k: int, c: int, *, train: bool,
                compute_dtype=jnp.float32):
    """Analytic flops / HBM bytes / peak-VMEM model of one fused forward
    launch, per-part so the conservation check (step_profile) can pin
    fused == sum-of-parts. The GEMM dominates (2MKC); the fused win is
    the BYTES column — train mode reads the patch matrix once and never
    round-trips the pre-BN activation through HBM, inference collapses
    the whole stage into one kernel. `peak_temp_mib` is the VMEM-resident
    working set of one grid step: the operand tiles plus the f32
    accumulator/moment blocks the sequential-k grid revisits."""
    isz = jnp.dtype(compute_dtype).itemsize
    parts = {"gemm": 2 * m * k * c, "bias": m * c}
    if train:
        # kernel 1's moment accumulation (u^2 + the two sums) and the
        # scale_shift_act epilogue pass (scale*u + shift, act compare)
        parts["moments"] = 3 * m * c
        parts["epilogue"] = 4 * m * c
        # u is written f32 (accumulator dtype), moments are 2x [C] f32;
        # the epilogue pass re-reads u and writes the cast activation
        hbm = (m * k * isz + k * c * isz + c * 4        # patches, w, b
               + m * c * 4 + 2 * c * 4                  # u, mean, mean_sq
               + m * c * 4 + m * c * isz)               # epilogue r/w
    else:
        # single-kernel stage: scale+shift fold the running stats, one
        # activation, output written once in compute dtype
        parts["scale_act"] = 3 * m * c
        hbm = (m * k * isz + k * c * isz + 3 * c * 4    # + scale, shift
               + m * c * isz)
    tm, tk = _row_tile(m), _k_tile(k)
    vmem = (tm * tk + tk * c) * isz + tm * c * 4 + 2 * c * 4
    return {"flops": sum(parts.values()), "flops_parts": parts,
            "bytes": hbm, "peak_temp_mib": round(vmem / 2**20, 3)}
