"""Linear / conv / transposed-conv / leaky-relu as pure init/apply pairs.

Reference behavior being matched (not copied — the reference is TF graph code):
- `linear`:   W ~ N(0, 0.02), b = 0                  (distriubted_model.py:160-173)
- `conv2d`:   5x5 stride-2 SAME, W ~ TruncNorm(0.02) (distriubted_model.py:176-187)
- `deconv2d`: 5x5 stride-2 SAME, W ~ N(0, 0.02)      (distriubted_model.py:190-213)
- `lrelu`:    max(x, 0.2x)                           (distriubted_model.py:156-157)

TPU notes: NHWC layout with HWIO kernels (XLA:TPU's preferred conv layout);
compute in bfloat16 with float32 params — the matmul/conv lands on the MXU, the
cast is free in the fused epilogue. All shapes are static so XLA can tile.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

Pytree = dict


def _fake_quant_fp8(x: jax.Array) -> jax.Array:
    """Simulated fp8 matmul/conv operand (TrainConfig.precision='fp8'):
    round-trip through float8_e4m3fn with per-tensor amax scaling — the
    e4m3 max normal is 448, so an unscaled cast overflows to NaN. Runs the
    fp8 NUMERICS on any backend; real fp8 MXU dispatch is a lowering
    concern this experiment deliberately leaves to XLA."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf)) / 448.0, 1e-12)
    q = (xf / scale).astype(jnp.float8_e4m3fn).astype(jnp.float32)
    return (q * scale).astype(x.dtype)


def _stddev_init(key, shape, stddev, dtype, truncated=False):
    if truncated:
        # TF truncated_normal: resample outside 2 sigma; jax provides the same.
        return stddev * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)
    return stddev * jax.random.normal(key, shape, dtype)


# ---------------------------------------------------------------------------
# linear
# ---------------------------------------------------------------------------

def linear_init(key, in_dim: int, out_dim: int, *, stddev: float = 0.02,
                dtype=jnp.float32) -> Pytree:
    kw, _ = jax.random.split(key)
    return {
        "w": _stddev_init(kw, (in_dim, out_dim), stddev, dtype),
        "b": jnp.zeros((out_dim,), dtype),
    }


def linear_apply(params: Pytree, x: jax.Array, *, compute_dtype=None) -> jax.Array:
    w, b = params["w"], params["b"]
    if compute_dtype is not None:
        x, w = x.astype(compute_dtype), w.astype(compute_dtype)
    return x @ w + b.astype(x.dtype)


# ---------------------------------------------------------------------------
# conv2d (strided, SAME)
# ---------------------------------------------------------------------------

_CONV_DIMS = ("NHWC", "HWIO", "NHWC")


def conv2d_init(key, in_ch: int, out_ch: int, *, kernel: int = 5,
                stddev: float = 0.02, dtype=jnp.float32) -> Pytree:
    kw, _ = jax.random.split(key)
    return {
        "w": _stddev_init(kw, (kernel, kernel, in_ch, out_ch), stddev, dtype,
                          truncated=True),
        "b": jnp.zeros((out_ch,), dtype),
    }


def conv2d_apply(params: Pytree, x: jax.Array, *, stride: int = 2,
                 compute_dtype=None, quant: str = "") -> jax.Array:
    w, b = params["w"], params["b"]
    if compute_dtype is not None:
        x, w = x.astype(compute_dtype), w.astype(compute_dtype)
    if quant == "fp8":
        x, w = _fake_quant_fp8(x), _fake_quant_fp8(w)
    y = lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=_CONV_DIMS)
    return y + b.astype(y.dtype)


# ---------------------------------------------------------------------------
# deconv2d (transposed conv, SAME, output = input * stride)
# ---------------------------------------------------------------------------

def deconv2d_init(key, in_ch: int, out_ch: int, *, kernel: int = 5,
                  stddev: float = 0.02, dtype=jnp.float32) -> Pytree:
    kw, _ = jax.random.split(key)
    return {
        "w": _stddev_init(kw, (kernel, kernel, in_ch, out_ch), stddev, dtype),
        "b": jnp.zeros((out_ch,), dtype),
    }


def deconv2d_apply(params: Pytree, x: jax.Array, *, stride: int = 2,
                   compute_dtype=None, quant: str = "") -> jax.Array:
    w, b = params["w"], params["b"]
    if compute_dtype is not None:
        x, w = x.astype(compute_dtype), w.astype(compute_dtype)
    if quant == "fp8":
        x, w = _fake_quant_fp8(x), _fake_quant_fp8(w)
    y = lax.conv_transpose(
        x, w, strides=(stride, stride), padding="SAME",
        dimension_numbers=_CONV_DIMS)
    return y + b.astype(y.dtype)


# ---------------------------------------------------------------------------
# lrelu
# ---------------------------------------------------------------------------

def lrelu(x: jax.Array, leak: float = 0.2) -> jax.Array:
    return jnp.maximum(x, leak * x)
