"""BatchNorm with explicit, functional EMA state.

The reference's `batch_norm` class (distriubted_model.py:15-52) keeps its running
statistics as hidden TF side-state: an ExponentialMovingAverage(decay=0.9) whose
shadow variables are captured during the *train* graph build and read back by the
inference-mode `sampler` (distriubted_model.py:42,47 — a trap: sampler silently
depends on generator having been traced first, SURVEY.md §2.4 #9).

Here the running (mean, var) are an explicit pytree threaded through apply():

    params = {"scale": gamma, "bias": beta}            # gamma ~ N(1, 0.02), beta = 0
    state  = {"mean": m, "var": v}                     # EMA with momentum 0.9

    y, new_state = batch_norm_apply(params, state, x, train=True)

Cross-replica ("synced") statistics come for free under jit-with-sharding: the
batch-axis mean/var below are *global* reductions, so GSPMD lowers them to ICI
all-reduces when the batch is sharded over the mesh. For explicit-collective code
(shard_map/pmap) pass `axis_name=` and the moments are pmean'd by hand — both
paths replace the reference's per-worker (unsynced) statistics, as required by
BASELINE.json's synced-BN config.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dcgan_tpu.utils.backend import shard_map

Pytree = dict


def batch_norm_init(key, num_features: int, *, dtype=jnp.float32,
                    scale_stddev: float = 0.02,
                    num_classes: int = 0) -> Tuple[Pytree, Pytree]:
    """Returns (params, state). gamma ~ N(1, 0.02), beta = 0 as in the reference
    (distriubted_model.py:31-34); state starts at (mean=0, var=1).

    num_classes > 0 makes the affine CONDITIONAL (the cBN of SAGAN/BigGAN):
    scale/bias become per-class tables [K, C] gathered per example at apply
    time; the running moments stay shared across classes (standard cBN)."""
    shape = (num_classes, num_features) if num_classes else (num_features,)
    params = {
        "scale": 1.0 + scale_stddev * jax.random.normal(key, shape, dtype),
        "bias": jnp.zeros(shape, dtype),
    }
    state = {
        "mean": jnp.zeros((num_features,), dtype),
        "var": jnp.ones((num_features,), dtype),
    }
    return params, state


def finish_batch_moments(state: Pytree, mean: jax.Array,
                         mean_sq: jax.Array, *, momentum: float = 0.9
                         ) -> Tuple[jax.Array, jax.Array, Pytree]:
    """The BN train-path arithmetic downstream of the (already cross-shard-
    reduced) raw moments: E[x^2]-E[x]^2 with the negative-cancellation
    clamp, and the EMA state update in the stored stat dtype. Shared by
    `batch_norm_apply` and the fused conv blocks (ops/pallas_fused.py) so
    the two paths cannot drift. Returns (mean, var, new_state) with
    mean/var in float32."""
    mean = mean.astype(jnp.float32)
    # E[x^2]-E[x]^2 can cancel slightly negative in f32; clamp so
    # rsqrt(var+eps) can never produce NaN.
    var = jnp.maximum(mean_sq.astype(jnp.float32) - jnp.square(mean), 0.0)
    stat_dtype = state["mean"].dtype
    new_state = {
        "mean": momentum * state["mean"]
                + (1.0 - momentum) * mean.astype(stat_dtype),
        "var": momentum * state["var"]
               + (1.0 - momentum) * var.astype(stat_dtype),
    }
    return mean, var, new_state


def _pallas_shard_moments(x: jax.Array, mesh) -> Tuple[jax.Array, jax.Array]:
    """channel_moments per data-shard + pmean — pallas_call is opaque to
    GSPMD (the partitioner would all-gather the batch around it), so under a
    sharded mesh the kernel runs inside a shard_map over the "data" axis with
    the cross-shard reduction written explicitly (the same nest-a-shard_map-
    in-the-gspmd-jit pattern as ring attention, ops/attention.py)."""
    from jax.sharding import PartitionSpec as P

    from dcgan_tpu.ops.pallas_kernels import channel_moments

    bspec = P("data", *([None] * (x.ndim - 1)))

    def _moments(xl):
        m, ms = channel_moments(xl.reshape(-1, xl.shape[-1]))
        return lax.pmean(m, "data"), lax.pmean(ms, "data")

    # check_vma=False: pallas_call outputs carry no vma annotations (the
    # same concession the shard_map backend makes, shard_map_backend.py:74);
    # AD still inserts the psum for replicated-input gradients
    return shard_map(_moments, mesh=mesh, in_specs=(bspec,),
                     out_specs=(P(), P()), check=False)(x)


def _pallas_shard_epilogue(x, scale, bias, mean, var, *, eps, act, leak,
                           mesh):
    """fused_bn_act per data-shard (elementwise over rows, so no collective
    is needed); shard_map's transpose inserts the psum for the replicated
    scale/bias gradients."""
    from jax.sharding import PartitionSpec as P

    from dcgan_tpu.ops.pallas_kernels import fused_bn_act

    bspec = P("data", *([None] * (x.ndim - 1)))

    def _epilogue(xl, s, b, m, v):
        return fused_bn_act(xl, s, b, m, v, eps=eps, act=act, leak=leak)

    return shard_map(_epilogue, mesh=mesh,
                     in_specs=(bspec, P(), P(), P(), P()),
                     out_specs=bspec,
                     check=False)(x, scale, bias, mean, var)


def batch_norm_apply(params: Pytree, state: Pytree, x: jax.Array, *,
                     train: bool, momentum: float = 0.9, eps: float = 1e-5,
                     axis_name: Optional[str] = None, act: str = "none",
                     leak: float = 0.2, use_pallas: bool = False,
                     labels: Optional[jax.Array] = None,
                     pallas_mesh=None) -> Tuple[jax.Array, Pytree]:
    """Normalize `x` over all axes but the last (channel) axis, optionally
    fusing the following activation (`act` in {"none","relu","lrelu","tanh"}).

    train=True : use batch moments, return EMA-updated state
                 (the reference's moments over [0,1,2] with a [0,1] fallback for
                 2-D inputs, distriubted_model.py:36-39, generalizes to "all but
                 channels" here).
    train=False: use the running statistics; state is returned unchanged.

    use_pallas=True routes the moments reduction and the normalize+activation
    epilogue through the fused Pallas kernels (ops/pallas_kernels.py) — one
    HBM pass each way instead of one per op. Under the gspmd backend on a
    multi-device mesh pass `pallas_mesh` and the kernels run per data-shard
    inside a shard_map (pallas_call is opaque to the partitioner); with
    explicit-collective code (shard_map backend) leave it None and pass
    `axis_name` as usual.

    Conditional BN (params built with num_classes > 0): pass `labels` [B] and
    each example is scaled/shifted by its class's row of the [K, C] tables.
    The per-example affine breaks the fused kernels' per-channel-vector
    contract, so cBN always takes the jnp path.
    """
    if train:
        if use_pallas:
            if pallas_mesh is not None:
                mean, mean_sq = _pallas_shard_moments(x, pallas_mesh)
            else:
                from dcgan_tpu.ops.pallas_kernels import channel_moments

                mean, mean_sq = channel_moments(x.reshape(-1, x.shape[-1]))
        else:
            # Moments in float32 even under bfloat16 activations — bf16
            # accumulation over a 64*64*64 reduction loses too many bits for
            # stable statistics.
            reduce_axes = tuple(range(x.ndim - 1))
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduce_axes)
            # E[x^2] - E[x]^2 so a single fused pass feeds both moments;
            # psum-friendly.
            mean_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
        if axis_name is not None:
            mean = lax.pmean(mean, axis_name)
            mean_sq = lax.pmean(mean_sq, axis_name)
        mean, var, new_state = finish_batch_moments(
            state, mean, mean_sq, momentum=momentum)
    else:
        mean = state["mean"]
        var = state["var"]
        new_state = state

    conditional = params["scale"].ndim == 2
    if conditional:
        if labels is None:
            raise ValueError("conditional BN requires labels")
        # per-example affine: gather class rows, broadcast over spatial dims
        bshape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
        scale = params["scale"][labels].reshape(bshape).astype(x.dtype)
        bias = params["bias"][labels].reshape(bshape).astype(x.dtype)
    elif use_pallas:
        if pallas_mesh is not None:
            y = _pallas_shard_epilogue(x, params["scale"], params["bias"],
                                       mean, var, eps=eps, act=act,
                                       leak=leak, mesh=pallas_mesh)
        else:
            from dcgan_tpu.ops.pallas_kernels import fused_bn_act

            y = fused_bn_act(x, params["scale"], params["bias"], mean, var,
                             eps=eps, act=act, leak=leak)
        return y, new_state
    else:
        scale = params["scale"].astype(x.dtype)
        bias = params["bias"].astype(x.dtype)
    inv = lax.rsqrt(var.astype(x.dtype) + jnp.asarray(eps, x.dtype))
    y = (x - mean.astype(x.dtype)) * inv * scale + bias
    y = _apply_act(y, act, leak)
    return y, new_state


def _apply_act(y: jax.Array, act: str, leak: float) -> jax.Array:
    # dispatch table shared with the pallas kernels (ops/activations.py) so
    # the two BN paths cannot silently diverge — without pulling
    # jax.experimental.pallas into the default path
    from dcgan_tpu.ops.activations import ACTS, act_fwd

    if act not in ACTS:
        raise ValueError(f"unknown act {act!r}")
    return act_fwd(y, act, leak)
