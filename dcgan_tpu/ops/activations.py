"""Activation dispatch shared by the plain-jnp and Pallas BN paths.

One table for forward and derivative so the two implementations of the fused
BN epilogue (ops/norm.py jnp path, ops/pallas_kernels.py kernels) cannot
silently diverge — and so the default path never imports
jax.experimental.pallas. Covers the reference's activation set: relu
(generator, distriubted_model.py:95-106), lrelu(0.2) (discriminator,
distriubted_model.py:118-121,156), tanh (generator output, :111).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

ACTS = ("none", "relu", "lrelu", "tanh")
LEAK = 0.2  # lrelu slope (distriubted_model.py:156)


def act_fwd(u: jax.Array, act: str, leak: float = LEAK) -> jax.Array:
    if act == "relu":
        return jnp.maximum(u, 0.0)
    if act == "lrelu":
        return jnp.maximum(u, leak * u)
    if act == "tanh":
        return jnp.tanh(u)
    return u


def act_grad(u: jax.Array, act: str, leak: float = LEAK) -> jax.Array:
    if act == "relu":
        return jnp.where(u > 0.0, 1.0, 0.0)
    if act == "lrelu":
        return jnp.where(u > 0.0, 1.0, leak)
    if act == "tanh":
        t = jnp.tanh(u)
        return 1.0 - t * t
    return jnp.ones_like(u)
