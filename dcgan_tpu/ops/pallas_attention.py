"""Pallas TPU flash attention: blocked online-softmax forward + flash backward.

The attention block (ops/attention.py) is the framework's long-context hot op
— images flatten to an H*W token sequence and DCGAN's conv stacks turn into
SAGAN stacks (ModelConfig.attn_res). XLA lowers dense attention as materialize
-softmax-matmul: the [S, S] score matrix crosses HBM twice per direction. This
module is the memory-optimal form (Flash Attention, arXiv:2205.14135,
expressed as TPU Pallas kernels): scores live only as [TQ, TK] VMEM tiles, an
online softmax folds each tile into running (max, normalizer, accumulator)
statistics, and the backward recomputes tiles from the saved log-sum-exp
instead of reading a stored probability matrix. O(S) HBM traffic in S instead
of O(S^2) — the property that makes sequence length a free axis.

Layout notes (TPU):
- Blocks are [TQ, d] / [TK, d] with TQ = 256, TK = 1024 (chip-swept, see
  BLOCK_Q/BLOCK_K below — NOT the 128 MXU edge: the systolic array stays
  busy either way, and wide k-tiles quarter the serialized online-softmax
  iterations); `q @ k^T` and `p @ v` land on the MXU in the input dtype
  with f32 accumulation (`preferred_element_type`).
- Grid is (B, S/TQ) for forward/dq and (B, S/TK) for dk/dv — the kernel loops
  over the opposite axis with `lax.fori_loop`, keeping per-program state in
  VMEM scratch.
- The head dims here are narrow (SAGAN: d_qk = C/8, d_v = C/2); they ride the
  lane axis zero-padded. That wastes lanes but not HBM, and the kernels are
  shape-agnostic — the same code serves wide heads.
- Off-TPU the kernels run under `interpret=True`, so the CPU test mesh
  exercises the identical code path (tests/test_pallas_attention.py asserts
  exactness against ops/attention.py::full_attention, gradients included).

Composition: `ops/attention.py::attn_apply(use_pallas=True)` routes its dense
path here (single chip, or per-shard under the shard_map backend — pallas_call
is opaque to the GSPMD partitioner, same constraint as ops/pallas_kernels.py).
Under a spatial mesh the same flag routes the ring strategy through
`ring_flash_attention` (bottom of this module): ring hops bound the
per-device sequence, flash tiles bound the per-hop fold, so neither level
ever materializes a score matrix — the nesting for sequences whose shards
are themselves long.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tile sizes. The per-tile softmax state update is loop-carried, so tile
# COUNT — not matmul rate — dominates at the head dims this model uses;
# large k-tiles amortize that serialization. (256, 1024) is the chip-swept
# optimum (v5e, S=16384 fwd+bwd: 11.95 ms vs 28.9 at the naive MXU-edge
# 128/128 and 21.6 dense; at S=40960 flash 35.96 ms vs dense 80.66 — the
# sweep grid and every measured cell are in DESIGN.md §8). Overridable via
# the DCGAN_FLASH_TQ / DCGAN_FLASH_TK env vars — read at TRACE time, and
# the resolved tiles are baked into the jit-compiled program (they are not
# part of the jit cache key), so set them before the first call for a given
# shape; sweeps use a fresh process per grid point (bench_attention.py).
BLOCK_Q = 256
BLOCK_K = 1024

# Measurement generation: bump on ANY change that alters attention-kernel
# performance characteristics (tile defaults, precision policy, block
# layouts). tools/bench_attention.py stamps it into every timing row and
# tools/capture_all.py publishes only the highest generation present per
# sequence length — so crossover tables never mix measurements of
# different kernel code. Gen 2 = bf16-operand policy + (256, 1024) tiles +
# lane-major backward stats.
ATTN_GEN = 2

_NEG_INF = -1e30  # finite stand-in for -inf: keeps exp()/max() NaN-free


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _compiler_params():
    """Grid programs are independent (softmax state is loop-carried INSIDE a
    program, never across grid steps), so both grid axes are parallel."""
    if _interpret():
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel"),
        # the dkv kernel holds full-sequence q/do residents (double-buffered
        # across the batch grid axis); the default VMEM budget is tighter
        # than the hardware's — claim most of the 128 MiB explicitly
        vmem_limit_bytes=100 * 1024 * 1024)


def _tile(s: int, which: str, default: int) -> int:
    """Largest tile <= the configured target dividing s, subject to the
    Mosaic constraint that non-full block dims be multiples of 8 (sequence
    lengths here are powers of two times small factors, so such a divisor
    exists for every supported shape; if none does, the full sequence is
    always a legal block)."""
    raw = os.environ.get(f"DCGAN_FLASH_{which}", default)
    try:
        target = int(raw)
    except ValueError:
        raise ValueError(
            f"DCGAN_FLASH_{which}={raw!r} is not an integer") from None
    if target < 1:
        raise ValueError(f"DCGAN_FLASH_{which}={target} must be >= 1")
    if target >= s:
        return s
    for b in range(min(s, target), 7, -1):
        if s % b == 0 and b % 8 == 0:
            return b
    return s


def _blocks(s: int) -> tuple:
    return _tile(s, "TQ", BLOCK_Q), _tile(s, "TK", BLOCK_K)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, tk):
    # Precision policy (shared with ops/attention.py::full_attention):
    # matmul operands stay in the INPUT dtype — bf16 rides the MXU fast
    # path — while scores/stats/accumulator are f32 via
    # preferred_element_type; p is cast back to the operand dtype for the
    # PV matmul (the flash-attention recipe). f32 inputs take the exact
    # f32 path unchanged.
    q = q_ref[0]                                        # [TQ, d]
    mmdt = q.dtype
    tq = q.shape[0]
    dv = v_ref.shape[-1]
    n_k = k_ref.shape[1] // tk

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * tk, tk), :]
        vb = v_ref[0, pl.ds(j * tk, tk), :]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p.astype(mmdt), vb,
                                   preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((tq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((tq, 1), jnp.float32)
    acc0 = jnp.zeros((tq, dv), jnp.float32)
    m, l, acc = lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # log-sum-exp per row — the single vector the backward needs to
    # reconstruct p tiles without storing them. Kept [S, 1] (not [S]):
    # Mosaic requires block last-two dims (8, 128)-divisible or full, which
    # a trailing singleton satisfies and a flat [B, S] block cannot.
    lse_ref[0] = m + jnp.log(l)


def _fwd_impl(q, k, v, scale):
    B, S, dk = q.shape
    dv = v.shape[-1]
    tq, tk = _blocks(S)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, tk=tk),
        grid=(B, S // tq),
        in_specs=[pl.BlockSpec((1, tq, dk), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((1, S, dk), lambda b, i: (b, 0, 0)),
                  pl.BlockSpec((1, S, dv), lambda b, i: (b, 0, 0))],
        out_specs=(pl.BlockSpec((1, tq, dv), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, tq, 1), lambda b, i: (b, i, 0))),
        out_shape=(jax.ShapeDtypeStruct((B, S, dv), jnp.float32),
                   jax.ShapeDtypeStruct((B, S, 1), jnp.float32)),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               scale, tk):
    # same operand-dtype / f32-accumulation policy as the forward; the
    # cotangent do arrives pre-cast to the operand dtype (_bwd_impl)
    q = q_ref[0]
    mmdt = q.dtype
    do = do_ref[0]
    lse = lse_ref[0]                                     # [TQ, 1]
    delta = delta_ref[0]                                 # [TQ, 1]
    tq, dk = q.shape
    n_k = k_ref.shape[1] // tk

    def body(j, dq):
        kb = k_ref[0, pl.ds(j * tk, tk), :]
        vb = v_ref[0, pl.ds(j * tk, tk), :]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)                             # [TQ, TK]
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jnp.dot(ds.astype(mmdt), kb,
                            preferred_element_type=jnp.float32) * scale

    dq = lax.fori_loop(0, n_k, body, jnp.zeros((tq, dk), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, tq):
    # This kernel walks ALL q-tiles per program, so q/do/lse/delta enter as
    # full-sequence residents. lse/delta arrive packed [1, 1, S] (sequence
    # on the LANE axis — a [S, 1] layout would lane-pad 128x and scale
    # VMEM residency with S, which walled compilation at large S/batch);
    # do arrives pre-cast to the operand dtype by _bwd_impl.
    kb = k_ref[0]                                        # [TK, dk]
    vb = v_ref[0]                                        # [TK, dv]
    mmdt = kb.dtype
    tk, dkd = kb.shape
    dvd = vb.shape[-1]
    n_q = q_ref.shape[1] // tq

    def body(i, carry):
        dk_acc, dv_acc = carry
        q = q_ref[0, pl.ds(i * tq, tq), :]
        do = do_ref[0, pl.ds(i * tq, tq), :]
        lse = lse_ref[0, 0, pl.ds(i * tq, tq)][:, None]  # [TQ, 1]
        delta = delta_ref[0, 0, pl.ds(i * tq, tq)][:, None]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)                             # [TQ, TK]
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                            # [TQ, TK]
        dk_acc = dk_acc + jax.lax.dot_general(
            ds.astype(mmdt), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        dv_acc = dv_acc + jax.lax.dot_general(
            p.astype(mmdt), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    dk_acc, dv_acc = lax.fori_loop(
        0, n_q, body, (jnp.zeros((tk, dkd), jnp.float32),
                       jnp.zeros((tk, dvd), jnp.float32)))
    dk_ref[0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


def _bwd_stats(q, out, lse, g):
    """The hop-invariant backward inputs, computed once per backward pass
    (the ring backward reuses them across every hop):

    - delta_i = rowsum(dO_i * O_i) — the softmax-jacobian correction term;
      one fused elementwise reduction, XLA handles it. [B, S, 1] like lse.
    - do: the f32 cotangent cast to the matmul operand dtype ONCE — under
      bf16 it halves do's HBM traffic and its full-array VMEM residency in
      the dkv kernel.
    - lse_r/delta_r: lane-major packing for the two per-row stats — the
      dkv kernel holds them full-sequence, and a [S, 1] block lane-pads
      128x (8 MiB at S=16384 where 64 KiB is the data); [1, S] keeps S on
      the lane axis.
    """
    B, S, _ = q.shape
    delta = jnp.sum(g.astype(jnp.float32) * out, axis=-1, keepdims=True)
    do = g.astype(q.dtype)
    return do, delta, lse.reshape(B, 1, S), delta.reshape(B, 1, S)


def _bwd_impl(scale, res, g):
    q, k, v, out, lse = res
    do, delta, lse_r, delta_r = _bwd_stats(q, out, lse, g)
    return _bwd_core(scale, q, k, v, do, lse, delta, lse_r, delta_r)


def _bwd_core(scale, q, k, v, do, lse, delta, lse_r, delta_r,
              grad_dtype=None):
    """The two backward pallas_calls. grad_dtype overrides the gradient
    output dtype (the ring backward asks for f32 so per-hop contributions
    are not rounded to bf16 before the cross-hop accumulation)."""
    B, S, dk = q.shape
    dv = v.shape[-1]
    tq, tk = _blocks(S)
    dq_dt = grad_dtype or q.dtype
    dk_dt = grad_dtype or k.dtype
    dv_dt = grad_dtype or v.dtype

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, tk=tk),
        grid=(B, S // tq),
        in_specs=[pl.BlockSpec((1, tq, dk), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((1, S, dk), lambda b, i: (b, 0, 0)),
                  pl.BlockSpec((1, S, dv), lambda b, i: (b, 0, 0)),
                  pl.BlockSpec((1, tq, dv), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((1, tq, 1), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((1, tq, 1), lambda b, i: (b, i, 0))],
        out_specs=pl.BlockSpec((1, tq, dk), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, S, dk), dq_dt),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dk_arr, dv_arr = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, tq=tq),
        grid=(B, S // tk),
        in_specs=[pl.BlockSpec((1, S, dk), lambda b, j: (b, 0, 0)),
                  pl.BlockSpec((1, tk, dk), lambda b, j: (b, j, 0)),
                  pl.BlockSpec((1, tk, dv), lambda b, j: (b, j, 0)),
                  pl.BlockSpec((1, S, dv), lambda b, j: (b, 0, 0)),
                  pl.BlockSpec((1, 1, S), lambda b, j: (b, 0, 0)),
                  pl.BlockSpec((1, 1, S), lambda b, j: (b, 0, 0))],
        out_specs=(pl.BlockSpec((1, tk, dk), lambda b, j: (b, j, 0)),
                   pl.BlockSpec((1, tk, dv), lambda b, j: (b, j, 0))),
        out_shape=(jax.ShapeDtypeStruct((B, S, dk), dk_dt),
                   jax.ShapeDtypeStruct((B, S, dv), dv_dt)),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v, do, lse_r, delta_r)
    return dq, dk_arr, dv_arr


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    scale: float) -> jax.Array:
    """softmax(q k^T * scale) v over [B, S, d] blocks without ever
    materializing the [S, S] score matrix in HBM. Returns float32 (matching
    ops/attention.py::full_attention's accumulation contract)."""
    out, _ = _fwd_impl(q, k, v, scale)
    return out


def _flash_vjp_fwd(q, k, v, scale):
    out, lse = _fwd_impl(q, k, v, scale)
    return out, (q, k, v, out, lse)


flash_attention.defvjp(_flash_vjp_fwd, _bwd_impl)


# ---------------------------------------------------------------------------
# ring x flash composition: sequence-parallel attention whose per-hop fold
# runs the flash kernels — for the regime where each device's S_local block
# itself outgrows what a dense [S_local, S_local] fold should materialize.
# ---------------------------------------------------------------------------

def ring_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         scale: float, axis_name: str,
                         n_shards: int) -> jax.Array:
    """Exact attention over a sequence sharded along `axis_name`, with every
    per-block fold running the flash kernels instead of a dense
    [S_local, S_local] einsum.

    Same contract as ops/attention.py::ring_attention (q/k/v [B, S_local, d]
    per device, n_shards-1 ppermute hops, f32 result), but the hop fold is
    `_fwd_impl` — each block contributes a normalized partial (out_b, lse_b)
    and partials merge associatively: lse = logaddexp(lse_a, lse_b),
    out = out_a*exp(lse_a-lse) + out_b*exp(lse_b-lse). The backward
    re-rotates (k, v) around the ring and reuses `_bwd_impl` per hop with
    the GLOBAL lse (p = exp(s - lse_global) gives each block's true global
    probabilities), accumulating dq locally while (dk, dv) ride the ring
    with their blocks and land home after the full cycle.
    """
    if n_shards == 1:
        return flash_attention(q, k, v, scale)
    return _ring_flash(q, k, v, scale, axis_name, n_shards)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, scale, axis_name, n_shards):
    out, _ = _ring_flash_fwd_pass(q, k, v, scale, axis_name, n_shards)
    return out


def _ring_flash_fwd_pass(q, k, v, scale, axis_name, n_shards):
    fwd = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    # resident block first (no hop result is discarded), then n-1 rotations
    out, lse = _fwd_impl(q, k, v, scale)

    def hop(carry, _):
        k_blk, v_blk, out, lse = carry
        k_blk = lax.ppermute(k_blk, axis_name, perm=fwd)
        v_blk = lax.ppermute(v_blk, axis_name, perm=fwd)
        out_b, lse_b = _fwd_impl(q, k_blk, v_blk, scale)
        lse_new = jnp.logaddexp(lse, lse_b)
        out = (out * jnp.exp(lse - lse_new)
               + out_b * jnp.exp(lse_b - lse_new))
        return (k_blk, v_blk, out, lse_new), None

    (_, _, out, lse), _ = lax.scan(
        hop, (k, v, out, lse), None, length=n_shards - 1)
    return out, lse


def _ring_flash_vjp_fwd(q, k, v, scale, axis_name, n_shards):
    out, lse = _ring_flash_fwd_pass(q, k, v, scale, axis_name, n_shards)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(scale, axis_name, n_shards, res, g):
    q, k, v, out, lse = res
    fwd = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    # hop-invariant backward inputs computed ONCE (delta, the operand-dtype
    # cotangent, and the lane-major stat packings) — only the two pallas
    # kernels re-run per hop
    do, delta, lse_r, delta_r = _bwd_stats(q, out, lse, g)

    def hop(carry, _):
        # (k, v) and their accumulated gradients travel TOGETHER: each
        # device adds its contribution to the passing block, and after the
        # full n_shards-rotation cycle every (dk, dv) sits on the block's
        # home device, complete. dq accumulates locally. Per-hop gradient
        # terms come out of the kernels ALREADY f32 (grad_dtype) so the
        # cross-hop accumulation never rounds through bf16.
        k_blk, v_blk, dk_c, dv_c, dq = carry
        dq_h, dk_h, dv_h = _bwd_core(
            scale, q, k_blk, v_blk, do, lse, delta, lse_r, delta_r,
            grad_dtype=jnp.float32)
        dq = dq + dq_h
        dk_c = dk_c + dk_h
        dv_c = dv_c + dv_h
        k_blk = lax.ppermute(k_blk, axis_name, perm=fwd)
        v_blk = lax.ppermute(v_blk, axis_name, perm=fwd)
        dk_c = lax.ppermute(dk_c, axis_name, perm=fwd)
        dv_c = lax.ppermute(dv_c, axis_name, perm=fwd)
        return (k_blk, v_blk, dk_c, dv_c, dq), None

    zeros = (jnp.zeros(k.shape, jnp.float32),
             jnp.zeros(v.shape, jnp.float32))
    (_, _, dk_c, dv_c, dq), _ = lax.scan(
        hop, (k, v) + zeros + (jnp.zeros(q.shape, jnp.float32),),
        None, length=n_shards)
    # after n rotations the blocks (and their grads) are home again
    return (dq.astype(q.dtype), dk_c.astype(k.dtype),
            dv_c.astype(v.dtype))


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)
