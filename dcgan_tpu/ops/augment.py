"""Differentiable augmentation for GAN training (DiffAugment, Zhao et al.
2020, arXiv:2006.10738).

Small datasets let the discriminator memorize; DiffAugment augments EVERY
input the discriminator sees — real and generated, D-step and G-step, each
with an independently sampled random transform — inside the compiled step.
Because the ops are differentiable, generator gradients flow through the
augmentation — the property that separates this from ordinary input
augmentation (which would let G learn the augmented distribution).

Policies (comma-separated in TrainConfig.diffaug): the paper's three.
- "color": random brightness (±0.5), saturation (×U[0,2]), contrast
  (×U[0.5,1.5]) per example;
- "translation": shift by U[-1/8, 1/8] of the image size per example,
  zero-padded (implemented as a gather on a padded canvas — static shapes,
  no data-dependent control flow);
- "cutout": zero a random half-size square per example (mask multiply).

All randomness is key-driven: every D input batch (real and fake, D-step
and G-step) gets an independently sampled transform, matching the paper's
implementation. Everything is elementwise/gather work that XLA fuses — no
host round trips, no shape dynamism, vmap-free batch handling via broadcast
arithmetic.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

POLICIES = ("color", "translation", "cutout")


def parse_policy(spec: str) -> Sequence[str]:
    """\"color,translation\" -> validated tuple; \"\" -> ()."""
    if not spec:
        return ()
    parts = tuple(p.strip() for p in spec.split(",") if p.strip())
    for p in parts:
        if p not in POLICIES:
            raise ValueError(
                f"unknown diffaug policy {p!r}; available: {POLICIES}")
    return parts


def _rand_color(x: jax.Array, key) -> jax.Array:
    kb, ks, kc = jax.random.split(key, 3)
    B = x.shape[0]
    shp = (B, 1, 1, 1)
    # brightness: x + U(-0.5, 0.5)
    x = x + jax.random.uniform(kb, shp, dtype=x.dtype, minval=-0.5,
                               maxval=0.5)
    # saturation: (x - mean_c) * U(0, 2) + mean_c
    mean_c = jnp.mean(x, axis=-1, keepdims=True)
    x = (x - mean_c) * jax.random.uniform(ks, shp, dtype=x.dtype,
                                          minval=0.0, maxval=2.0) + mean_c
    # contrast: (x - mean_all) * U(0.5, 1.5) + mean_all
    mean_all = jnp.mean(x, axis=(1, 2, 3), keepdims=True)
    x = (x - mean_all) * jax.random.uniform(kc, shp, dtype=x.dtype,
                                            minval=0.5, maxval=1.5) + mean_all
    return x


def _rand_translation(x: jax.Array, key) -> jax.Array:
    B, H, W, C = x.shape
    ky, kx = jax.random.split(key)
    max_y, max_x = H // 8, W // 8
    ty = jax.random.randint(ky, (B,), -max_y, max_y + 1)
    tx = jax.random.randint(kx, (B,), -max_x, max_x + 1)
    # zero-pad then gather shifted windows — static shapes throughout
    pad = jnp.pad(x, ((0, 0), (max_y, max_y), (max_x, max_x), (0, 0)))
    rows = (jnp.arange(H)[None, :] + max_y - ty[:, None])      # [B, H]
    cols = (jnp.arange(W)[None, :] + max_x - tx[:, None])      # [B, W]
    batch = jnp.arange(B)[:, None, None]
    return pad[batch, rows[:, :, None], cols[:, None, :]]      # [B, H, W, C]


def _rand_cutout(x: jax.Array, key) -> jax.Array:
    B, H, W, C = x.shape
    ky, kx = jax.random.split(key)
    ch, cw = H // 2, W // 2
    # top-left corner of the hole, allowed to hang off the border like the
    # paper's implementation (offset range [0, size + hole) around the edge)
    oy = jax.random.randint(ky, (B, 1, 1), 0, H + (1 - ch % 2)) - ch // 2
    ox = jax.random.randint(kx, (B, 1, 1), 0, W + (1 - cw % 2)) - cw // 2
    yy = jnp.arange(H)[None, :, None]
    xx = jnp.arange(W)[None, None, :]
    inside = ((yy >= oy) & (yy < oy + ch) & (xx >= ox) & (xx < ox + cw))
    return x * (1.0 - inside[..., None].astype(x.dtype))


_FNS = {"color": _rand_color, "translation": _rand_translation,
        "cutout": _rand_cutout}


def diff_augment(x: jax.Array, key, policy: Sequence[str]) -> jax.Array:
    """Apply the policy chain to [B, H, W, C] images (same key -> same
    augmentation; callers draw a fresh key per D input batch)."""
    for i, name in enumerate(policy):
        x = _FNS[name](x, jax.random.fold_in(key, i))
    return x
