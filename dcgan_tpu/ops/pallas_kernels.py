"""Pallas TPU kernels for the training step's hot elementwise/reduction ops.

The MXU work (conv / conv-transpose / matmul) stays with XLA — it already
tiles those optimally. What Pallas buys here is the HBM-bandwidth-bound tail
around BatchNorm, the op the reference applies after nearly every conv
(distriubted_model.py:93-121): with BN + activation fused into two single-pass
kernels, each activation tensor crosses HBM once per direction instead of
once per op.

- `channel_moments(x)`: one pass producing per-channel (mean, mean(x^2)) — the
  batch-statistics reduction of BN's train path (the reference's
  tf.nn.moments, distriubted_model.py:36-39). Accumulates in float32 across a
  row-block grid (sequential on TPU, so in-place accumulation is safe).
- `scale_shift_act(x, scale, shift, act)`: the entire BN epilogue
  y = act(x * scale + shift) as one elementwise pass, with a custom VJP whose
  backward is itself a single Pallas pass producing dx and the per-channel
  dscale/dshift reductions together.

Both degrade to `interpret=True` off-TPU, so the same code path is exercised
by the CPU test mesh. Models opt in via ModelConfig.use_pallas; the jnp path
remains the default for a measured reason: on this workload XLA's own
elementwise fusion already saturates HBM — DCGAN-64 batch-64 on a v5e chip
measures ~19.8k img/s unfused vs ~16.3k fused (readback-synced, bench.py),
so the kernels are a capability (and the pattern for ops XLA can't fuse),
not a default. GSPMD cannot repartition an opaque kernel call, so on
multi-device meshes the kernels run per data-shard inside a shard_map — the
gspmd backend nests one around each fused BN call
(ops/norm.py::_pallas_shard_moments, VERDICT r1 #5), and the shard_map
backend's whole step already is one.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from dcgan_tpu.ops.activations import ACTS, LEAK
from dcgan_tpu.ops.activations import act_fwd as _act_fwd
from dcgan_tpu.ops.activations import act_grad as _act_grad


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _row_tile(n: int) -> int:
    """Largest row-block <= 256 that divides n (shapes here are powers of
    two; a divisor always exists, so no ragged masking is needed)."""
    tile = min(n, 256)
    while n % tile:
        tile -= 1
    return tile


# ---------------------------------------------------------------------------
# channel_moments: [N, C] -> (mean [C], mean_sq [C])
# ---------------------------------------------------------------------------

def _moments_kernel(x_ref, sum_ref, sumsq_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sum_ref[:] = jnp.zeros_like(sum_ref)
        sumsq_ref[:] = jnp.zeros_like(sumsq_ref)

    xf = x_ref[:].astype(jnp.float32)
    sum_ref[:] += jnp.sum(xf, axis=0, keepdims=True)
    sumsq_ref[:] += jnp.sum(xf * xf, axis=0, keepdims=True)


def _moments_fwd_impl(x2d: jax.Array) -> Tuple[jax.Array, jax.Array]:
    n, c = x2d.shape
    tile = _row_tile(n)
    acc_spec = pl.BlockSpec((1, c), lambda i: (0, 0))
    sums, sumsqs = pl.pallas_call(
        _moments_kernel,
        grid=(n // tile,),
        in_specs=[pl.BlockSpec((tile, c), lambda i: (i, 0))],
        out_specs=(acc_spec, acc_spec),
        out_shape=(jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)),
        interpret=_interpret(),
    )(x2d)
    inv_n = 1.0 / n
    return sums[0] * inv_n, sumsqs[0] * inv_n


@jax.custom_vjp
def channel_moments(x2d: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-channel (E[x], E[x^2]) over axis 0 of [N, C], in one HBM pass."""
    return _moments_fwd_impl(x2d)


def _moments_vjp_fwd(x2d):
    return _moments_fwd_impl(x2d), x2d


def _moments_vjp_bwd(x2d, g):
    # d mean/dx = 1/N ; d mean_sq/dx = 2x/N — a broadcastwise epilogue XLA
    # fuses into the surrounding backward graph; no kernel needed.
    g_mean, g_msq = g
    n = x2d.shape[0]
    dx = (g_mean[None, :] + 2.0 * x2d.astype(jnp.float32) * g_msq[None, :]) / n
    return (dx.astype(x2d.dtype),)


channel_moments.defvjp(_moments_vjp_fwd, _moments_vjp_bwd)


# ---------------------------------------------------------------------------
# scale_shift_act: y = act(x * scale + shift), per-channel scale/shift
# ---------------------------------------------------------------------------

def _ssa_fwd_kernel(x_ref, scale_ref, shift_ref, y_ref, *, act, leak):
    xf = x_ref[:].astype(jnp.float32)
    u = xf * scale_ref[:] + shift_ref[:]
    y_ref[:] = _act_fwd(u, act, leak).astype(y_ref.dtype)


def _ssa_bwd_kernel(x_ref, scale_ref, shift_ref, g_ref,
                    dx_ref, dscale_ref, dshift_ref, *, act, leak):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dscale_ref[:] = jnp.zeros_like(dscale_ref)
        dshift_ref[:] = jnp.zeros_like(dshift_ref)

    xf = x_ref[:].astype(jnp.float32)
    u = xf * scale_ref[:] + shift_ref[:]
    du = g_ref[:].astype(jnp.float32) * _act_grad(u, act, leak)
    dx_ref[:] = (du * scale_ref[:]).astype(dx_ref.dtype)
    dscale_ref[:] += jnp.sum(du * xf, axis=0, keepdims=True)
    dshift_ref[:] += jnp.sum(du, axis=0, keepdims=True)


def _ssa_impl(x2d, scale, shift, act, leak):
    # Validated here — shared by the primal and the custom-VJP forward — so a
    # bad act name errors under jax.grad too (the primal wrapper is bypassed
    # when differentiating) instead of silently applying identity.
    if act not in ACTS:
        raise ValueError(f"unknown act {act!r}")
    n, c = x2d.shape
    tile = _row_tile(n)
    vec_spec = pl.BlockSpec((1, c), lambda i: (0, 0))
    return pl.pallas_call(
        functools.partial(_ssa_fwd_kernel, act=act, leak=leak),
        grid=(n // tile,),
        in_specs=[pl.BlockSpec((tile, c), lambda i: (i, 0)),
                  vec_spec, vec_spec],
        out_specs=pl.BlockSpec((tile, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, c), x2d.dtype),
        interpret=_interpret(),
    )(x2d, scale.reshape(1, c).astype(jnp.float32),
      shift.reshape(1, c).astype(jnp.float32))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def scale_shift_act(x2d: jax.Array, scale: jax.Array, shift: jax.Array,
                    act: str = "none", leak: float = LEAK) -> jax.Array:
    """Fused y = act(x * scale + shift) over [N, C] with per-channel [C]
    scale/shift. act in {"none", "relu", "lrelu", "tanh"}."""
    return _ssa_impl(x2d, scale, shift, act, leak)


def _ssa_vjp_fwd(x2d, scale, shift, act, leak):
    return _ssa_impl(x2d, scale, shift, act, leak), (x2d, scale, shift)


def _ssa_vjp_bwd(act, leak, res, g):
    x2d, scale, shift = res
    n, c = x2d.shape
    tile = _row_tile(n)
    vec_spec = pl.BlockSpec((1, c), lambda i: (0, 0))
    dx, dscale, dshift = pl.pallas_call(
        functools.partial(_ssa_bwd_kernel, act=act, leak=leak),
        grid=(n // tile,),
        in_specs=[pl.BlockSpec((tile, c), lambda i: (i, 0)),
                  vec_spec, vec_spec,
                  pl.BlockSpec((tile, c), lambda i: (i, 0))],
        out_specs=(pl.BlockSpec((tile, c), lambda i: (i, 0)),
                   vec_spec, vec_spec),
        out_shape=(jax.ShapeDtypeStruct((n, c), x2d.dtype),
                   jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)),
        interpret=_interpret(),
    )(x2d, scale.reshape(1, c).astype(jnp.float32),
      shift.reshape(1, c).astype(jnp.float32), g)
    return (dx, dscale[0].astype(scale.dtype), dshift[0].astype(shift.dtype))


scale_shift_act.defvjp(_ssa_vjp_fwd, _ssa_vjp_bwd)


# ---------------------------------------------------------------------------
# Fused BN + activation built from the two kernels
# ---------------------------------------------------------------------------

def fused_bn_act(x: jax.Array, gamma: jax.Array, beta: jax.Array,
                 mean: jax.Array, var: jax.Array, *, eps: float,
                 act: str, leak: float = LEAK) -> jax.Array:
    """y = act((x - mean) * rsqrt(var + eps) * gamma + beta) for NHWC (or
    [N, C]) `x`, as one fused elementwise pass. mean/var may be batch moments
    (train) or running statistics (inference) — gradients flow through them
    either way via the scale/shift vectors."""
    c = x.shape[-1]
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + jnp.float32(eps))
    scale = gamma.astype(jnp.float32) * inv
    shift = beta.astype(jnp.float32) - mean.astype(jnp.float32) * scale
    y2d = scale_shift_act(x.reshape(-1, c), scale, shift, act, leak)
    return y2d.reshape(x.shape)
