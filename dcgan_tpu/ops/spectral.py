"""Spectral normalization (Miyato et al. 2018, arXiv:1802.05957) as explicit
functional state.

The reference has no Lipschitz control at all — its discriminator is the raw
DCGAN stack (distriubted_model.py:114-128). Spectral norm is the modern
stabilizer the SAGAN family (arXiv:1805.08318) is defined with: every weight
is divided by its largest singular value, estimated by one power-iteration
step per training step. Torch/TF keep the power-iteration vector `u` as
hidden mutable module state; here it is an explicit state leaf threaded
through apply exactly like BatchNorm's running moments (ops/norm.py) — no
hidden side effects, checkpointed with everything else, replicated under the
mesh (it is a tiny per-layer vector).

Gradient convention (matching the paper and the torch implementation): the
power-iteration vectors are stop-gradiented, but sigma = v^T W u keeps W
live, so d(W/sigma)/dW includes the -W·(dsigma/dW)/sigma^2 term.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _l2n(x: jax.Array, eps: float) -> jax.Array:
    return x / (jnp.linalg.norm(x) + eps)


def spectral_u_init(key, out_dim: int, *, dtype=jnp.float32) -> jax.Array:
    """Unit-norm power-iteration start vector, one per normalized weight."""
    return _l2n(jax.random.normal(key, (out_dim,), jnp.float32),
                1e-12).astype(dtype)


def spectral_normalize(w: jax.Array, u: jax.Array, *, train: bool,
                       n_iter: int = 1, eps: float = 1e-12
                       ) -> Tuple[jax.Array, jax.Array]:
    """Returns (w / sigma_max_estimate, u_new).

    `w` is any-rank weight; its last axis is the output dim ([in, out]
    linear, [h, w, in, out] conv — both reshape to [N, out] for the power
    iteration, torch's convention transposed). Both modes run `n_iter`
    power-iteration steps from the stored u to estimate sigma; train=True
    persists the advanced u into the returned state, train=False returns
    the stored u unchanged (the BN train/eval contract — repeated eval
    applies are idempotent).
    """
    out_dim = w.shape[-1]
    w2d = w.astype(jnp.float32).reshape(-1, out_dim)     # [N, out]
    w_sg = lax.stop_gradient(w2d)
    u_c = lax.stop_gradient(u.astype(jnp.float32))

    def one_iter(u_i, _):
        v_i = _l2n(w_sg @ u_i, eps)          # [N]
        u_i = _l2n(w_sg.T @ v_i, eps)        # [out]
        return u_i, None

    u_new, _ = lax.scan(one_iter, u_c, None, length=n_iter)
    v = _l2n(w_sg @ u_new, eps)
    u_new = lax.stop_gradient(u_new)
    v = lax.stop_gradient(v)
    # sigma through the LIVE weight: the normalization's own gradient term
    sigma = v @ (w2d @ u_new)
    w_sn = (w2d / sigma).reshape(w.shape).astype(w.dtype)
    return w_sn, (u_new if train else
                  lax.stop_gradient(u.astype(jnp.float32))).astype(u.dtype)
