"""Core ops: pure init/apply functions over pytrees, compiled by XLA onto the MXU.

TPU-native re-expression of the reference's op layer
(distriubted_model.py:156-213 linear/conv2d/deconv2d/lrelu and :15-52 batch_norm).
"""

from dcgan_tpu.ops.layers import (  # noqa: F401
    conv2d_apply,
    conv2d_init,
    deconv2d_apply,
    deconv2d_init,
    linear_apply,
    linear_init,
    lrelu,
)
from dcgan_tpu.ops.norm import batch_norm_apply, batch_norm_init  # noqa: F401
