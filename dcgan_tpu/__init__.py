"""dcgan_tpu — a TPU-native (JAX/XLA/pjit) framework for distributed GAN training.

Built from scratch with the capabilities of the reference
`tiantengfei/Distributed-tensorflow-for-DCGAN` (an async parameter-server DCGAN
trainer, see /root/repo/SURVEY.md), re-designed TPU-first:

- pure-functional ops/models (init/apply over pytrees) compiled by XLA onto the MXU,
- synchronous data parallelism via `jax.sharding.Mesh` + `jit` with `NamedSharding`
  (gradient all-reduce and cross-replica BatchNorm ride ICI collectives inserted by
  GSPMD) instead of the reference's gRPC parameter-server pulls/pushes
  (reference: image_train.py:55-67, distriubted_model.py:70),
- a host-side sharded TFRecord loader with device prefetch (native C++ reader)
  instead of queue runners (reference: image_input.py),
- functional BatchNorm EMA state instead of hidden ExponentialMovingAverage
  side-state (reference: distriubted_model.py:15-52),
- checkpoint/resume, metric writing, and fixed-z sample grids as first-class
  subsystems (reference: image_train.py:103-194).
"""

__version__ = "0.1.0"

from dcgan_tpu.config import ModelConfig, TrainConfig  # noqa: F401
