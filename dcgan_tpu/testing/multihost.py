"""Shared bring-up for multi-process CPU harnesses (ISSUE 4).

Every harness that forms a real 2+ process jax.distributed job over
localhost gRPC — tests/multihost_worker.py, tests/test_multihost.py's
launcher, and tools/chaos_drill.py's --multihost driver — needs the same
three version-sensitive pieces; keeping them here means a jax upgrade that
changes any of them is a one-site edit instead of a silent third-copy
drift:

- the CPU platform pin (the ambient TPU plugin force-selects itself),
- `jax_cpu_collectives_implementation=gloo` — on this container's jax
  0.4.37 a cross-process CPU computation without it dies with
  "Multiprocess computations aren't implemented on the CPU backend"
  (newer jax selects CPU collectives automatically; the try/except keeps
  the call portable),
- the partitionable threefry flag the test env standardizes on.

Callers must still set XLA_FLAGS/JAX_PLATFORMS env *before* the first
`import jax` in their process (the device-count flag is read at backend
init) — this module deliberately takes the already-imported `jax` so it
cannot hide that ordering requirement.
"""

from __future__ import annotations

import socket


def configure_cpu_multiprocess(jax) -> None:
    """Apply the CPU multi-process config trio to an imported jax."""
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # newer jax selects CPU collectives automatically


def free_port() -> int:
    """An OS-assigned localhost port for the coordinator address."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
