"""Deterministic fault injection: the proof harness for the fail-operational
layer (ISSUE 3 tentpole).

A `FaultPlan` names exactly which fault fires and when — no randomness, so
every chaos scenario is a reproducible test, not a flake generator. Plans are
selected explicitly, never ambiently: either programmatically (`set_plan`,
tests) or through the `DCGAN_CHAOS` environment variable (JSON, read once per
process — the contract tools/chaos_drill.py uses to arm one fault per
subprocess). With no plan armed every hook below is a cheap None-check.

Injection points in production code:

- `should_inject_nan(step)`  trainer's numerical-health gate: the gate's view
  of the step metrics is poisoned ONCE at `nan_at_step` — exercises the
  `--nan_policy rollback` restore path without needing real divergence.
- `maybe_io_error(tag)`      inside utils/retry.retry_io attempts: raises one
  OSError when `io_error_once` equals the site's tag ("ckpt-manifest",
  "services") — exercises the bounded-retry path.
- `should_crash_worker(n)`   train/services.py worker: raises before
  executing the `services_worker_crash`-th task (1-based) — exercises the
  dispatch-thread error surfacing contract.
- `maybe_self_signal(step)`  trainer step boundary: delivers SIGTERM to this
  process once at `sigterm_at_step` — exercises the coordinated preemption
  stop without a racy cross-process kill().
- `maybe_hang(step)`         inside the trainer's watchdog-guarded dispatch
  section: sleeps `hang_secs` once at `hang_at_step`, simulating a process
  that never joins the next collective — the other processes block in a
  real allgather/allreduce and the hung-collective watchdog must trip on
  every process.
- `should_kill_replica(r, n)` / `maybe_replica_hang(r, n)` /
  `maybe_replica_slow_beat(r, n)`  serve/worker.py per-dispatch hooks for
  the serving fleet (ISSUE 19): crash, wedge, or heartbeat-mute exactly
  one replica at its n-th dispatch — exercises failover routing, health
  draining, and re-admission without real device faults.
- `poll_notice(step)`        elastic/live.py's NoticePlane: returns a
  preemption-notice verdict (NOTICE_SHRINK / NOTICE_GROW) once at
  `preempt_notice_at_step` / `grow_notice_at_step` — the deterministic
  stand-in for a scheduler's advance preemption notice, driving the
  live mesh shrink/grow-back switch without a real signal or file.

Multi-process plans (ISSUE 4): when the DCGAN_CHAOS JSON object's keys are
all digit strings, it is a PER-PROCESS map `{"<pid>": {fields...}}` selected
by the `MH_PID` environment variable (the id the multihost harnesses —
tests/multihost_worker.py and tools/chaos_drill.py — already export per
subprocess; absent means "0"). A process with no entry gets no plan, so one
env value arms a fault on exactly one host of a multi-host job — the shape
every coordinated-recovery drill needs.

Disk faults (`corrupt_record`, `truncate_checkpoint`) are properties of the
bytes on disk, not of running code, so the plan only CARRIES them for the
drill's bookkeeping; the drill applies them with the helpers below
(`corrupt_tfrecord_payload`, `truncate_file`) between process launches.

One-shot semantics: each armed fault fires exactly once per process. That is
load-bearing for the rollback drill — a step-keyed NaN that re-fired on the
replayed step would burn the whole `max_rollbacks` budget on one fault.
"""

from __future__ import annotations

import dataclasses
import json
import os
import struct
from typing import Optional, Set

ENV_VAR = "DCGAN_CHAOS"


@dataclasses.dataclass
class FaultPlan:
    """One deterministic fault schedule. Zero/empty fields are unarmed."""

    nan_at_step: int = 0           # >0: poison the NaN gate's metrics once
    corrupt_record: int = 0        # drill bookkeeping: which record index the
                                   # drill corrupts on disk (helpers below)
    truncate_checkpoint: int = 0   # drill bookkeeping: which checkpoint step
                                   # the drill truncates on disk
    io_error_once: str = ""        # site tag whose next retry_io attempt
                                   # raises one OSError
    services_worker_crash: int = 0  # >0: services worker raises before its
                                    # n-th task (1-based)
    sigterm_at_step: int = 0       # >0: deliver SIGTERM to this process at
                                   # that trainer step boundary (once)
    hang_at_step: int = 0          # >0: sleep hang_secs at that step
                                   # boundary (once) — a peer that never
                                   # joins the next collective
    hang_secs: float = 3600.0      # how long hang_at_step sleeps (far past
                                   # any sane collective_timeout_secs)
    preempt_notice_at_step: int = 0  # >0: raise a preemption notice (live
                                     # mesh SHRINK) at that step boundary
                                     # (once) — consumed by poll_notice
    grow_notice_at_step: int = 0     # >0: raise a capacity-restored notice
                                     # (live mesh GROW-back) at that step
                                     # boundary (once)
    # serving-fleet faults (ISSUE 19): target ONE replica of an
    # in-process ServeFleet. `fault_replica` names the replica index the
    # replica_* fields apply to (arming comes from the *_at_dispatch
    # fields being >0, so replica 0 is targetable); dispatch indices are
    # 1-based counts of that replica's device dispatches.
    fault_replica: int = 0           # replica index the replica_* faults
                                     # target
    replica_kill_at_dispatch: int = 0   # >0: the replica's worker raises
                                        # before its n-th dispatch — a
                                        # replica crash mid-trace
    replica_hang_at_dispatch: int = 0   # >0: the replica's worker sleeps
                                        # hang_secs before its n-th
                                        # dispatch — a wedged device that
                                        # stops heartbeating
    replica_slow_beat_at_dispatch: int = 0  # >0: suppress the replica's
                                            # heartbeat for slow_beat_secs
                                            # starting at its n-th
                                            # dispatch — still serving,
                                            # but looks dead to the
                                            # router's health monitor
    slow_beat_secs: float = 2.0      # how long replica_slow_beat mutes
                                     # the heartbeat
    _fired: Set[str] = dataclasses.field(default_factory=set)

    def fire_once(self, name: str) -> bool:
        """True exactly once per armed fault name."""
        if name in self._fired:
            return False
        self._fired.add(name)
        return True


_plan: Optional[FaultPlan] = None
_plan_loaded = False


def plan_from_env(env=None) -> Optional[FaultPlan]:
    """Parse DCGAN_CHAOS, or None.

    Flat JSON object of FaultPlan fields = one plan for this process.
    All-digit keys = per-process map selected by MH_PID (no entry for this
    process = no plan armed here).
    """
    environ = env if env is not None else os.environ
    raw = environ.get(ENV_VAR, "")
    if not raw:
        return None
    d = json.loads(raw)
    if d and all(isinstance(k, str) and k.isdigit() for k in d):
        pid = environ.get("MH_PID", "0")
        d = d.get(pid)
        if d is None:
            return None
        if not isinstance(d, dict):
            raise ValueError(
                f"per-process {ENV_VAR} entry for pid {pid} must be an "
                f"object of FaultPlan fields, got {d!r}")
    fields = {f.name for f in dataclasses.fields(FaultPlan)
              if not f.name.startswith("_")}
    unknown = sorted(set(d) - fields)
    if unknown:
        raise ValueError(f"unknown {ENV_VAR} fault(s) {unknown}; "
                         f"known: {sorted(fields)}")
    return FaultPlan(**d)


def active_plan() -> Optional[FaultPlan]:
    """The process's armed plan: set_plan() wins, else DCGAN_CHAOS (parsed
    once), else None."""
    global _plan, _plan_loaded
    if not _plan_loaded:
        _plan = plan_from_env()
        _plan_loaded = True
    return _plan


def set_plan(plan: Optional[FaultPlan]) -> None:
    """Arm (or with None, disarm) a plan programmatically — tests."""
    global _plan, _plan_loaded
    _plan = plan
    _plan_loaded = True


def reset() -> None:
    """Forget any armed plan AND the env cache (next access re-reads env)."""
    global _plan, _plan_loaded
    _plan = None
    _plan_loaded = False


# -- hooks (called from production code; all no-ops without a plan) ----------

def should_inject_nan(step: int) -> bool:
    plan = active_plan()
    return bool(plan and plan.nan_at_step
                and step == plan.nan_at_step
                and plan.fire_once("nan_at_step"))


def maybe_io_error(tag: str) -> None:
    plan = active_plan()
    if plan and plan.io_error_once and plan.io_error_once == tag \
            and plan.fire_once("io_error_once"):
        raise OSError(f"chaos: injected transient IO error at {tag!r}")


def should_crash_worker(task_index: int) -> bool:
    """`task_index` is 1-based: the n-th task the worker picks up."""
    plan = active_plan()
    return bool(plan and plan.services_worker_crash
                and task_index >= plan.services_worker_crash
                and plan.fire_once("services_worker_crash"))


def maybe_self_signal(step: int) -> None:
    """Deliver SIGTERM to this process once at `sigterm_at_step` — the
    deterministic stand-in for a preemption notice landing on one host."""
    import signal

    plan = active_plan()
    if plan and plan.sigterm_at_step and step == plan.sigterm_at_step \
            and plan.fire_once("sigterm_at_step"):
        os.kill(os.getpid(), signal.SIGTERM)


def maybe_hang(step: int) -> None:
    """Sleep `hang_secs` once at `hang_at_step`: this process goes silent
    inside the trainer's watchdog-guarded section while its peers block in
    a real collective it never joins."""
    import time

    plan = active_plan()
    if plan and plan.hang_at_step and step == plan.hang_at_step \
            and plan.fire_once("hang_at_step"):
        print(f"[dcgan_tpu] chaos: hanging process for {plan.hang_secs:.0f}s "
              f"at step {step}", flush=True)
        time.sleep(plan.hang_secs)


def _replica_armed(plan: Optional[FaultPlan], replica: int,
                   field: str, dispatch_index: int) -> bool:
    """Shared predicate for the fleet hooks: the plan targets `replica`
    and the named *_at_dispatch field matches this 1-based dispatch."""
    if not plan or plan.fault_replica != replica:
        return False
    at = getattr(plan, field)
    return bool(at and dispatch_index >= at and plan.fire_once(field))


def should_kill_replica(replica: int, dispatch_index: int) -> bool:
    """True once when replica `replica` reaches its
    `replica_kill_at_dispatch`-th dispatch (1-based) — the worker raises
    and the replica poisons, exactly like a device crash mid-trace."""
    return _replica_armed(active_plan(), replica,
                          "replica_kill_at_dispatch", dispatch_index)


def maybe_replica_hang(replica: int, dispatch_index: int) -> None:
    """Sleep `hang_secs` once at replica `replica`'s
    `replica_hang_at_dispatch`-th dispatch: the worker wedges on its own
    dispatch thread, heartbeats stop, and the router's health monitor
    must drain the replica and failover its queue."""
    import time

    plan = active_plan()
    if _replica_armed(plan, replica, "replica_hang_at_dispatch",
                      dispatch_index):
        print(f"[dcgan_tpu] chaos: hanging replica {replica} for "
              f"{plan.hang_secs:.0f}s at dispatch {dispatch_index}",
              flush=True)
        time.sleep(plan.hang_secs)


def maybe_replica_slow_beat(replica: int, dispatch_index: int) -> float:
    """Seconds to suppress replica `replica`'s heartbeat, or 0.0. Fires
    once at `replica_slow_beat_at_dispatch`: the replica keeps serving
    but looks dead to the router until `slow_beat_secs` elapse — the
    false-positive/re-admission path of the health monitor."""
    plan = active_plan()
    if _replica_armed(plan, replica, "replica_slow_beat_at_dispatch",
                      dispatch_index):
        return float(plan.slow_beat_secs)
    return 0.0


#: poll_notice verdicts — match elastic/live.py's wire encoding (0 = no
#: notice) so the chaos hook slots straight into the consensus vote.
NOTICE_NONE = 0
NOTICE_GROW = 1
NOTICE_SHRINK = 2   # outranks GROW under the consensus max — when hosts
                    # disagree, losing capacity is the direction to honor


def poll_notice(step: int) -> int:
    """Local preemption-notice verdict for this step boundary: NOTICE_SHRINK
    once at `preempt_notice_at_step`, NOTICE_GROW once at
    `grow_notice_at_step`, else NOTICE_NONE. One-shot like every hook — the
    notice is an edge, not a level; the consensus collective (elastic/live)
    spreads it to every process, so re-firing on the replayed boundary would
    double-switch."""
    plan = active_plan()
    if plan and plan.preempt_notice_at_step \
            and step >= plan.preempt_notice_at_step \
            and plan.fire_once("preempt_notice_at_step"):
        return NOTICE_SHRINK
    if plan and plan.grow_notice_at_step \
            and step >= plan.grow_notice_at_step \
            and plan.fire_once("grow_notice_at_step"):
        return NOTICE_GROW
    return NOTICE_NONE


# -- disk-fault helpers (drill/tests only; never called by production) -------

def clone_checkpoint_dir(src: str, dst: str) -> str:
    """Copy a finished checkpoint directory (steps + integrity manifests +
    sharding sidecars + config.json) so independent resume arms can each
    append their own events/checkpoints without contaminating the other —
    the elastic shrink/grow drills resume ONE saved state on TWO
    topologies and diff the replays (tools/chaos_drill.py). Returns dst."""
    import shutil

    shutil.copytree(src, dst)
    return dst

def corrupt_tfrecord_payload(path: str, record_index: int = 0) -> int:
    """Flip one byte inside record `record_index`'s payload, leaving its CRC
    untouched — a CRC-verifying reader sees a data-CRC mismatch at exactly
    that record. Returns the file offset of the corrupted record."""
    with open(path, "r+b") as f:
        idx = 0
        while True:
            offset = f.tell()
            header = f.read(12)
            if len(header) < 12:
                raise ValueError(f"{path} has only {idx} record(s); cannot "
                                 f"corrupt record {record_index}")
            (length,) = struct.unpack("<Q", header[:8])
            if idx == record_index:
                f.seek(offset + 12)   # first payload byte
                b = f.read(1)
                f.seek(offset + 12)
                f.write(bytes([b[0] ^ 0xFF]))
                return offset
            f.seek(offset + 12 + length + 4)
            idx += 1


def truncate_file(path: str, drop_bytes: int = 64) -> int:
    """Chop `drop_bytes` off the end of `path` (at least one byte remains).
    Returns the new size."""
    size = os.path.getsize(path)
    new_size = max(1, size - drop_bytes)
    with open(path, "r+b") as f:
        f.truncate(new_size)
    return new_size
