"""Deterministic fault injection: the proof harness for the fail-operational
layer (ISSUE 3 tentpole).

A `FaultPlan` names exactly which fault fires and when — no randomness, so
every chaos scenario is a reproducible test, not a flake generator. Plans are
selected explicitly, never ambiently: either programmatically (`set_plan`,
tests) or through the `DCGAN_CHAOS` environment variable (JSON, read once per
process — the contract tools/chaos_drill.py uses to arm one fault per
subprocess). With no plan armed every hook below is a cheap None-check.

Injection points in production code:

- `should_inject_nan(step)`  trainer's numerical-health gate: the gate's view
  of the step metrics is poisoned ONCE at `nan_at_step` — exercises the
  `--nan_policy rollback` restore path without needing real divergence.
- `maybe_io_error(tag)`      inside utils/retry.retry_io attempts: raises one
  OSError when `io_error_once` equals the site's tag ("ckpt-manifest",
  "services") — exercises the bounded-retry path.
- `should_crash_worker(n)`   train/services.py worker: raises before
  executing the `services_worker_crash`-th task (1-based) — exercises the
  dispatch-thread error surfacing contract.

Disk faults (`corrupt_record`, `truncate_checkpoint`) are properties of the
bytes on disk, not of running code, so the plan only CARRIES them for the
drill's bookkeeping; the drill applies them with the helpers below
(`corrupt_tfrecord_payload`, `truncate_file`) between process launches.

One-shot semantics: each armed fault fires exactly once per process. That is
load-bearing for the rollback drill — a step-keyed NaN that re-fired on the
replayed step would burn the whole `max_rollbacks` budget on one fault.
"""

from __future__ import annotations

import dataclasses
import json
import os
import struct
from typing import Optional, Set

ENV_VAR = "DCGAN_CHAOS"


@dataclasses.dataclass
class FaultPlan:
    """One deterministic fault schedule. Zero/empty fields are unarmed."""

    nan_at_step: int = 0           # >0: poison the NaN gate's metrics once
    corrupt_record: int = 0        # drill bookkeeping: which record index the
                                   # drill corrupts on disk (helpers below)
    truncate_checkpoint: int = 0   # drill bookkeeping: which checkpoint step
                                   # the drill truncates on disk
    io_error_once: str = ""        # site tag whose next retry_io attempt
                                   # raises one OSError
    services_worker_crash: int = 0  # >0: services worker raises before its
                                    # n-th task (1-based)
    _fired: Set[str] = dataclasses.field(default_factory=set)

    def fire_once(self, name: str) -> bool:
        """True exactly once per armed fault name."""
        if name in self._fired:
            return False
        self._fired.add(name)
        return True


_plan: Optional[FaultPlan] = None
_plan_loaded = False


def plan_from_env(env=None) -> Optional[FaultPlan]:
    """Parse DCGAN_CHAOS (JSON object of FaultPlan fields), or None."""
    raw = (env if env is not None else os.environ).get(ENV_VAR, "")
    if not raw:
        return None
    fields = {f.name for f in dataclasses.fields(FaultPlan)
              if not f.name.startswith("_")}
    d = json.loads(raw)
    unknown = sorted(set(d) - fields)
    if unknown:
        raise ValueError(f"unknown {ENV_VAR} fault(s) {unknown}; "
                         f"known: {sorted(fields)}")
    return FaultPlan(**d)


def active_plan() -> Optional[FaultPlan]:
    """The process's armed plan: set_plan() wins, else DCGAN_CHAOS (parsed
    once), else None."""
    global _plan, _plan_loaded
    if not _plan_loaded:
        _plan = plan_from_env()
        _plan_loaded = True
    return _plan


def set_plan(plan: Optional[FaultPlan]) -> None:
    """Arm (or with None, disarm) a plan programmatically — tests."""
    global _plan, _plan_loaded
    _plan = plan
    _plan_loaded = True


def reset() -> None:
    """Forget any armed plan AND the env cache (next access re-reads env)."""
    global _plan, _plan_loaded
    _plan = None
    _plan_loaded = False


# -- hooks (called from production code; all no-ops without a plan) ----------

def should_inject_nan(step: int) -> bool:
    plan = active_plan()
    return bool(plan and plan.nan_at_step
                and step == plan.nan_at_step
                and plan.fire_once("nan_at_step"))


def maybe_io_error(tag: str) -> None:
    plan = active_plan()
    if plan and plan.io_error_once and plan.io_error_once == tag \
            and plan.fire_once("io_error_once"):
        raise OSError(f"chaos: injected transient IO error at {tag!r}")


def should_crash_worker(task_index: int) -> bool:
    """`task_index` is 1-based: the n-th task the worker picks up."""
    plan = active_plan()
    return bool(plan and plan.services_worker_crash
                and task_index >= plan.services_worker_crash
                and plan.fire_once("services_worker_crash"))


# -- disk-fault helpers (drill/tests only; never called by production) -------

def corrupt_tfrecord_payload(path: str, record_index: int = 0) -> int:
    """Flip one byte inside record `record_index`'s payload, leaving its CRC
    untouched — a CRC-verifying reader sees a data-CRC mismatch at exactly
    that record. Returns the file offset of the corrupted record."""
    with open(path, "r+b") as f:
        idx = 0
        while True:
            offset = f.tell()
            header = f.read(12)
            if len(header) < 12:
                raise ValueError(f"{path} has only {idx} record(s); cannot "
                                 f"corrupt record {record_index}")
            (length,) = struct.unpack("<Q", header[:8])
            if idx == record_index:
                f.seek(offset + 12)   # first payload byte
                b = f.read(1)
                f.seek(offset + 12)
                f.write(bytes([b[0] ^ 0xFF]))
                return offset
            f.seek(offset + 12 + length + 4)
            idx += 1


def truncate_file(path: str, drop_bytes: int = 64) -> int:
    """Chop `drop_bytes` off the end of `path` (at least one byte remains).
    Returns the new size."""
    size = os.path.getsize(path)
    new_size = max(1, size - drop_bytes)
    with open(path, "r+b") as f:
        f.truncate(new_size)
    return new_size
