"""Test-support subpackage: deterministic fault injection (chaos.py).

Production code imports from here only through narrow, default-off hooks
(`chaos.active_plan()` returns None unless a plan was explicitly selected),
so shipping the injection points costs nothing on the happy path.
"""
