"""Improved precision & recall for generative models (Kynkäänniemi et al.
2019, arXiv:1904.06991), plus density & coverage (Naeem et al. 2020,
arXiv:2002.09797) from the same k-NN radii.

FID/KID compress fidelity and diversity into one number; this family
separates them:

- precision: fraction of FAKE samples lying inside the real manifold
  (fidelity — are generated images realistic?);
- recall: fraction of REAL samples lying inside the fake manifold
  (diversity — is the whole data distribution covered?);
- density/coverage: the same questions with estimators that are robust to
  outlier samples inflating a manifold (density counts how many real
  k-NN balls contain each fake; coverage counts reals whose ball contains
  at least one fake).

The manifold is the classic k-NN estimate: a point set's manifold is the
union of balls centered on each point with radius = distance to its k-th
nearest neighbor within the set. Works on any feature embedding — here the
same pools the KID reservoir already collects (evals/kid.py), so the eval
CLI gets P&R from features it has in hand, no extra passes. Memory: the
[Nf, Nr] f32 distance matrix is materialized (400 MB at 10k reservoirs)
plus its bool membership mask (~100 MB) — peak ~600 MB; the blockwise
loops only bound per-chunk temporaries. Shrink --kid_pool on small hosts.

No counterpart in the reference (its eval was eyeballing sample grids,
SURVEY.md §4).
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def _pairwise_sq_dists(a: np.ndarray, b: np.ndarray,
                       block: int = 2048) -> np.ndarray:
    """Squared euclidean distances [len(a), len(b)], blockwise over rows."""
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    b_sq = (b ** 2).sum(axis=1)
    out = np.empty((len(a), len(b)), np.float32)
    for i in range(0, len(a), block):
        chunk = a[i:i + block]
        d = ((chunk ** 2).sum(axis=1)[:, None] + b_sq[None, :]
             - 2.0 * chunk @ b.T)
        np.maximum(d, 0.0, out=d)  # clamp fp cancellation
        out[i:i + block] = d
    return out


def _knn_radii_sq(feats: np.ndarray, k: int, block: int = 2048) -> np.ndarray:
    """Squared distance from each point to its k-th nearest OTHER point."""
    n = len(feats)
    if not 1 <= k < n:
        raise ValueError(f"k must be in [1, {n - 1}], got {k}")
    radii = np.empty((n,), np.float32)
    sq = np.asarray(feats, np.float32)
    for i in range(0, n, block):
        d = _pairwise_sq_dists(sq[i:i + block], sq, block=block)
        # self-distance sits at position i+j; exclude it from the k-NN by
        # taking the (k+1)-th smallest including self
        radii[i:i + block] = np.partition(d, k, axis=1)[:, k]
    return radii


def prdc(real_feats: np.ndarray, fake_feats: np.ndarray, *,
         k: int = 5) -> Dict[str, float]:
    """Precision, recall, density, coverage between two feature sets.

    Both sets should be uniform samples of comparable size (the KID
    reservoirs qualify). k=5 is the papers' standard setting.
    """
    real = np.asarray(real_feats, np.float32)
    fake = np.asarray(fake_feats, np.float32)
    if real.ndim != 2 or fake.ndim != 2 or real.shape[1] != fake.shape[1]:
        raise ValueError(
            f"expected [N, D] feature sets with equal D, got "
            f"{real.shape} vs {fake.shape}")

    real_r = _knn_radii_sq(real, k)              # [Nr]
    fake_r = _knn_radii_sq(fake, k)              # [Nf]
    d_fr = _pairwise_sq_dists(fake, real)        # [Nf, Nr]

    # precision: fake j inside ANY real ball
    inside_real = d_fr <= real_r[None, :]
    precision = float(inside_real.any(axis=1).mean())
    # recall: real i inside ANY fake ball — reuse d_fr transposed
    recall = float((d_fr.T <= fake_r[None, :]).any(axis=1).mean())
    # density: average count of real balls containing each fake, /k —
    # unlike precision it is not saturated by a single outlier ball
    density = float(inside_real.sum(axis=1).mean() / k)
    # coverage: fraction of real balls containing at least one fake —
    # the membership matrix is inside_real, already in hand
    coverage = float(inside_real.any(axis=0).mean())
    return {"precision": precision, "recall": recall,
            "density": density, "coverage": coverage}
