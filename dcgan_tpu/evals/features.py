"""Feature extractors for Fréchet-distance scoring.

FID canonically uses InceptionV3 pool3 activations. Inception weights are not
shippable inside this repo (and the build environment has no network egress),
so the rig is built around a pluggable `feature_fn: [B,H,W,C] in [-1,1] ->
[B,D] float32` with two backends:

- `make_random_feature_fn`: a fixed-seed, untrained strided-conv embedder
  (jitted JAX, MXU-friendly). Fréchet distances under random conv features are
  a documented surrogate that tracks true FID's ordering (random-feature FID /
  "FID-infinity"-style ablations); scores are comparable *within* a feature
  seed, which is all the north-star needs (parity between two trainers scored
  by the same rig).
- `make_npz_feature_fn`: loads user-supplied conv weights from an .npz (e.g.
  converted Inception blocks) into the same harness, so a deployment with real
  weights gets canonical features with no code change.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dcgan_tpu.ops.layers import conv2d_apply, conv2d_init, lrelu

FeatureFn = Callable[[jax.Array], jax.Array]


def _build_conv_stack(params: dict) -> FeatureFn:
    """Shared apply: strided conv tower -> per-stage global-avg-pool features,
    concatenated and projected. Multi-scale pooling makes the embedding
    sensitive to both texture (early stages) and layout (late stages)."""

    n_stages = len([k for k in params if k.startswith("conv")])

    def feature_fn(images: jax.Array) -> jax.Array:
        h = images.astype(jnp.float32)
        pooled = []
        for i in range(n_stages):
            h = conv2d_apply(params[f"conv{i}"], h, compute_dtype=jnp.float32)
            h = lrelu(h, 0.2)
            pooled.append(jnp.mean(h, axis=(1, 2)))
        feats = jnp.concatenate(pooled, axis=-1)
        return feats @ params["proj"]

    return jax.jit(feature_fn)


def make_random_feature_fn(image_size: int, c_dim: int = 3, *,
                           feature_dim: int = 512, base_ch: int = 32,
                           seed: int = 42) -> Tuple[FeatureFn, int]:
    """Fixed-seed untrained embedder; returns (feature_fn, feature_dim).

    Same (image_size, c_dim, feature_dim, base_ch, seed) -> bitwise-identical
    features, so stats computed in different processes are comparable.
    """
    n_stages = max(1, int(np.log2(image_size / 4)))
    keys = jax.random.split(jax.random.key(seed), n_stages + 1)

    params = {}
    in_ch, total = c_dim, 0
    for i in range(n_stages):
        out_ch = base_ch * (2 ** i)
        params[f"conv{i}"] = conv2d_init(keys[i], in_ch, out_ch)
        total += out_ch
        in_ch = out_ch
    # Orthogonal-ish projection: normalized gaussian keeps feature variance
    # bounded so covariances stay well-conditioned for sqrtm.
    proj = jax.random.normal(keys[-1], (total, feature_dim), jnp.float32)
    params["proj"] = proj / jnp.sqrt(jnp.asarray(total, jnp.float32))

    return _build_conv_stack(params), feature_dim


def make_npz_feature_fn(weights_path: str) -> Tuple[FeatureFn, int]:
    """Load a conv-tower embedder from an .npz of arrays named
    `conv{i}/w`, `conv{i}/b` (HWIO kernels) and `proj` [total_pooled, D].

    This is the drop-in slot for converted Inception (or any trained) weights
    when scoring runs outside this no-egress environment.
    """
    raw = np.load(weights_path)
    params: dict = {}
    i = 0
    while f"conv{i}/w" in raw:
        if f"conv{i}/b" not in raw:
            raise ValueError(
                f"{weights_path}: conv{i}/w present but conv{i}/b missing")
        params[f"conv{i}"] = {"w": jnp.asarray(raw[f"conv{i}/w"]),
                              "b": jnp.asarray(raw[f"conv{i}/b"])}
        i += 1
    if i == 0 or "proj" not in raw:
        raise ValueError(
            f"{weights_path}: expected conv0/w, conv0/b, ..., proj arrays")
    params["proj"] = jnp.asarray(raw["proj"])
    feature_dim = int(params["proj"].shape[1])
    return _build_conv_stack(params), feature_dim
