"""Eval CLI: score a trained checkpoint against a dataset (FID; KID with
--kid, from the same feature pass).

    python -m dcgan_tpu.evals --checkpoint_dir ckpt --data_dir /data/celeba
    python -m dcgan_tpu.evals --checkpoint_dir ckpt --synthetic --kid \
        --num_samples 1024 --platform cpu        # smoke run

Prints one JSON line: {"fid": ..., "num_samples": ..., "feature_dim": ...,
("kid": ..., "kid_std": ...,) ("precision"/"recall"/"density"/"coverage"
with --prdc,) "step": ...}. There is no counterpart in the reference — its
only eval was the human eyeballing the sample grids (SURVEY.md §4).
"""

from __future__ import annotations

import argparse
import json
from typing import List, Optional

from dcgan_tpu.config import add_model_override_flags


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dcgan_tpu.evals",
                                description="FID scoring of a checkpoint")
    p.add_argument("--checkpoint_dir", required=True)
    p.add_argument("--data_dir", default=None,
                   help="TFRecord shards of real images")
    p.add_argument("--synthetic", action="store_true",
                   help="score against the synthetic data stream")
    p.add_argument("--num_samples", type=int, default=50_000)
    p.add_argument("--batch_size", type=int, default=256)
    # architecture flags default to None = "take it from the checkpoint's
    # config.json" (written by the trainer); explicit flags override
    add_model_override_flags(p)
    p.add_argument("--kid", action="store_true",
                   help="also report KID (subset-averaged unbiased MMD^2) "
                        "from the same feature pass")
    p.add_argument("--prdc", action="store_true",
                   help="also report precision/recall/density/coverage "
                        "(k-NN manifolds over the same feature reservoirs) "
                        "— fidelity and diversity separated. Note: k-NN "
                        "balls in a 512-d embedding are stringent at small "
                        "pools; compare values across checkpoints at a "
                        "fixed (pool, k), don't read absolutes")
    p.add_argument("--prdc_k", type=int, default=5,
                   help="k for the k-NN manifold radii (papers' default 5)")
    p.add_argument("--kid_subset_size", type=int, default=1000)
    p.add_argument("--kid_subsets", type=int, default=100)
    p.add_argument("--kid_pool", type=int, default=10_000,
                   help="per-side reservoir cap for KID features; raise to "
                        "num_samples for full-set KID (memory: pool*D*4 "
                        "bytes per side)")
    p.add_argument("--feature_npz", default=None,
                   help="optional trained embedder weights (evals/features.py)")
    p.add_argument("--real_stats", default=None,
                   help="cache file for real-side statistics: loaded when "
                        "present (the real pass is skipped), written after "
                        "computing otherwise. One file per (dataset, "
                        "feature config, num_samples); include --kid when "
                        "writing if KID scoring will ever read it")
    p.add_argument("--use_ema", action="store_true",
                   help="score the EMA generator weights (trained with "
                        "--g_ema_decay > 0) instead of the live weights")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None)
    p.add_argument("--multihost", action="store_true",
                   help="distributed scoring: initialize jax.distributed, "
                        "split --num_samples over the processes (each host "
                        "streams its own shard / z stream), all-gather the "
                        "statistics; chief prints the JSON line")
    return p


def main(argv: Optional[List[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    if not args.synthetic and not args.data_dir:
        raise SystemExit("need --data_dir or --synthetic")

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    if args.multihost:
        from dcgan_tpu.parallel import initialize_multihost

        initialize_multihost()

    from dcgan_tpu.config import MODEL_OVERRIDE_FLAGS, TrainConfig, \
        resolve_model_config
    from dcgan_tpu.evals.features import make_npz_feature_fn
    from dcgan_tpu.evals.job import compute_fid
    from dcgan_tpu.parallel import batch_sharding, make_mesh, \
        make_parallel_train
    from dcgan_tpu.utils.checkpoint import Checkpointer

    mcfg = resolve_model_config(
        args.checkpoint_dir,
        overrides={name: getattr(args, name)
                   for name in MODEL_OVERRIDE_FLAGS})
    cfg = TrainConfig(
        model=mcfg,
        batch_size=args.batch_size,
        checkpoint_dir=args.checkpoint_dir,
        # any value > 0 makes sample() read state["ema_gen"]
        g_ema_decay=0.999 if args.use_ema else 0.0)
    # --multihost scores embarrassingly parallel: each process samples its
    # OWN z stream on its LOCAL devices (a global-mesh sample would be a
    # collective over one shared z — the wrong program for split scoring);
    # only the final moment statistics cross processes (job.py allgather)
    devices = jax.local_devices() if args.multihost else None
    mesh = make_mesh(cfg.mesh, devices)
    pt = make_parallel_train(cfg, mesh)

    state = pt.init(jax.random.key(0))
    restored = Checkpointer(args.checkpoint_dir).restore_latest(state)
    if restored is None:
        raise SystemExit(f"no checkpoint under {args.checkpoint_dir}")
    state = restored
    step = int(jax.device_get(state["step"]))

    if args.synthetic:
        from dcgan_tpu.data import synthetic_batches

        # pool=0: the real-side statistics need every sample distinct —
        # cycled batches would bias the FID moments and the KID reservoir.
        # Per-process seed offset: under --multihost each process must
        # stream DIFFERENT reals (its share of the split)
        data = synthetic_batches(args.batch_size, mcfg.output_size,
                                 mcfg.c_dim,
                                 seed=args.seed + 1 + jax.process_index(),
                                 pool=0)
    else:
        from dcgan_tpu.data import DataConfig, make_dataset
        from dcgan_tpu.data.pipeline import read_manifest

        # adopt the wire format the records were prepared with (scoring has
        # no --record_dtype flag on purpose — the manifest is authoritative
        # for a read-only consumer; uint8 datasets score without ceremony).
        # Only keys the manifest carries are passed, so DataConfig stays the
        # single source of the defaults for manifest-less datasets.
        manifest = read_manifest(args.data_dir)
        wire = {k: manifest[k] for k in ("record_dtype", "feature_name")
                if k in manifest}
        dcfg = DataConfig(data_dir=args.data_dir,
                          image_size=mcfg.output_size, channels=mcfg.c_dim,
                          batch_size=args.batch_size, seed=args.seed,
                          normalize=True, **wire)
        if args.multihost and jax.process_count() > 1:
            # ADVICE r2: shard_for_process falls back to "everyone reads
            # everything, seeds differ" when there are fewer shards than
            # processes — the gathered real moments would then sample with
            # replacement/duplicates, silently biasing FID. Disjoint real
            # splits need >= process_count shards (re-shard with
            # `python -m dcgan_tpu.data.prepare --num_shards N`).
            from dcgan_tpu.data.pipeline import list_shards

            n_shards = len(list_shards(args.data_dir))
            if n_shards < jax.process_count():
                raise SystemExit(
                    f"--multihost real-data scoring needs at least one "
                    f"TFRecord shard per process for a disjoint real split: "
                    f"{n_shards} shard(s) < {jax.process_count()} processes "
                    f"in {args.data_dir!r}")
        data = make_dataset(dcfg, batch_sharding(mesh, 4))

    feature_fn = feature_dim = None
    if args.feature_npz:
        feature_fn, feature_dim = make_npz_feature_fn(args.feature_npz)

    def sample_fn(z, labels=None):
        return pt.sample(state, z, labels) if labels is not None \
            else pt.sample(state, z)

    try:
        result = compute_fid(
            sample_fn, data, image_size=mcfg.output_size, c_dim=mcfg.c_dim,
            z_dim=mcfg.z_dim, num_samples=args.num_samples,
            batch_size=args.batch_size, num_classes=mcfg.num_classes,
            seed=args.seed, feature_fn=feature_fn, feature_dim=feature_dim,
            kid=args.kid, kid_subset_size=args.kid_subset_size,
            kid_subsets=args.kid_subsets, kid_pool_size=args.kid_pool,
            prdc=args.prdc, prdc_k=args.prdc_k,
            distributed=args.multihost, real_cache_path=args.real_stats)
    except ValueError as e:
        raise SystemExit(str(e)) from None
    finally:
        if hasattr(data, "close"):  # stop the device-feed thread
            data.close()
    result["step"] = step
    if jax.process_index() == 0:
        print(json.dumps(result))


if __name__ == "__main__":
    main()
