"""The eval job: stream real-data and generator features once, score FID
(BASELINE.md north star: FID-50k parity) and optionally KID from the same
pass.

Layout mirrors the training driver: the sampler is the mesh-sharded
`ParallelTrain.sample` (generation fans out over the data axis), features are
extracted on device batch-by-batch, and only [D] / [D, D] moment statistics —
plus a bounded KID reservoir when enabled — live on host. 50k samples at
batch 256 is ~200 device round trips of [B, D] floats — negligible next to
generation itself.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

import jax
import numpy as np

from dcgan_tpu.evals.features import FeatureFn, make_random_feature_fn
from dcgan_tpu.evals.fid import StreamingStats, frechet_distance
from dcgan_tpu.evals.kid import FeaturePool, kid_score


def stats_from_batches(feature_fn: FeatureFn, batches: Iterable,
                       num_examples: int, feature_dim: int,
                       pool: Optional[FeaturePool] = None) -> StreamingStats:
    """Fold image batches ([B,H,W,C] in [-1,1]) into feature statistics until
    `num_examples` have been consumed; the last batch is trimmed to land
    exactly on the target count. `pool`, if given, reservoir-samples the same
    features for KID."""
    stats = StreamingStats(feature_dim)
    for batch in batches:
        take = min(int(batch.shape[0]), num_examples - stats.n)
        feats = jax.device_get(feature_fn(batch[:take]))
        stats.update(feats)
        if pool is not None:
            pool.update(feats)
        if stats.n >= num_examples:
            break
    if stats.n < num_examples:
        raise ValueError(
            f"data stream exhausted at {stats.n}/{num_examples} examples")
    return stats


def generator_stats(sample_fn: Callable, feature_fn: FeatureFn,
                    feature_dim: int, *, num_samples: int, batch_size: int,
                    z_dim: int, seed: int = 0, num_classes: int = 0,
                    pool: Optional[FeaturePool] = None) -> StreamingStats:
    """Stream `num_samples` generated images into feature statistics.

    `sample_fn(z[, labels]) -> images` is the EMA-stat sampler path
    (ParallelTrain.sample / sampler_apply). z is drawn U(-1,1) like training
    (image_train.py:151); labels cycle through the classes when conditional.
    """
    stats = StreamingStats(feature_dim)
    base = jax.random.key(seed)
    i = 0
    while stats.n < num_samples:
        z = jax.random.uniform(jax.random.fold_in(base, i),
                               (batch_size, z_dim), minval=-1.0, maxval=1.0)
        if num_classes:
            labels = (np.arange(i * batch_size, (i + 1) * batch_size)
                      % num_classes)
            images = sample_fn(z, jax.numpy.asarray(labels))
        else:
            images = sample_fn(z)
        take = min(batch_size, num_samples - stats.n)
        feats = jax.device_get(feature_fn(images[:take]))
        stats.update(feats)
        if pool is not None:
            pool.update(feats)
        i += 1
    return stats


def _allgather_f64(x: np.ndarray) -> np.ndarray:
    """process_allgather that PRESERVES float64: device_put canonicalizes
    f64 -> f32 without jax_enable_x64, which would silently corrupt the
    moment accumulators (finalize()'s covariance is a cancellation-prone
    subtraction that needs the full 52-bit mantissa at 50k samples). The
    array crosses the wire as its uint32 bit pattern instead."""
    from jax.experimental import multihost_utils as mh

    bits = np.ascontiguousarray(np.asarray(x, np.float64)).view(np.uint32)
    return np.ascontiguousarray(
        np.asarray(mh.process_allgather(bits))).view(np.float64)


def _norm_npz(path: str) -> str:
    """np.savez APPENDS '.npz' to extensionless paths; normalize up front so
    the save path and the existence check can never disagree."""
    return path if path.endswith(".npz") else path + ".npz"


def real_side_to_npz(path: str, stats: StreamingStats,
                     pool: Optional[FeaturePool] = None) -> None:
    """Persist real-side statistics (raw accumulators, not finalized
    moments, so merging/extending later stays exact; plus the KID reservoir
    when present). The standard precomputed-real-statistics pattern of FID
    tooling: the real pass over 50k images is paid once per dataset, not
    once per checkpoint."""
    path = _norm_npz(path)
    arrays = {"n": np.asarray(stats.n, np.int64), "sum": stats._sum,
              "outer": stats._outer}
    if pool is not None:
        arrays["pool_features"] = pool.features()
        arrays["pool_n_seen"] = np.asarray(pool.n_seen, np.int64)
        arrays["pool_capacity"] = np.asarray(pool.capacity, np.int64)
    np.savez(path, **arrays)


def real_side_from_npz(path: str, *, need_pool: bool
                       ) -> tuple:
    """Load (StreamingStats, FeaturePool | None) written by
    real_side_to_npz. Raises if KID is requested but the file carries no
    reservoir (it was written without kid)."""
    raw = np.load(_norm_npz(path))
    dim = int(raw["sum"].shape[0])
    stats = StreamingStats(dim)
    stats.n = int(raw["n"])
    stats._sum = np.asarray(raw["sum"], np.float64)
    stats._outer = np.asarray(raw["outer"], np.float64)
    pool = None
    if "pool_features" in raw:
        pool = pool_from_features(
            np.asarray(raw["pool_features"], np.float32),
            int(raw["pool_n_seen"]), int(raw["pool_capacity"]))
    if need_pool and pool is None:
        raise ValueError(
            f"{path} has no feature reservoir (it was written without "
            "--kid/--prdc); recompute the real statistics with the "
            "reservoir-needing flag set")
    return stats, pool


def allgather_merge_stats(stats: StreamingStats) -> StreamingStats:
    """Cross-process reduction of per-process feature statistics: every
    process contributes its (n, Σx, Σxxᵀ) accumulators and every process
    gets the identical global StreamingStats back. No-op single-process."""
    if jax.process_count() == 1:
        return stats
    from jax.experimental import multihost_utils as mh

    merged = StreamingStats(stats.dim)
    # n fits int32 comfortably (sample budgets are ~1e5), so the default
    # canonicalization is harmless here
    merged.n = int(np.sum(mh.process_allgather(np.asarray(stats.n))))
    merged._sum = np.sum(_allgather_f64(stats._sum), axis=0)
    merged._outer = np.sum(_allgather_f64(stats._outer), axis=0)
    return merged


def pool_from_features(feats: np.ndarray, n_seen: int, capacity: int, *,
                       seed: int = 0) -> FeaturePool:
    """Rebuild a FeaturePool around an existing uniform sample (used to
    reconstruct remote processes' pools after an allgather)."""
    pool = FeaturePool(feats.shape[1], capacity, seed=seed)
    pool._buf[:len(feats)] = feats
    pool.n_seen = int(n_seen)
    return pool


def allgather_merge_pool(pool: FeaturePool) -> FeaturePool:
    """Cross-process weighted reservoir merge: gather every process's pool
    and fold them with FeaturePool.merge. Deterministic given the pool's
    rng state, so all processes converge on the same merged sample.

    Requires every process to have streamed the same number of examples
    (the distributed compute_fid splits num_samples evenly), so the
    gathered buffers have equal shapes.
    """
    if jax.process_count() == 1:
        return pool
    from jax.experimental import multihost_utils as mh

    feats = mh.process_allgather(pool.features())           # [P, S, D]
    counts = mh.process_allgather(np.asarray(pool.n_seen))  # [P]
    counts = counts.reshape(-1)
    # EVERY process folds in the same order (0, then 1..P-1) with the same
    # fixed rng — starting from each process's own buffer would swap
    # mine/theirs in the weighted draws and give per-process results
    merged = pool_from_features(np.asarray(feats[0]), counts[0],
                                pool.capacity, seed=0)
    merged._rng = np.random.default_rng(12345)
    for p in range(1, feats.shape[0]):
        merged.merge(pool_from_features(np.asarray(feats[p]), counts[p],
                                        pool.capacity))
    return merged


def compute_fid(sample_fn: Callable, data_batches: Iterable, *,
                image_size: int, c_dim: int = 3, z_dim: int = 100,
                num_samples: int = 50_000, batch_size: int = 256,
                num_classes: int = 0, seed: int = 0,
                feature_fn: Optional[FeatureFn] = None,
                feature_dim: Optional[int] = None,
                kid: bool = False, kid_subset_size: int = 1000,
                kid_subsets: int = 100,
                kid_pool_size: int = 10_000,
                prdc: bool = False, prdc_k: int = 5,
                distributed: bool = False,
                real_side: Optional[tuple] = None,
                real_cache_path: Optional[str] = None) -> dict:
    """End-to-end scoring: returns {"fid", "num_samples", "feature_dim"} and,
    with kid=True, {"kid", "kid_std"} from the SAME feature pass (a bounded
    reservoir of features feeds the subset-averaged unbiased-MMD estimator —
    evals/kid.py). prdc=True adds {"precision", "recall", "density",
    "coverage"} (evals/prdc.py) computed on the same reservoirs — fidelity
    and diversity separated, where FID/KID compress them into one number.

    With feature_fn=None the fixed-seed random embedder is used — scores are
    then comparable across runs/processes but are surrogate scores, not
    Inception ones (see evals/features.py).

    distributed=True under a jax.distributed job splits num_samples evenly
    over the processes — each streams its own real-data shard and generates
    with a process-distinct z stream — then all-gathers the moment
    accumulators (and KID reservoirs) so every process returns the same
    global score. There is no multi-eval counterpart in the reference (its
    only eval was the chief eyeballing sample grids, SURVEY.md §4).

    real_side, if given, is a (StreamingStats, FeaturePool | None) pair of
    PRECOMPUTED real statistics — the data stream is not touched. Repeated
    scoring of a fixed real set (the in-training probe) computes it once
    and amortizes it; the pair must have been built with the same
    feature_fn and sample budget.

    real_cache_path names an on-disk cache for the real side (the CLI's
    --real_stats): loaded when the file exists (with n / feature-dim /
    reservoir-capacity validation), else the real side is computed here as
    usual and written there. Keeping this inside compute_fid means the
    cached and uncached paths share one copy of the real-pass construction
    (same pool seeding, same trimming). Exclusive with real_side and with
    distributed (the distributed real pass is a per-process split).
    """
    if feature_fn is None:
        feature_fn, feature_dim = make_random_feature_fn(image_size, c_dim)
    elif feature_dim is None:
        raise ValueError("feature_dim required with a custom feature_fn")

    n_proc = jax.process_count() if distributed else 1
    local_samples = num_samples // n_proc
    if distributed and num_samples % n_proc:
        raise ValueError(
            f"num_samples ({num_samples}) must divide evenly over "
            f"{n_proc} processes")
    # process-distinct generator stream; real-data sharding is the
    # pipeline's job (per-host shard ownership / per-process seeds)
    gen_seed = seed + 7919 * (jax.process_index() if distributed else 0)

    if real_cache_path:
        import os

        if real_side is not None:
            raise ValueError("pass real_side OR real_cache_path, not both")
        if distributed:
            raise ValueError(
                "real_cache_path does not compose with distributed scoring "
                "(the distributed real pass is a per-process split)")
        if os.path.exists(_norm_npz(real_cache_path)):
            real_side = real_side_from_npz(real_cache_path,
                                           need_pool=kid or prdc)
            cached, cached_pool = real_side
            if cached.n != num_samples:
                raise ValueError(
                    f"{real_cache_path} holds statistics over {cached.n} "
                    f"examples but num_samples is {num_samples}; FID sides "
                    "must match — recompute or adjust num_samples")
            if cached.dim != feature_dim:
                raise ValueError(
                    f"{real_cache_path} has feature dim {cached.dim}, the "
                    f"current extractor yields {feature_dim} — it was "
                    "written under a different feature config")
            if (kid or prdc) and cached_pool.capacity != kid_pool_size:
                raise ValueError(
                    f"{real_cache_path} reservoir capacity "
                    f"{cached_pool.capacity} != kid_pool_size "
                    f"{kid_pool_size}; kid/prdc sides must draw from "
                    "same-sized reservoirs — recompute or adjust kid_pool")

    need_pools = kid or prdc
    fake_pool = FeaturePool(feature_dim, kid_pool_size, seed=seed + 1) \
        if need_pools else None
    if real_side is not None:
        real, real_pool = real_side
        if need_pools and real_pool is None:
            raise ValueError(
                "kid/prdc need a FeaturePool in real_side")
    else:
        real_pool = FeaturePool(feature_dim, kid_pool_size, seed=seed) \
            if need_pools else None
        real = stats_from_batches(feature_fn, data_batches, local_samples,
                                  feature_dim, pool=real_pool)
        if real_cache_path:
            real_side_to_npz(real_cache_path, real, real_pool)
    fake = generator_stats(sample_fn, feature_fn, feature_dim,
                           num_samples=local_samples, batch_size=batch_size,
                           z_dim=z_dim, seed=gen_seed,
                           num_classes=num_classes,
                           pool=fake_pool)
    if distributed:
        # a caller-provided real_side is taken as already global — merging
        # it again would double-count
        if real_side is None:
            real = allgather_merge_stats(real)
            if need_pools:
                real_pool = allgather_merge_pool(real_pool)
        fake = allgather_merge_stats(fake)
        if need_pools:
            fake_pool = allgather_merge_pool(fake_pool)
    fid = frechet_distance(*real.finalize(), *fake.finalize())
    out = {"fid": fid, "num_samples": num_samples,
           "feature_dim": feature_dim}
    if kid:
        mean, std = kid_score(real_pool.features(), fake_pool.features(),
                              subset_size=kid_subset_size,
                              num_subsets=kid_subsets, seed=seed)
        out["kid"] = mean
        out["kid_std"] = std
        # the score is computed on at most this many reservoir-sampled
        # features per side — recorded so KID numbers are comparable
        out["kid_pool"] = min(kid_pool_size, num_samples)
    if prdc:
        from dcgan_tpu.evals.prdc import prdc as prdc_fn

        out.update(prdc_fn(real_pool.features(), fake_pool.features(),
                           k=prdc_k))
        # comparability keys, like kid_pool above: P&R values only compare
        # across runs at a fixed (pool, k)
        out["prdc_pool"] = min(kid_pool_size, num_samples)
        out["prdc_k"] = prdc_k
    return out
