"""The eval job: stream real-data and generator features once, score FID
(BASELINE.md north star: FID-50k parity) and optionally KID from the same
pass.

Layout mirrors the training driver: the sampler is the mesh-sharded
`ParallelTrain.sample` (generation fans out over the data axis), features are
extracted on device batch-by-batch, and only [D] / [D, D] moment statistics —
plus a bounded KID reservoir when enabled — live on host. 50k samples at
batch 256 is ~200 device round trips of [B, D] floats — negligible next to
generation itself.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

import jax
import numpy as np

from dcgan_tpu.evals.features import FeatureFn, make_random_feature_fn
from dcgan_tpu.evals.fid import StreamingStats, frechet_distance
from dcgan_tpu.evals.kid import FeaturePool, kid_score


def stats_from_batches(feature_fn: FeatureFn, batches: Iterable,
                       num_examples: int, feature_dim: int,
                       pool: Optional[FeaturePool] = None) -> StreamingStats:
    """Fold image batches ([B,H,W,C] in [-1,1]) into feature statistics until
    `num_examples` have been consumed; the last batch is trimmed to land
    exactly on the target count. `pool`, if given, reservoir-samples the same
    features for KID."""
    stats = StreamingStats(feature_dim)
    for batch in batches:
        take = min(int(batch.shape[0]), num_examples - stats.n)
        feats = jax.device_get(feature_fn(batch[:take]))
        stats.update(feats)
        if pool is not None:
            pool.update(feats)
        if stats.n >= num_examples:
            break
    if stats.n < num_examples:
        raise ValueError(
            f"data stream exhausted at {stats.n}/{num_examples} examples")
    return stats


def generator_stats(sample_fn: Callable, feature_fn: FeatureFn,
                    feature_dim: int, *, num_samples: int, batch_size: int,
                    z_dim: int, seed: int = 0, num_classes: int = 0,
                    pool: Optional[FeaturePool] = None) -> StreamingStats:
    """Stream `num_samples` generated images into feature statistics.

    `sample_fn(z[, labels]) -> images` is the EMA-stat sampler path
    (ParallelTrain.sample / sampler_apply). z is drawn U(-1,1) like training
    (image_train.py:151); labels cycle through the classes when conditional.
    """
    stats = StreamingStats(feature_dim)
    base = jax.random.key(seed)
    i = 0
    while stats.n < num_samples:
        z = jax.random.uniform(jax.random.fold_in(base, i),
                               (batch_size, z_dim), minval=-1.0, maxval=1.0)
        if num_classes:
            labels = (np.arange(i * batch_size, (i + 1) * batch_size)
                      % num_classes)
            images = sample_fn(z, jax.numpy.asarray(labels))
        else:
            images = sample_fn(z)
        take = min(batch_size, num_samples - stats.n)
        feats = jax.device_get(feature_fn(images[:take]))
        stats.update(feats)
        if pool is not None:
            pool.update(feats)
        i += 1
    return stats


def compute_fid(sample_fn: Callable, data_batches: Iterable, *,
                image_size: int, c_dim: int = 3, z_dim: int = 100,
                num_samples: int = 50_000, batch_size: int = 256,
                num_classes: int = 0, seed: int = 0,
                feature_fn: Optional[FeatureFn] = None,
                feature_dim: Optional[int] = None,
                kid: bool = False, kid_subset_size: int = 1000,
                kid_subsets: int = 100,
                kid_pool_size: int = 10_000) -> dict:
    """End-to-end scoring: returns {"fid", "num_samples", "feature_dim"} and,
    with kid=True, {"kid", "kid_std"} from the SAME feature pass (a bounded
    reservoir of features feeds the subset-averaged unbiased-MMD estimator —
    evals/kid.py).

    With feature_fn=None the fixed-seed random embedder is used — scores are
    then comparable across runs/processes but are surrogate scores, not
    Inception ones (see evals/features.py).
    """
    if feature_fn is None:
        feature_fn, feature_dim = make_random_feature_fn(image_size, c_dim)
    elif feature_dim is None:
        raise ValueError("feature_dim required with a custom feature_fn")

    real_pool = FeaturePool(feature_dim, kid_pool_size, seed=seed) \
        if kid else None
    fake_pool = FeaturePool(feature_dim, kid_pool_size, seed=seed + 1) \
        if kid else None
    real = stats_from_batches(feature_fn, data_batches, num_samples,
                              feature_dim, pool=real_pool)
    fake = generator_stats(sample_fn, feature_fn, feature_dim,
                           num_samples=num_samples, batch_size=batch_size,
                           z_dim=z_dim, seed=seed, num_classes=num_classes,
                           pool=fake_pool)
    fid = frechet_distance(*real.finalize(), *fake.finalize())
    out = {"fid": fid, "num_samples": num_samples,
           "feature_dim": feature_dim}
    if kid:
        mean, std = kid_score(real_pool.features(), fake_pool.features(),
                              subset_size=kid_subset_size,
                              num_subsets=kid_subsets, seed=seed)
        out["kid"] = mean
        out["kid_std"] = std
        # the score is computed on at most this many reservoir-sampled
        # features per side — recorded so KID numbers are comparable
        out["kid_pool"] = min(kid_pool_size, num_samples)
    return out
