"""The FID eval job: stream real-data and generator features into statistics,
score the Fréchet distance (BASELINE.md north star: FID-50k parity).

Layout mirrors the training driver: the sampler is the mesh-sharded
`ParallelTrain.sample` (generation fans out over the data axis), features are
extracted on device batch-by-batch, and only [D] / [D, D] statistics live on
host. 50k samples at batch 256 is ~200 device round trips of [B, D] floats —
negligible next to generation itself.
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, Optional, Tuple

import jax
import numpy as np

from dcgan_tpu.evals.features import FeatureFn, make_random_feature_fn
from dcgan_tpu.evals.fid import StreamingStats, frechet_distance


def stats_from_batches(feature_fn: FeatureFn, batches: Iterable,
                       num_examples: int, feature_dim: int) -> StreamingStats:
    """Fold image batches ([B,H,W,C] in [-1,1]) into feature statistics until
    `num_examples` have been consumed; the last batch is trimmed to land
    exactly on the target count."""
    stats = StreamingStats(feature_dim)
    for batch in batches:
        take = min(int(batch.shape[0]), num_examples - stats.n)
        feats = jax.device_get(feature_fn(batch[:take]))
        stats.update(feats)
        if stats.n >= num_examples:
            break
    if stats.n < num_examples:
        raise ValueError(
            f"data stream exhausted at {stats.n}/{num_examples} examples")
    return stats


def generator_stats(sample_fn: Callable, feature_fn: FeatureFn,
                    feature_dim: int, *, num_samples: int, batch_size: int,
                    z_dim: int, seed: int = 0,
                    num_classes: int = 0) -> StreamingStats:
    """Stream `num_samples` generated images into feature statistics.

    `sample_fn(z[, labels]) -> images` is the EMA-stat sampler path
    (ParallelTrain.sample / sampler_apply). z is drawn U(-1,1) like training
    (image_train.py:151); labels cycle through the classes when conditional.
    """
    stats = StreamingStats(feature_dim)
    base = jax.random.key(seed)
    i = 0
    while stats.n < num_samples:
        z = jax.random.uniform(jax.random.fold_in(base, i),
                               (batch_size, z_dim), minval=-1.0, maxval=1.0)
        if num_classes:
            labels = (np.arange(i * batch_size, (i + 1) * batch_size)
                      % num_classes)
            images = sample_fn(z, jax.numpy.asarray(labels))
        else:
            images = sample_fn(z)
        take = min(batch_size, num_samples - stats.n)
        feats = jax.device_get(feature_fn(images[:take]))
        stats.update(feats)
        i += 1
    return stats


def compute_fid(sample_fn: Callable, data_batches: Iterable, *,
                image_size: int, c_dim: int = 3, z_dim: int = 100,
                num_samples: int = 50_000, batch_size: int = 256,
                num_classes: int = 0, seed: int = 0,
                feature_fn: Optional[FeatureFn] = None,
                feature_dim: Optional[int] = None) -> dict:
    """End-to-end FID: returns {"fid", "num_samples", "feature_dim"}.

    With feature_fn=None the fixed-seed random embedder is used — scores are
    then comparable across runs/processes but are surrogate-FID, not
    Inception-FID (see evals/features.py).
    """
    if feature_fn is None:
        feature_fn, feature_dim = make_random_feature_fn(image_size, c_dim)
    elif feature_dim is None:
        raise ValueError("feature_dim required with a custom feature_fn")

    real = stats_from_batches(feature_fn, data_batches, num_samples,
                              feature_dim)
    fake = generator_stats(sample_fn, feature_fn, feature_dim,
                           num_samples=num_samples, batch_size=batch_size,
                           z_dim=z_dim, seed=seed, num_classes=num_classes)
    fid = frechet_distance(*real.finalize(), *fake.finalize())
    return {"fid": fid, "num_samples": num_samples,
            "feature_dim": feature_dim}
