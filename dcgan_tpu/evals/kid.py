"""Kernel Inception Distance (Bińkowski et al. 2018, arXiv:1801.01401).

KID is the unbiased MMD^2 between real and generated feature distributions
under the polynomial kernel k(x, y) = (x·y / D + 1)^3, reported as the mean
(and std) over random subsets. It complements FID in the eval rig: the
estimator is unbiased at small sample counts (FID's Gaussian fit is not), so
it is the score to trust for quick evals during training, and it needs no
matrix square root.

Unlike FID's O(D)/O(D^2) streaming moments (evals/fid.py), MMD needs actual
feature vectors. `FeaturePool` keeps a bounded uniform sample of the stream
via reservoir sampling — memory is capacity·D however many examples stream
through, and the pooled subset is an unbiased draw, which is exactly what the
subset-averaged estimator wants. Pools merge across hosts (weighted reservoir
merge) the way StreamingStats.merge does.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class FeaturePool:
    """Bounded uniform sample of a feature stream ([B, D] updates)."""

    def __init__(self, dim: int, capacity: int, *, seed: int = 0):
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        self.dim = dim
        self.capacity = capacity
        self.n_seen = 0
        self._buf = np.zeros((capacity, dim), np.float32)
        self._rng = np.random.default_rng(seed)

    def update(self, feats) -> None:
        feats = np.asarray(feats, np.float32)
        if feats.ndim != 2 or feats.shape[1] != self.dim:
            raise ValueError(f"expected [B, {self.dim}], got {feats.shape}")
        # fill phase: copy rows straight into empty slots
        if self.n_seen < self.capacity:
            take = min(self.capacity - self.n_seen, feats.shape[0])
            self._buf[self.n_seen:self.n_seen + take] = feats[:take]
            self.n_seen += take
            feats = feats[take:]
        if feats.shape[0] == 0:
            return
        # classic reservoir (Algorithm R), vectorized per batch: stream
        # element i replaces a uniform slot j ~ [0, i] iff j < capacity
        idx = np.arange(self.n_seen + 1, self.n_seen + 1 + feats.shape[0])
        js = (self._rng.random(feats.shape[0]) * idx).astype(np.int64)
        keep = js < self.capacity
        # later duplicates must win (they would in the sequential loop)
        self._buf[js[keep]] = feats[keep]
        self.n_seen += feats.shape[0]

    def merge(self, other: "FeaturePool") -> "FeaturePool":
        """Fold another pool in, keeping the union uniform: each slot draws
        from self/other proportional to their stream counts.

        Each side's buffer is shuffled before the draw: a reservoir's
        *contents* are a uniform sample but its *order* correlates with
        stream position (the fill phase is stream-ordered), so consuming
        sequential prefixes would bias the merged sample toward
        early-stream features whenever take < mine+theirs (ADVICE r1)."""
        if other.dim != self.dim or other.capacity != self.capacity:
            raise ValueError("pool shape mismatch")
        mine = self.features().copy()
        theirs = other.features().copy()
        self._rng.shuffle(mine)
        self._rng.shuffle(theirs)
        total = self.n_seen + other.n_seen
        take = min(self.capacity, len(mine) + len(theirs))
        p_other = other.n_seen / max(1, total)
        out = np.zeros((take, self.dim), np.float32)
        mi = ti = 0
        for i in range(take):
            from_other = (self._rng.random() < p_other and ti < len(theirs)) \
                or mi >= len(mine)
            if from_other:
                out[i] = theirs[ti]; ti += 1
            else:
                out[i] = mine[mi]; mi += 1
        self._buf[:take] = out
        self.n_seen = total
        return self

    def features(self) -> np.ndarray:
        """The sampled features, [min(n_seen, capacity), D]."""
        return self._buf[:min(self.n_seen, self.capacity)]


def polynomial_kernel(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """k(x, y) = (x·y / D + 1)^3 — the KID paper's kernel (degree 3,
    gamma = 1/D, coef 1)."""
    d = x.shape[1]
    return (x @ y.T / d + 1.0) ** 3


def mmd2_unbiased(x: np.ndarray, y: np.ndarray) -> float:
    """Unbiased MMD^2 estimate between equal-size feature sets [n, D]."""
    n = x.shape[0]
    m = y.shape[0]
    if n < 2 or m < 2:
        raise ValueError(f"need >= 2 samples per side, got {n}, {m}")
    kxx = polynomial_kernel(x, x)
    kyy = polynomial_kernel(y, y)
    kxy = polynomial_kernel(x, y)
    sum_xx = (kxx.sum() - np.trace(kxx)) / (n * (n - 1))
    sum_yy = (kyy.sum() - np.trace(kyy)) / (m * (m - 1))
    sum_xy = kxy.mean()
    return float(sum_xx + sum_yy - 2.0 * sum_xy)


def kid_score(real: np.ndarray, fake: np.ndarray, *,
              subset_size: int = 1000, num_subsets: int = 100,
              seed: int = 0) -> Tuple[float, float]:
    """Mean and std of unbiased MMD^2 over `num_subsets` random subsets of
    size `subset_size` (the paper's block estimator; subsets are drawn
    without replacement within a block, with replacement across blocks).
    Subset size clamps to the smaller feature set."""
    real = np.asarray(real, np.float64)
    fake = np.asarray(fake, np.float64)
    n = min(subset_size, real.shape[0], fake.shape[0])
    rng = np.random.default_rng(seed)
    vals = np.empty(num_subsets, np.float64)
    for i in range(num_subsets):
        rs = real[rng.choice(real.shape[0], n, replace=False)]
        fs = fake[rng.choice(fake.shape[0], n, replace=False)]
        vals[i] = mmd2_unbiased(rs, fs)
    return float(vals.mean()), float(vals.std())
