"""Evaluation rig: FID-50k scoring of generator checkpoints (SURVEY.md §7
phase 8 — the benchmark component the reference never had; its only built-in
quality signal was eyeballing fixed-z sample grids, image_train.py:179-192).
"""

from dcgan_tpu.evals.features import (
    make_npz_feature_fn,
    make_random_feature_fn,
)
from dcgan_tpu.evals.fid import StreamingStats, frechet_distance
from dcgan_tpu.evals.job import (
    compute_fid,
    generator_stats,
    stats_from_batches,
)

__all__ = [
    "StreamingStats",
    "frechet_distance",
    "make_npz_feature_fn",
    "make_random_feature_fn",
    "stats_from_batches",
    "generator_stats",
    "compute_fid",
]
