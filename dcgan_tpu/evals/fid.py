"""Fréchet Inception Distance core: streaming activation statistics and the
matrix-sqrt Fréchet distance.

FID(N(mu1, C1), N(mu2, C2)) = |mu1-mu2|^2 + tr(C1 + C2 - 2 (C1 C2)^{1/2})

Statistics accumulate in a streaming (sum / outer-product-sum) form so 50k
samples never need to be resident at once — features arrive in device batches,
are folded into float64 host accumulators, and the 50k-sample pass is O(D^2)
memory regardless of sample count. Accumulators merge across hosts for
multi-process eval.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class StreamingStats:
    """Mean/covariance accumulator over feature batches [B, D]."""

    def __init__(self, dim: int):
        self.dim = dim
        self.n = 0
        self._sum = np.zeros((dim,), np.float64)
        self._outer = np.zeros((dim, dim), np.float64)

    def update(self, feats) -> None:
        feats = np.asarray(feats, np.float64)
        if feats.ndim != 2 or feats.shape[1] != self.dim:
            raise ValueError(f"expected [B, {self.dim}], got {feats.shape}")
        self.n += feats.shape[0]
        self._sum += feats.sum(axis=0)
        self._outer += feats.T @ feats

    def merge(self, other: "StreamingStats") -> "StreamingStats":
        """Fold another accumulator in (cross-host reduction for multi-process
        eval — each host streams its shard, stats merge at the end)."""
        if other.dim != self.dim:
            raise ValueError("dim mismatch")
        self.n += other.n
        self._sum += other._sum
        self._outer += other._outer
        return self

    def finalize(self) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (mean [D], covariance [D, D]) with the unbiased (n-1)
        normalization the reference FID implementations use (np.cov default).
        """
        if self.n < 2:
            raise ValueError(f"need >= 2 samples, have {self.n}")
        mu = self._sum / self.n
        cov = (self._outer - self.n * np.outer(mu, mu)) / (self.n - 1)
        return mu, cov


def frechet_distance(mu1, cov1, mu2, cov2, *, eps: float = 1e-6) -> float:
    """Fréchet distance between two Gaussians.

    The matrix square root runs on host in float64 (scipy); it's a one-shot
    O(D^3) epilogue, not worth a device kernel. A diagonal jitter retry
    handles the near-singular covariances that small sample counts produce.
    """
    import scipy.linalg

    mu1 = np.asarray(mu1, np.float64)
    mu2 = np.asarray(mu2, np.float64)
    cov1 = np.asarray(cov1, np.float64)
    cov2 = np.asarray(cov2, np.float64)

    diff = mu1 - mu2
    covmean = scipy.linalg.sqrtm(cov1 @ cov2)
    if not np.isfinite(covmean).all():
        offset = eps * np.eye(cov1.shape[0])
        covmean = scipy.linalg.sqrtm((cov1 + offset) @ (cov2 + offset))
    if np.iscomplexobj(covmean):
        # numerical imaginary leakage from sqrtm of a near-PSD product
        covmean = covmean.real
    fid = diff @ diff + np.trace(cov1) + np.trace(cov2) - 2.0 * np.trace(covmean)
    # tiny negative values are pure roundoff; true FID is >= 0
    return float(max(fid, 0.0))
