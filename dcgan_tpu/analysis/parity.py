"""DCG004: every trainer metric/JSONL key must be in the gated inventory.

The parity contract (DESIGN.md §6b, tests/test_services.py,
tests/test_chaos.py): with every new flag at its default, the trainer's
JSONL event stream must be byte-identical to the previous build — new
keys may appear only when their feature activates. The contract used to
be enforced after the fact, by the parity A/B noticing a diff; this
checker moves the failure to lint time. Every namespaced key literal the
trainer (and the fleet-metrics builder) emits must appear in the declared
inventory `dcgan_tpu/train/event_keys.py`, annotated with the knob that
gates it (or "always") — so an ungated new key fails `python -m
dcgan_tpu.analysis` before it fails the parity A/B.

Extraction is syntactic: any string constant in the scanned modules that
looks like a metric key (`<namespace>/...` with a known namespace), plus
f-strings whose leading constant is a namespaced prefix (recorded as
`prefix*` and matched against wildcard inventory entries). Keys built
through a prefix parameter in another module (StepTimer's `perf/`,
StartupProfile's `perf/startup/`) are declared in the inventory and
pinned by the runtime completeness tests in tests/test_analysis.py — the
static pass and the runtime test together close the loop.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Sequence, Tuple

from dcgan_tpu.analysis.core import Config, Finding, SourceFile

CHECK_ID = "DCG004"

#: namespaces that mark a string literal as a metric/JSONL event key
KEY_NAMESPACES = ("perf", "fleet", "eval", "anomaly", "data", "sample",
                  "serve", "elastic", "progressive")

_KEY_RE = re.compile(
    r"^(?:%s)/[A-Za-z0-9_./]+$" % "|".join(KEY_NAMESPACES))
_PREFIX_RE = re.compile(
    r"^(?:%s)/[A-Za-z0-9_./]*$" % "|".join(KEY_NAMESPACES))


def key_in_inventory(key: str, inventory: Dict[str, str]) -> bool:
    """Exact entry, or a wildcard entry ('perf/compile_ms/*') whose prefix
    matches. A literal extracted as a prefix wildcard ('sample/*') needs a
    wildcard entry covering it."""
    if key in inventory:
        return True
    for entry in inventory:
        if entry.endswith("*") and key[:-1 if key.endswith("*") else None] \
                .startswith(entry[:-1]):
            if key.endswith("*"):
                # wildcard literal: the inventory wildcard must be at
                # least as general
                if entry[:-1] and key[:-1].startswith(entry[:-1]):
                    return True
            else:
                return True
    return False


def _extract_keys(sf: SourceFile) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    # constants living inside f-strings are reported once, as the
    # f-string's prefix wildcard — not again as bare literals
    fstring_parts = {id(v) for node in ast.walk(sf.tree)
                     if isinstance(node, ast.JoinedStr)
                     for v in node.values}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if id(node) not in fstring_parts and _KEY_RE.match(node.value):
                out.append((node.value, node.lineno))
        elif isinstance(node, ast.JoinedStr) and node.values:
            first = node.values[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str) \
                    and "/" in first.value \
                    and _PREFIX_RE.match(first.value):
                out.append((first.value + "*", node.lineno))
    return out


def check_key_inventory(sources: Sequence[SourceFile],
                        config: Config) -> List[Finding]:
    inventory = config.load_inventory()
    findings: List[Finding] = []
    for sf in sources:
        if sf.path not in config.parity_modules:
            continue
        for key, line in _extract_keys(sf):
            if key_in_inventory(key, inventory):
                continue
            findings.append(Finding(
                check=CHECK_ID, path=sf.path, line=line,
                symbol="<key>", key=key,
                message=(
                    f"metric key {key!r} is not in the declared event-key "
                    "inventory (dcgan_tpu/train/event_keys.py) — add it "
                    "with the knob that gates it (or 'always' if it may "
                    "appear in default-flag runs), so the parity contract "
                    "is checked at lint time instead of failing the "
                    "JSONL A/B")))
    # one finding per key per file (the same literal often appears at a
    # read site and a write site)
    seen = set()
    out = []
    for f in findings:
        k = (f.path, f.key)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out
