"""Invariant analyzer (ISSUE 8): static AST lint + runtime tripwire.

The contracts that keep seven PRs of concurrency, donation, and parity
machinery correct live here as executable checks instead of docstring
folklore:

    DCG001  collectives only on the dispatch thread   analysis/threads.py
    DCG002  no donating non-XLA-owned buffers         analysis/donation.py
    DCG003  shard_map only via utils/backend shim     analysis/hygiene.py
    DCG004  event keys declared + gated (parity)      analysis/parity.py
    DCG005  no wall-clock/host-RNG in traced bodies   analysis/hygiene.py
    DCG006  retry-wrapped IO in services/checkpoint   analysis/hygiene.py

Surface: `python -m dcgan_tpu.analysis [--json] [--baseline FILE]
[paths...]` — exit 1 on any non-baselined finding. Per-line suppression:
`# dcg: disable=DCG005`. Committed exemptions: analysis/baseline.jsonl
(every entry carries a `why`). The runtime half is analysis/tripwire.py
(`DCGAN_THREAD_CHECKS=1`), armed across tier-1 by tests/conftest.py.
See docs/DESIGN.md §7b for the full invariant catalog.
"""

from dcgan_tpu.analysis.core import (  # noqa: F401
    Config,
    Finding,
    SourceFile,
    collect_sources,
    default_baseline_path,
    default_root,
    load_baseline,
    run_checks,
    split_baselined,
)
