"""Invariant analyzer: AST lint, runtime tripwire, semantic + protocol tiers.

The contracts that keep a dozen PRs of concurrency, donation, parity,
and coordination machinery correct live here as executable checks
instead of docstring folklore. Three static tiers plus a runtime
tripwire:

AST tier (ISSUE 8 — no imports of the code under analysis, milliseconds):

    DCG001  collectives only on the dispatch thread   analysis/threads.py
    DCG002  no donating non-XLA-owned buffers         analysis/donation.py
    DCG003  shard_map only via utils/backend shim     analysis/hygiene.py
    DCG004  event keys declared + gated (parity)      analysis/parity.py
    DCG005  no wall-clock/host-RNG in traced bodies   analysis/hygiene.py
    DCG006  retry-wrapped IO in services/checkpoint   analysis/hygiene.py
    DCG013  no host-local branch into a collective    analysis/protocol.py
    DCG014  stale `# dcg: disable` suppressions       analysis/core.py
    DCG015  stale baseline rows (--prune-baseline)    analysis/core.py

Semantic tier (ISSUE 11 — imports, builds, and `.lower()`s every program
the repo can dispatch on a canonical CPU topology; `--semantic`):

    DCG007  donation realized as input_output_aliases analysis/semantic.py
    DCG008  collective census + program manifest      analysis/semantic.py
    DCG009  retrace hazards + warmup-plan coverage    analysis/semantic.py
    DCG010  traced-body hygiene (callbacks/f64/...)   analysis/semantic.py
    DCG011  sharding-rule coverage + grad-spec parity analysis/semantic.py

Protocol tier (ISSUE 14 — N virtual processes through the REAL
coordination decision code over the knob x one-shot-fault lattice;
`--protocol`):

    DCG012  lockstep audit: termination + identical   analysis/protocol.py
            per-process collective schedules vs the   analysis/simulate.py
            committed analysis/protocol.lock.jsonl

Surface: `python -m dcgan_tpu.analysis [--semantic|--protocol|--all]
[--json] [--baseline FILE] [--prune-baseline] [paths...]` — exit 1 on
any non-baselined finding; `--all` runs the three tiers with per-tier
timing under one exit code (the consolidated tier-1 pin). Per-line
suppression (AST tier, real comment tokens only): `# dcg:
disable=DCG005`. Committed exemptions (all tiers):
analysis/baseline.jsonl (every entry carries a `why`). Committed
contracts: analysis/programs.lock.jsonl (`--semantic --write-manifest`)
and analysis/protocol.lock.jsonl (`--protocol --write-lock`) — any
unexplained drift is a DCG008/DCG012 finding. The runtime halves are
analysis/tripwire.py (`DCGAN_THREAD_CHECKS=1`, armed across tier-1 by
tests/conftest.py) and the chaos drill's protocol replay
(`DCGAN_PROTOCOL_LOG`: the live mh-sigterm-stop collective sequence must
equal the committed simulator schedule). See docs/DESIGN.md §7b/§7c/§7d
for the invariant catalog.
"""

from dcgan_tpu.analysis.core import (  # noqa: F401
    Config,
    Finding,
    SourceFile,
    collect_sources,
    default_baseline_path,
    default_root,
    load_baseline,
    run_checks,
    split_baselined,
)
