"""Invariant analyzer: static AST lint, runtime tripwire, semantic tier.

The contracts that keep ten PRs of concurrency, donation, and parity
machinery correct live here as executable checks instead of docstring
folklore. Two static tiers plus a runtime tripwire:

AST tier (ISSUE 8 — no imports of the code under analysis, milliseconds):

    DCG001  collectives only on the dispatch thread   analysis/threads.py
    DCG002  no donating non-XLA-owned buffers         analysis/donation.py
    DCG003  shard_map only via utils/backend shim     analysis/hygiene.py
    DCG004  event keys declared + gated (parity)      analysis/parity.py
    DCG005  no wall-clock/host-RNG in traced bodies   analysis/hygiene.py
    DCG006  retry-wrapped IO in services/checkpoint   analysis/hygiene.py

Semantic tier (ISSUE 11 — imports, builds, and `.lower()`s every program
the repo can dispatch on a canonical CPU topology; `--semantic`):

    DCG007  donation realized as input_output_aliases analysis/semantic.py
    DCG008  collective census + program manifest      analysis/semantic.py
    DCG009  retrace hazards + warmup-plan coverage    analysis/semantic.py
    DCG010  traced-body hygiene (callbacks/f64/...)   analysis/semantic.py

Surface: `python -m dcgan_tpu.analysis [--semantic] [--json] [--baseline
FILE] [paths...]` — exit 1 on any non-baselined finding. Per-line
suppression (AST tier): `# dcg: disable=DCG005`. Committed exemptions
(both tiers): analysis/baseline.jsonl (every entry carries a `why`). The
semantic tier's committed contract is analysis/programs.lock.jsonl
(program name -> call shapes -> jaxpr fingerprint -> collective census ->
donation map), regenerated via `--semantic --write-manifest`; any
unexplained drift is a DCG008 finding. The runtime half is
analysis/tripwire.py (`DCGAN_THREAD_CHECKS=1`), armed across tier-1 by
tests/conftest.py. See docs/DESIGN.md §7b/§7c for the invariant catalog.
"""

from dcgan_tpu.analysis.core import (  # noqa: F401
    Config,
    Finding,
    SourceFile,
    collect_sources,
    default_baseline_path,
    default_root,
    load_baseline,
    run_checks,
    split_baselined,
)
