"""Semantic tier (ISSUE 11): contracts checked in the LOWERED programs.

The AST tier (core.py + the DCG001-006 checkers) polices source without
importing it; this tier deliberately does the opposite — it imports,
builds, and `.lower()`s every program the repo can dispatch, on CPU at a
small preset, and checks the contracts that only exist after tracing:

    DCG007  donation realized as aliasing     check_donation
    DCG008  collective census vs the manifest check_manifest/check_transports
    DCG009  retrace hazards + warmup coverage check_warmup_coverage/check_retrace
    DCG010  traced-body hygiene               check_hygiene
    DCG011  sharding-rule spec coverage       check_spec_coverage

The enumeration is the repo's real dispatch surface: both ParallelTrain
backends' `programs` dicts through the AOT warmup plan (train/warmup.py —
including the k=1 tail, the `steps_per_call` scan, and the LR-backoff
rebuild variants), the `--pipeline_gd` stage programs, and the serving
plane's bucket-ladder sampler rungs (serve/buckets.py). Host-side
coordination transports (`process_allgather` is opaque to `.lower()`)
join the manifest as declared rows from
train/coordination.py::TRANSPORT_CENSUS.

Everything is computed on one canonical topology — CPU, 2 virtual
devices, a 2-way "data" mesh, partitionable threefry — because the
committed manifest (analysis/programs.lock.jsonl) is byte-reproducible by
contract. Two devices, not one: collectives over a size-1 axis are elided
at trace time, so a 1-device census would be structurally empty. The CLI
(`python -m dcgan_tpu.analysis --semantic`) arranges the topology before
jax initializes; in-process callers must already satisfy it
(tests/conftest.py's 8-virtual-device env does — the mesh only takes the
first two devices, and the jaxprs are identical).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dcgan_tpu.analysis import manifest as manifest_lib
from dcgan_tpu.analysis.core import Finding

SEMANTIC_CHECKS = ("DCG007", "DCG008", "DCG009", "DCG010", "DCG011")

#: devices the canonical topology forces / the enumeration's mesh uses
CANONICAL_DEVICES = 2

#: serve bucket ladder top rung for the enumeration (granule = the data
#: axis, so the default doubling ladder is 2, 4, 8 — three compiled rungs,
#: the shape set `serve.buckets.build_ladder` produces for this preset)
SERVE_MAX_BATCH = 8

#: jaxpr primitive -> canonical census op. `psum2`/`all_gather_invariant`
#: are the names the experimental shard_map check_rep rewriter gives the
#: user-written collectives in this container's jax 0.4.37 — same ops,
#: rewritten for replication tracking.
CENSUS_PRIMS = {
    "psum": "psum", "psum2": "psum",
    "all_gather": "all_gather", "all_gather_invariant": "all_gather",
    "reduce_scatter": "reduce_scatter", "psum_scatter": "reduce_scatter",
    "ppermute": "ppermute", "all_to_all": "all_to_all",
    "pmax": "pmax", "pmin": "pmin",
}

#: DCG010: host-callback primitives — a callback inside a dispatched
#: program re-enters Python from the runtime (ordering hazards against the
#: async dispatch stream, catastrophic on real meshes)
CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                  "host_callback_call", "outside_call", "python_callback"}

#: DCG010: explicit transfer primitives inside traced code
TRANSFER_PRIMS = {"device_put"}

#: DCG009: closure-captured consts above this element count are flagged —
#: an array baked into the program bloats every retrace and defeats the
#: persistent-cache key (the array's VALUE is in the HLO)
CONST_SIZE_LIMIT = 64

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")

#: where findings for each enumeration group anchor
GROUP_PATHS = {
    "gspmd": "dcgan_tpu/parallel/api.py",
    "shard_map": "dcgan_tpu/parallel/shard_map_backend.py",
    "serve": "dcgan_tpu/serve/buckets.py",
    "coordination": "dcgan_tpu/train/coordination.py",
    "elastic": "dcgan_tpu/elastic/rules.py",
}


def ensure_semantic_platform() -> None:
    """Arrange the canonical topology. Must run before jax initializes —
    the CLI calls it first; tools embedding the tier should too."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None or int(m.group(1)) < CANONICAL_DEVICES:
        # no ambient count, or one too small for the census (an ambient
        # `=1` is common in CPU dev shells and would elide every
        # collective at trace time) — rewrite it; a LARGER ambient count
        # (the 8-device test env) is left alone, the mesh only takes the
        # first CANONICAL_DEVICES devices either way
        if m is not None:
            flags = flags.replace(m.group(0), "")
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{CANONICAL_DEVICES}").strip()
    import jax

    # the ambient environment may have force-selected a platform at
    # interpreter startup (config beats env var) — override it back
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)


def _require_platform() -> None:
    """The enumeration refuses to run on a non-canonical topology rather
    than produce fingerprints that can never match the manifest."""
    import jax

    devs = jax.devices()
    problems = []
    if devs[0].platform != "cpu":
        problems.append(f"platform is {devs[0].platform!r}, need cpu")
    if len(devs) < CANONICAL_DEVICES:
        problems.append(f"{len(devs)} device(s), need >= "
                        f"{CANONICAL_DEVICES} (collectives over a size-1 "
                        "axis are elided at trace time)")
    if not jax.config.jax_threefry_partitionable:
        problems.append("jax_threefry_partitionable is off (RNG lowering "
                        "differs, fingerprints cannot match)")
    if problems:
        raise RuntimeError(
            "semantic tier needs the canonical topology — "
            + "; ".join(problems)
            + ". Run via `python -m dcgan_tpu.analysis --semantic` (it "
            "arranges the environment before jax initializes).")


def small_config(backend: str = "gspmd", pipeline: bool = False,
                 zero: int = 1, precision: str = "",
                 pallas_fused: bool = False, overlap: str = "off"):
    """The small CPU preset every program is lowered at: tiny dcgan16
    model, global batch 8 over the 2-way data mesh, every optional
    program's knob armed (sampler / probe / summarize / rollback with LR
    backoff) so the warmup plan enumerates the full dispatch surface.
    `zero` selects the ZeRO stage (ISSUE 13) — the 2-way data mesh is
    exactly the canonical topology stages >= 2 need. `precision` /
    `pallas_fused` select the reduced-precision policy and the fused
    Pallas conv(+BN+act) blocks (ISSUE 17); the fused kernels lower in
    interpreter mode on CPU so the fingerprints are device-independent.
    `overlap` selects the collective overlap plane (ISSUE 20) for the
    `@overlap`/`@prefetch` variant rows."""
    from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig

    return TrainConfig(
        model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                          compute_dtype="float32",
                          use_pallas=pallas_fused,
                          pallas_fused=pallas_fused),
        mesh=MeshConfig(data=CANONICAL_DEVICES, zero_stage=zero),
        batch_size=8,
        backend=backend,
        precision=precision,
        comm_overlap=overlap,
        # pipeline_gd is config-validated to steps_per_call=1; the plain
        # variant scans k=2 so the multi_step program joins the manifest
        steps_per_call=1 if pipeline else 2,
        pipeline_gd=pipeline,
        sample_every_steps=100,
        activation_summary_steps=100,
        nan_check_steps=100,
        nan_policy="rollback",
        rollback_snapshot_steps=100,
        rollback_lr_backoff=0.5,
        tensorboard=False)


def progressive_config(backend: str = "gspmd"):
    """The canonical progressive schedule the semantic tier enumerates
    (ISSUE 15): the headline 64 -> 128 -> 256 ladder at the small feature
    dims, fade armed so the per-phase blend programs join the audit.
    Every phase's step program is lowered and fingerprinted (`@r64` /
    `@r128` / `@r256` rows), so the donation audit (DCG007) holds for the
    grown conv stacks and the warmup-coverage check (DCG009) proves the
    switch dispatches only planned programs."""
    from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig

    return TrainConfig(
        model=ModelConfig(output_size=256, gf_dim=8, df_dim=8,
                          compute_dtype="float32"),
        mesh=MeshConfig(data=CANONICAL_DEVICES),
        batch_size=8,
        backend=backend,
        progressive="64:4,128:4,256:*",
        progressive_fade_steps=2,
        sample_every_steps=0,
        activation_summary_steps=0,
        nan_check_steps=100,
        tensorboard=False)


@dataclasses.dataclass(frozen=True)
class ProgramAudit:
    """Everything the checkers need about one lowered program."""

    name: str              # "gspmd::train_step", "serve::sampler@b4", ...
    path: str              # repo-relative path findings anchor to
    args: Tuple[str, ...]  # short per-argument signatures
    fingerprint: str       # sha256[:16] of the sanitized jaxpr text
    collectives: Dict[str, int]
    donation: Optional[Dict[str, object]]   # None when nothing is donated
    expect_donation: bool
    consts: Tuple[Tuple[str, int, str, bool], ...]  # (label, size, dtype,
                                                    #  weak_type)
    callbacks: Tuple[str, ...]   # callback primitive names found
    transfers: Tuple[str, ...]   # transfer primitive names found
    f64_prims: Tuple[str, ...]   # primitives with float64/complex128 out
    cadence: str = ""

    @property
    def base(self) -> str:
        """Program name without the group / @shape qualifiers."""
        return self.name.split("::", 1)[-1].split("@", 1)[0]


def _walk_jaxpr(jaxpr, visit) -> None:
    """visit(eqn) over every equation, recursing into sub-jaxprs (scan
    bodies, pjit calls, shard_map bodies, cond branches, custom-vjp
    closures — anything whose params carry a Jaxpr/ClosedJaxpr)."""
    for eqn in jaxpr.eqns:
        visit(eqn)
        for v in eqn.params.values():
            for j in (v if isinstance(v, (list, tuple)) else (v,)):
                inner = getattr(j, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    _walk_jaxpr(inner, visit)
                elif hasattr(j, "eqns"):
                    _walk_jaxpr(j, visit)


def _arg_sig(x) -> str:
    import jax

    leaves = jax.tree_util.tree_leaves(x)
    if len(leaves) != 1 or leaves[0] is not x:
        return f"tree({len(leaves)} leaves)"
    try:
        from jax.api_util import shaped_abstractify
    except ImportError:  # moved in newer jax
        from jax._src.api_util import shaped_abstractify
    return shaped_abstractify(leaves[0]).str_short()


def _alias_param_numbers(hlo_text: str) -> Set[int]:
    """Entry-parameter numbers in the compiled module's
    `input_output_alias={ {out}: (param, {index}, kind), ... }` map."""
    i = hlo_text.find("input_output_alias={")
    if i < 0:
        return set()
    j = i + len("input_output_alias=")
    depth = 0
    end = None
    for k in range(j, len(hlo_text)):
        if hlo_text[k] == "{":
            depth += 1
        elif hlo_text[k] == "}":
            depth -= 1
            if depth == 0:
                end = k + 1
                break
    if end is None:
        return set()
    return {int(m.group(1)) for m in
            re.finditer(r":\s*\(\s*(\d+)\s*,", hlo_text[j:end])}


def audit_callable(name: str, fn, args: tuple, *, path: str,
                   expect_donation: bool = False,
                   cadence: str = "") -> ProgramAudit:
    """Trace + lower (+ compile, iff anything is donated) one program and
    extract the audited facts. `fn` is a jitted callable (tripwire
    wrappers forward `.trace`/`.lower`); `args` are example arguments —
    ShapeDtypeStructs are fine, nothing is executed."""
    import jax.tree_util as jtu

    traced = fn.trace(*args)
    closed = traced.jaxpr

    census: Dict[str, int] = {}
    callbacks: List[str] = []
    transfers: List[str] = []
    f64: List[str] = []

    def visit(eqn):
        prim = eqn.primitive.name
        op = CENSUS_PRIMS.get(prim)
        if op is not None:
            census[op] = census.get(op, 0) + 1
        if prim in CALLBACK_PRIMS or (prim not in CENSUS_PRIMS
                                      and "callback" in prim):
            callbacks.append(prim)
        if prim in TRANSFER_PRIMS:
            transfers.append(prim)
        for ov in eqn.outvars:
            dt = getattr(getattr(ov, "aval", None), "dtype", None)
            if dt is not None and str(dt) in ("float64", "complex128"):
                f64.append(prim)
                break

    _walk_jaxpr(closed.jaxpr, visit)

    consts: List[Tuple[str, int, str, bool]] = []
    for i, c in enumerate(closed.consts):
        aval = getattr(c, "aval", None)
        shape = tuple(getattr(c, "shape", ()))
        size = 1
        for d in shape:
            size *= int(d)
        dtype = str(getattr(c, "dtype", "?"))
        weak = bool(getattr(aval, "weak_type", False))
        label = f"const{i}:{dtype}{list(shape)}"
        consts.append((label, size, dtype, weak))

    fingerprint = hashlib.sha256(
        _ADDR_RE.sub("0x", str(closed)).encode()).hexdigest()[:16]

    import warnings

    with warnings.catch_warnings():
        # the audit below IS the actionable form of this lowering warning
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        # lower the Traced we already have — fn.lower(*args) would re-trace
        # every program from scratch (tracing dominates enumeration cost)
        lowered = traced.lower()
    flat_info, _ = jtu.tree_flatten(lowered.args_info)
    donated = [i for i, a in enumerate(flat_info) if a.donated]
    donation: Optional[Dict[str, object]] = None
    if donated:
        labels = [jtu.keystr(p) for p, _ in
                  jtu.tree_flatten_with_path(lowered.args_info)[0]]
        try:
            kept = sorted(lowered._lowering.compile_args["kept_var_idx"])
        except Exception:  # internals moved: assume nothing was pruned
            kept = list(range(len(flat_info)))
        compiled = lowered.compile()
        aliased_flat = {kept[p] for p in
                        _alias_param_numbers(compiled.as_text())
                        if p < len(kept)}
        kept_set = set(kept)
        donation = {
            "donated": len(donated),
            "aliased": len(aliased_flat & set(donated)),
            "pruned": sum(1 for i in donated if i not in kept_set),
            "unaliased": sorted(labels[i] for i in donated
                                if i in kept_set
                                and i not in aliased_flat),
        }

    return ProgramAudit(
        name=name, path=path, args=tuple(_arg_sig(a) for a in args),
        fingerprint=fingerprint,
        collectives=dict(sorted(census.items())), donation=donation,
        expect_donation=expect_donation, consts=tuple(consts),
        callbacks=tuple(sorted(set(callbacks))),
        transfers=tuple(sorted(set(transfers))),
        f64_prims=tuple(sorted(set(f64))), cadence=cadence)


@dataclasses.dataclass(frozen=True)
class CoverageRow:
    """One config variant's dispatch surface vs its warmup plan (DCG009):
    `programs` is the ParallelTrain programs-dict key set, `plan` the
    warmup plan's row names, `must_cover` the names the trainer loop
    dispatches at THAT config (so the plan must contain them)."""

    variant: str
    path: str
    programs: frozenset
    plan: Tuple[str, ...]
    must_cover: frozenset


def _base(name: str) -> str:
    return name.split("@", 1)[0]


def enumerate_audits() -> Tuple[List[ProgramAudit], List[CoverageRow]]:
    """Lower the full dispatch surface at the small preset. Order is
    deterministic; the returned audits are the manifest's program rows."""
    _require_platform()
    import jax
    import jax.numpy as jnp

    from dcgan_tpu.parallel import make_mesh, make_parallel_train
    from dcgan_tpu.parallel.api import DONATED_PROGRAMS
    from dcgan_tpu.serve.buckets import build_ladder, sampler_plan
    from dcgan_tpu.train import warmup

    devices = jax.devices()[:CANONICAL_DEVICES]
    audits: List[ProgramAudit] = []
    coverage: List[CoverageRow] = []
    serve_rows: List[Tuple[str, object, tuple]] = []

    for backend in ("gspmd", "shard_map"):
        path = GROUP_PATHS[backend]
        cfg = small_config(backend)
        mesh = make_mesh(cfg.mesh, devices)
        pt = make_parallel_train(cfg, mesh)
        state = warmup.state_example(pt)
        z = jax.ShapeDtypeStruct((cfg.batch_size, cfg.model.z_dim),
                                 jnp.float32)
        plan, _pt_backoff = warmup.build_warmup_plan(
            cfg, pt, state, sample_z=z, eval_z=z,
            make_backoff_pt=lambda c, _m=mesh: make_parallel_train(c, _m))
        rows = [("init", pt.programs["init"], (jax.random.key(0),))]
        rows += [(n, f, a) for n, f, a in plan]

        cfg_p = small_config(backend, pipeline=True)
        pt_p = make_parallel_train(cfg_p, mesh)
        plan_p, _bk = warmup.build_warmup_plan(
            cfg_p, pt_p, state, sample_z=None, eval_z=None,
            make_backoff_pt=lambda c, _m=mesh: make_parallel_train(c, _m))
        stages = ("gen_fakes", "d_update", "g_update")
        rows += [(n, f, a) for n, f, a in plan_p if _base(n) in stages]

        coverage.append(CoverageRow(
            variant=backend, path=path,
            programs=frozenset(pt.programs),
            plan=tuple(n for n, _, _ in plan),
            must_cover=frozenset(
                {"train_step", f"multi_step@k{cfg.steps_per_call}",
                 "sampler", "eval_losses", "summarize", "state_copy"})))
        coverage.append(CoverageRow(
            variant=f"{backend}+pipeline_gd", path=path,
            programs=frozenset(pt_p.programs),
            plan=tuple(n for n, _, _ in plan_p),
            must_cover=frozenset(stages)))

        # ZeRO-2/3 variants (ISSUE 13): the state-sharded step programs —
        # the census intentionally changes (shard_map gains explicit
        # psum_scatter/all_gather rows; gspmd rows stay "0 explicit", the
        # partitioner inserts theirs) and the donation audit must hold for
        # every data-SHARDED donated leaf in both backends, including the
        # LR-backoff rebuild variants. Only the step-family rows are
        # traced (sampler/probe/summarize differ from the stage-1 rows
        # only by the state gathers, which the stage rows already cover);
        # the coverage rows still see the FULL warmup plan.
        step_bases = {"train_step", "multi_step"}
        for stage in (2, 3):
            cfg_z = small_config(backend, zero=stage)
            pt_z = make_parallel_train(cfg_z, mesh)
            state_z = warmup.state_example(pt_z)
            plan_z, _bkz = warmup.build_warmup_plan(
                cfg_z, pt_z, state_z, sample_z=z, eval_z=z,
                make_backoff_pt=lambda c, _m=mesh: make_parallel_train(
                    c, _m))
            cfg_zp = small_config(backend, pipeline=True, zero=stage)
            pt_zp = make_parallel_train(cfg_zp, mesh)
            plan_zp, _bkzp = warmup.build_warmup_plan(
                cfg_zp, pt_zp, warmup.state_example(pt_zp), sample_z=None,
                eval_z=None,
                make_backoff_pt=lambda c, _m=mesh: make_parallel_train(
                    c, _m))
            zrows = [(n, f, a) for n, f, a in plan_z
                     if _base(n) in step_bases]
            zrows += [(n, f, a) for n, f, a in plan_zp
                      if _base(n) in stages]
            coverage.append(CoverageRow(
                variant=f"{backend}+zero{stage}", path=path,
                programs=frozenset(pt_z.programs),
                plan=tuple(n for n, _, _ in plan_z),
                must_cover=frozenset(
                    {"train_step", f"multi_step@k{cfg_z.steps_per_call}",
                     "sampler", "eval_losses", "summarize",
                     "state_copy"})))
            coverage.append(CoverageRow(
                variant=f"{backend}+pipeline_gd+zero{stage}", path=path,
                programs=frozenset(pt_zp.programs),
                plan=tuple(n for n, _, _ in plan_zp),
                must_cover=frozenset(stages)))
            for n, f, a in zrows:
                cadence = ""
                if n == "train_step":
                    cadence = (
                        f"every step when `--zero_stage {stage}` "
                        + ("(grads reduce-scatter onto the data axis, one "
                           "fused all-gather rebuilds params per update)"
                           if stage == 2 else
                           "(stage 2's pattern + params resident sharded; "
                           "just-in-time all-gather per forward)"))
                audits.append(audit_callable(
                    f"{backend}::{n}@zero{stage}", f, a, path=path,
                    expect_donation=_base(n) in DONATED_PROGRAMS,
                    cadence=cadence))

        # Collective-overlap variants (ISSUE 20, DESIGN §6n): shard_map
        # only — the bucket/prefetch restructuring changes the lowered
        # program only where collectives are hand-placed (gspmd's half
        # of the overlap plane is async-scheduler XLA flags; its
        # constraint-hook program is unchanged and already audited by
        # the @zero rows above). The SHRUNKEN census on the @overlap
        # rows is the tentpole's headline proof — one collective per
        # dtype bucket instead of one per leaf — and the @prefetch rows
        # pin the staged-gather structure (same all-gather count as
        # "off": the barrier chain moves gathers, it does not merge
        # them). Donation must hold for every variant, and the coverage
        # rows extend the DCG009 warmup-coverage check to the new
        # plans (the zero-recompile contract under `--comm_overlap`).
        if backend == "shard_map":
            for o_stage, o_mode in ((2, "bucket"), (3, "bucket"),
                                    (3, "prefetch")):
                o_tag = "overlap" if o_mode == "bucket" else "prefetch"
                cfg_o = small_config(backend, zero=o_stage,
                                     overlap=o_mode)
                pt_o = make_parallel_train(cfg_o, mesh)
                plan_o, _bko = warmup.build_warmup_plan(
                    cfg_o, pt_o, warmup.state_example(pt_o), sample_z=z,
                    eval_z=z,
                    make_backoff_pt=lambda c, _m=mesh:
                        make_parallel_train(c, _m))
                cfg_op = small_config(backend, pipeline=True,
                                      zero=o_stage, overlap=o_mode)
                pt_op = make_parallel_train(cfg_op, mesh)
                plan_op, _bkop = warmup.build_warmup_plan(
                    cfg_op, pt_op, warmup.state_example(pt_op),
                    sample_z=None, eval_z=None,
                    make_backoff_pt=lambda c, _m=mesh:
                        make_parallel_train(c, _m))
                coverage.append(CoverageRow(
                    variant=f"{backend}+zero{o_stage}+{o_mode}",
                    path=path, programs=frozenset(pt_o.programs),
                    plan=tuple(n for n, _, _ in plan_o),
                    must_cover=frozenset(
                        {"train_step",
                         f"multi_step@k{cfg_o.steps_per_call}",
                         "sampler", "eval_losses", "summarize",
                         "state_copy"})))
                coverage.append(CoverageRow(
                    variant=(f"{backend}+pipeline_gd+zero{o_stage}"
                             f"+{o_mode}"),
                    path=path, programs=frozenset(pt_op.programs),
                    plan=tuple(n for n, _, _ in plan_op),
                    must_cover=frozenset(stages)))
                orows = [(n, f, a) for n, f, a in plan_o
                         if _base(n) in step_bases]
                orows += [(n, f, a) for n, f, a in plan_op
                          if _base(n) in stages]
                for n, f, a in orows:
                    cadence = ""
                    if n == "train_step":
                        cadence = (
                            f"every step when `--comm_overlap bucket` "
                            f"at `--zero_stage {o_stage}` (per-leaf "
                            "reduce-scatter/all-gather packed into ONE "
                            "collective per dtype bucket; each bucket's "
                            "reduce-scatter issues as its cotangents "
                            "complete)"
                            if o_mode == "bucket" else
                            "every step when `--comm_overlap prefetch` "
                            "(bucket's grad plan + layer-ahead staged "
                            "param gathers: gather i+1 overlaps "
                            "compute i via an optimization_barrier "
                            "chain)")
                    audits.append(audit_callable(
                        f"{backend}::{n}@zero{o_stage}@{o_tag}", f, a,
                        path=path,
                        expect_donation=_base(n) in DONATED_PROGRAMS,
                        cadence=cadence))

        # Fused-kernel / reduced-precision variants (ISSUE 17): the
        # @pallas_fused rows swap every interior conv/BN/act stack for
        # the fused Pallas GEMM programs — the census intentionally
        # changes (the per-shard batch moments ride explicit `psum` rows
        # in BOTH backends; the gspmd path routes the opaque pallas_call
        # through an inner shard_map, so even the "0 explicit" backend
        # gains them) — and the @bf16 rows lower the reduced-precision
        # policy (bf16 params/compute, f32 master Adam mu). Only the
        # step-family rows are traced (sampler/probe/summarize differ
        # only by kernel routing / dtype, which the step rows already
        # fingerprint). The donation audit must hold for both: note the
        # bf16 lowering emits a conservative "donated buffers were not
        # usable" warning for the small (C,)-shaped bf16 leaves, but the
        # compiled alias map realizes every donation (unaliased=[]) —
        # the structured audit below, not the warning, is the gate.
        for vtag, vkw in (("pallas_fused", {"pallas_fused": True}),
                          ("bf16", {"precision": "bf16"})):
            cfg_v = small_config(backend, **vkw)
            pt_v = make_parallel_train(cfg_v, mesh)
            plan_v, _bkv = warmup.build_warmup_plan(
                cfg_v, pt_v, warmup.state_example(pt_v), sample_z=z,
                eval_z=z,
                make_backoff_pt=lambda c, _m=mesh: make_parallel_train(
                    c, _m))
            coverage.append(CoverageRow(
                variant=f"{backend}+{vtag}", path=path,
                programs=frozenset(pt_v.programs),
                plan=tuple(n for n, _, _ in plan_v),
                must_cover=frozenset(
                    {"train_step", f"multi_step@k{cfg_v.steps_per_call}",
                     "sampler", "eval_losses", "summarize",
                     "state_copy"})))
            for n, f, a in plan_v:
                if _base(n) not in step_bases:
                    continue
                cadence = ""
                if n == "train_step":
                    cadence = (
                        "every step when `--pallas_fused` (interior "
                        "conv⊕BN⊕act stages fused into Pallas GEMM "
                        "kernels; per-shard moments psum explicitly)"
                        if vtag == "pallas_fused" else
                        "every step when `--precision bf16` (bf16 "
                        "params+compute, f32 master Adam mu; fp8 adds "
                        "operand fake-quant at >=128px stages only)")
                audits.append(audit_callable(
                    f"{backend}::{n}@{vtag}", f, a, path=path,
                    expect_donation=_base(n) in DONATED_PROGRAMS,
                    cadence=cadence))

        for n, f, a in rows:
            cadence = ""
            if n == "train_step":
                cadence = ("every step (default `steps_per_call`=1; a "
                           "scanned run dispatches `multi_step`, census "
                           "identical ×k)")
            audits.append(audit_callable(
                f"{backend}::{n}", f, a, path=path,
                expect_donation=_base(n) in DONATED_PROGRAMS,
                cadence=cadence))

        # Progressive-resolution variants (ISSUE 15): the canonical
        # 64->128->256 schedule's per-phase step programs, named @r<res>
        # (EVERY phase suffixed — the base rows above are a different
        # model config, so the plain names must not collide). The plan
        # comes from the same PhaseRuntime the trainer warms, so the
        # coverage row proves a mid-run switch dispatches only planned
        # programs; the fade blends (phase > 0, non-donating) are audited
        # once under gspmd (the program is backend-agnostic).
        from dcgan_tpu.progressive import PhaseRuntime, parse_schedule

        cfg_pr = progressive_config(backend)
        rt = PhaseRuntime(
            cfg_pr, mesh,
            parse_schedule(cfg_pr.progressive, model=cfg_pr.model,
                           batch_size=cfg_pr.batch_size,
                           max_steps=cfg_pr.max_steps,
                           fade_steps=cfg_pr.progressive_fade_steps),
            cfg_pr.max_steps,
            make_pt=lambda c, m: make_parallel_train(c, m))
        plan_pr = rt.build_warmup_plan(warmup.state_example(rt.pt))
        coverage.append(CoverageRow(
            variant=f"{backend}+progressive", path=path,
            programs=frozenset(rt.pt.programs),
            plan=tuple(n for n, _, _ in plan_pr),
            must_cover=frozenset(
                {"train_step", "init@r128", "train_step@r128",
                 "state_copy@r128", "fade@r128", "init@r256",
                 "train_step@r256", "state_copy@r256", "fade@r256"})))
        res0 = rt.schedule.phases[0].resolution
        for n, f, a in plan_pr:
            base_n = _base(n)
            if base_n not in ("train_step", "fade"):
                continue
            if base_n == "fade" and backend != "gspmd":
                continue
            nm = n if "@" in n else f"{n}@r{res0}"
            audits.append(audit_callable(
                f"{backend}::{nm}", f, a, path=path,
                expect_donation=base_n in DONATED_PROGRAMS,
                cadence=f"every step of its phase under `--progressive "
                        f"\"64:N,128:N,256:*\"`" if base_n == "train_step"
                        else "per-step inside a fade window "
                             "(`--progressive_fade_steps`)"))

        # Live-elasticity variants (ISSUE 18): the target-submesh step
        # programs a preemption-notice-driven switch lands on, named
        # @t<data>x<model> by the same LiveTopologyRuntime the trainer
        # warms — so the coverage row proves a live shrink dispatches only
        # planned programs (the AOT-warm-both-topologies contract behind
        # compile_requests_delta == 0 across a switch). The launch
        # topology's rows keep their plain names and are NOT re-audited
        # (same programs as the base rows above); only the @t1x1 step row
        # is traced — sampler/probe rows differ from the base ones only by
        # mesh extent, which the step row already fingerprints.
        from dcgan_tpu.elastic.live import LiveTopologyRuntime

        cfg_le = dataclasses.replace(cfg, elastic_target_devices=1,
                                     sample_every_steps=0)
        rt_le = LiveTopologyRuntime(
            cfg_le, mesh, make_pt=lambda c, m: make_parallel_train(c, m),
            launch_pt=pt)
        plan_le = rt_le.build_warmup_plan(warmup.state_example(rt_le.pt))
        sub_tag = rt_le.tag(1)
        coverage.append(CoverageRow(
            variant=f"{backend}+live_elastic", path=path,
            programs=frozenset(rt_le.surface(1)[2].programs),
            plan=tuple(n for n, _, _ in plan_le),
            must_cover=frozenset(
                {"train_step", f"init@{sub_tag}",
                 f"train_step@{sub_tag}",
                 f"multi_step@k{cfg_le.steps_per_call}@{sub_tag}",
                 f"state_copy@{sub_tag}"})))
        for n, f, a in plan_le:
            if _base(n) != "train_step" or not n.endswith(f"@{sub_tag}"):
                continue
            audits.append(audit_callable(
                f"{backend}::{n}", f, a, path=path,
                expect_donation=_base(n) in DONATED_PROGRAMS,
                cadence=f"every step after a notice-driven live shrink "
                        f"onto `--elastic_target_devices 1` (grow-back "
                        f"returns to the plain rows)"))

        if backend == "gspmd":
            # the serving plane's rungs: the checkpoint-source sampler at
            # every bucket of the default doubling ladder (granule = the
            # data-axis size, the BucketLadder contract)
            ladder = build_ladder(SERVE_MAX_BATCH, mesh.shape["data"])
            serve_rows = sampler_plan(pt.sample, ladder, cfg.model.z_dim,
                                      state=state)

    for n, f, a in serve_rows:
        audits.append(audit_callable(
            f"serve::{n}", f, a, path=GROUP_PATHS["serve"],
            expect_donation=False))
    return audits, coverage


# -- checkers ----------------------------------------------------------------

def check_donation(audits: Sequence[ProgramAudit]) -> List[Finding]:
    """DCG007: donation realized as aliasing, in both directions."""
    findings: List[Finding] = []
    for a in audits:
        if a.donation is None:
            if a.expect_donation:
                findings.append(Finding(
                    check="DCG007", path=a.path, line=0, symbol=a.name,
                    key=f"undonated:{a.name}",
                    message=f"{a.name} is declared a donating program "
                            "(parallel/api.py::DONATED_PROGRAMS) but its "
                            "compiled form donates nothing — the state "
                            "update silently stopped being in-place"))
            continue
        if not a.expect_donation:
            findings.append(Finding(
                check="DCG007", path=a.path, line=0, symbol=a.name,
                key=f"undeclared-donor:{a.name}",
                message=f"{a.name} donates buffers but is not declared in "
                        "parallel/api.py::DONATED_PROGRAMS — undeclared "
                        "donors bypass the donation-safety discipline "
                        "(DESIGN §6d); declare it and regenerate the "
                        "manifest"))
        for label in a.donation.get("unaliased", ()):
            findings.append(Finding(
                check="DCG007", path=a.path, line=0, symbol=a.name,
                key=f"unaliased:{a.name}:{label}",
                message=f"{a.name}: donated argument {label} is NOT "
                        "realized as an input_output_aliases pair in the "
                        "compiled executable — a silent copy every "
                        "dispatch, and under deserialized-executable "
                        "donation (DESIGN §6d) a latent heap hazard"))
    return findings


def check_transports() -> List[Finding]:
    """DCG008 (registry half): every declared transport row must name a
    live callable in train/coordination.py that the runtime tripwire
    wraps — a renamed transport must fail here, not silently drop out of
    the manifest."""
    from dcgan_tpu.analysis import tripwire
    from dcgan_tpu.train import coordination

    findings: List[Finding] = []
    path = GROUP_PATHS["coordination"]
    for tname, (fn_name, census, _cadence) in sorted(
            coordination.TRANSPORT_CENSUS.items()):
        name = f"coordination::{tname}"
        if not callable(getattr(coordination, fn_name, None)):
            findings.append(Finding(
                check="DCG008", path=path, line=0, symbol=name,
                key=f"transport:{tname}",
                message=f"TRANSPORT_CENSUS entry {tname!r} names "
                        f"coordination.{fn_name}, which does not exist — "
                        "the declared census no longer describes a live "
                        "transport"))
        if fn_name not in tripwire.WRAPPED_TRANSPORTS:
            findings.append(Finding(
                check="DCG008", path=path, line=0, symbol=name,
                key=f"transport-unwrapped:{tname}",
                message=f"transport {fn_name!r} (census entry {tname!r}) "
                        "is not in the runtime tripwire's wrap list — a "
                        "declared collective transport must also be "
                        "thread-policed (analysis/tripwire.py)"))
    return findings


def transport_records() -> List[manifest_lib.ProgramRecord]:
    from dcgan_tpu.train import coordination

    return [manifest_lib.ProgramRecord(
        name=f"coordination::{tname}", kind="transport",
        path=GROUP_PATHS["coordination"], args=(fn_name,),
        fingerprint="-", collectives=dict(census), donation=None,
        cadence=cadence)
        for tname, (fn_name, census, cadence) in
        sorted(coordination.TRANSPORT_CENSUS.items())]


def records_from(audits: Sequence[ProgramAudit]
                 ) -> List[manifest_lib.ProgramRecord]:
    return [manifest_lib.ProgramRecord(
        name=a.name, kind="program", path=a.path, args=a.args,
        fingerprint=a.fingerprint, collectives=dict(a.collectives),
        donation=a.donation, cadence=a.cadence)
        for a in audits] + transport_records()


def check_warmup_coverage(coverage: Sequence[CoverageRow]) -> List[Finding]:
    """DCG009 (coverage half): the warmup plan must enumerate what the
    loop dispatches — per variant (`must_cover` rows present verbatim)
    and per backend (every `programs`-dict entry except the pre-warmup
    `init` planned by SOME variant). Generalizes PR 7's test-pinned
    stage-coverage check to every program and both backends."""
    findings: List[Finding] = []
    planned_by_backend: Dict[str, Set[str]] = {}
    programs_by_backend: Dict[str, Tuple[str, Set[str]]] = {}
    for row in coverage:
        backend = row.variant.split("+", 1)[0]
        planned_by_backend.setdefault(backend, set()).update(
            _base(n) for n in row.plan)
        # UNION across the backend's variants: a program registered by
        # only one variant's construction must still be planned somewhere
        programs_by_backend.setdefault(backend, (row.path, set()))[1] \
            .update(row.programs)
        for want in sorted(row.must_cover):
            if want not in row.plan:
                findings.append(Finding(
                    check="DCG009", path=row.path, line=0,
                    symbol=f"{row.variant}::warmup_plan",
                    key=f"warmup-gap:{row.variant}:{want}",
                    message=f"[{row.variant}] the trainer loop dispatches "
                            f"{want!r} at this config but the warmup plan "
                            "does not enumerate it — its first live "
                            "dispatch would compile under an armed "
                            "watchdog deadline (DESIGN §6d)"))
    for backend, (path, programs) in sorted(programs_by_backend.items()):
        for prog in sorted(programs - {"init"}
                           - planned_by_backend.get(backend, set())):
            findings.append(Finding(
                check="DCG009", path=path, line=0,
                symbol=f"{backend}::warmup_plan",
                key=f"warmup-unplanned:{backend}:{prog}",
                message=f"[{backend}] ParallelTrain.programs[{prog!r}] is "
                        "dispatchable but no warmup-plan variant ever "
                        "enumerates it — AOT warmup cannot pre-compile "
                        "what the plan does not name"))
    return findings


def check_retrace(audits: Sequence[ProgramAudit]) -> List[Finding]:
    """DCG009 (hazard half): closure-captured constvars and weak-typed
    (python-scalar) leakage in the traced programs."""
    findings: List[Finding] = []
    for a in audits:
        for label, size, _dtype, weak in a.consts:
            if size > CONST_SIZE_LIMIT:
                findings.append(Finding(
                    check="DCG009", path=a.path, line=0, symbol=a.name,
                    key=f"const:{a.name}:{label}",
                    message=f"{a.name} closes over {label} ({size} "
                            "elements) as a baked-in constant — its VALUE "
                            "is part of the HLO, so every change retraces "
                            "and re-keys the persistent compile cache; "
                            "pass it as an argument instead"))
            elif weak:
                findings.append(Finding(
                    check="DCG009", path=a.path, line=0, symbol=a.name,
                    key=f"weak-const:{a.name}:{label}",
                    message=f"{a.name} closes over weak-typed {label} — a "
                            "leaked python scalar whose promotion "
                            "semantics differ from committed arrays; bind "
                            "it with an explicit dtype"))
    return findings


def check_hygiene(audits: Sequence[ProgramAudit]) -> List[Finding]:
    """DCG010: host callbacks, implicit f64 promotion, and explicit
    transfers inside the traced bodies."""
    findings: List[Finding] = []
    for a in audits:
        for prim in a.callbacks:
            findings.append(Finding(
                check="DCG010", path=a.path, line=0, symbol=a.name,
                key=f"callback:{a.name}:{prim}",
                message=f"{a.name} contains host callback {prim!r} — a "
                        "dispatched program re-entering Python has no "
                        "ordering against the async dispatch stream "
                        "(DESIGN §6b) and stalls the device on the host"))
        for prim in a.f64_prims:
            findings.append(Finding(
                check="DCG010", path=a.path, line=0, symbol=a.name,
                key=f"f64:{a.name}:{prim}",
                message=f"{a.name} computes in float64/complex128 "
                        f"(first at {prim!r}) — an implicit promotion "
                        "slipped in; TPUs emulate f64 at ~100x cost"))
        for prim in a.transfers:
            findings.append(Finding(
                check="DCG010", path=a.path, line=0, symbol=a.name,
                key=f"transfer:{a.name}:{prim}",
                message=f"{a.name} embeds transfer primitive {prim!r} "
                        "inside traced code — placement belongs to the "
                        "caller (shardings/donation), not the program "
                        "body"))
    return findings


#: DCG011: the model-family variants whose FULL train state (params, both
#: optimizer states, BN/SN state, EMA, step) must be rule-covered — the
#: structural union of what the repo can train: plain dcgan, dcgan with
#: attention + spectral norm + conditioning, the resnet family with
#: attention + SN, and stylegan with SN (its norm-free critic is the
#: resnet one). eval_shape only — no arrays, no lowering.
def spec_coverage_variants():
    from dcgan_tpu.config import ModelConfig, TrainConfig

    return (
        ("dcgan", TrainConfig(model=ModelConfig(
            output_size=16, gf_dim=8, df_dim=8,
            compute_dtype="float32"), batch_size=8)),
        ("dcgan+attn+sn+cond", TrainConfig(model=ModelConfig(
            output_size=32, gf_dim=8, df_dim=8, compute_dtype="float32",
            attn_res=16, spectral_norm="gd", num_classes=10),
            batch_size=8)),
        ("resnet+attn+sn", TrainConfig(model=ModelConfig(
            arch="resnet", output_size=32, gf_dim=8, df_dim=8,
            compute_dtype="float32", attn_res=16, spectral_norm="d"),
            batch_size=8, loss="hinge")),
        ("stylegan+sn", TrainConfig(model=ModelConfig(
            arch="stylegan", output_size=32, gf_dim=8, df_dim=8,
            compute_dtype="float32", spectral_norm="d"),
            batch_size=8, loss="hinge")),
    )


def check_spec_coverage() -> List[Finding]:
    """DCG011: every leaf of every model family's train state must match
    EXACTLY ONE row of the sharding-rule table (elastic/rules.py). An
    unmatched leaf means a new layer has no classified placement (the
    engine raises at run time — this catches it at lint time, for every
    family at once); a multiply-matched leaf means two rows compete and
    first-match order silently decides a spec — the checkpoint sidecar
    and the cross-topology restore both resolve through this table, so
    ambiguity here is placement nondeterminism there."""
    import jax

    from dcgan_tpu.elastic import rules
    from dcgan_tpu.train.steps import init_train_state

    findings: List[Finding] = []
    path = GROUP_PATHS["elastic"]
    for variant, cfg in spec_coverage_variants():
        shapes = jax.eval_shape(lambda k, c=cfg: init_train_state(k, c),
                                jax.random.key(0))
        for leaf_path, leaf in jax.tree_util.tree_flatten_with_path(
                shapes)[0]:
            p = rules.path_str(leaf_path)
            ndim = len(getattr(leaf, "shape", ()))
            hits = rules.matching_rules(p, ndim)
            if len(hits) == 1:
                continue
            if not hits:
                findings.append(Finding(
                    check="DCG011", path=path, line=0,
                    symbol=f"{variant}::state",
                    key=f"spec-unmatched:{variant}:{p}",
                    message=f"[{variant}] state leaf {p!r} (rank {ndim}) "
                            "matches NO row of PARTITION_RULES — an "
                            "unclassified placement; the engine would "
                            "raise at the first state_shardings call at "
                            "this config"))
            else:
                pats = [rules.PARTITION_RULES[i][0] for i in hits]
                findings.append(Finding(
                    check="DCG011", path=path, line=0,
                    symbol=f"{variant}::state",
                    key=f"spec-ambiguous:{variant}:{p}",
                    message=f"[{variant}] state leaf {p!r} (rank {ndim}) "
                            f"matches {len(hits)} rules ({pats}) — "
                            "first-match order is silently deciding its "
                            "spec; make the patterns disjoint"))
        # grad-spec derivation (ISSUE 13): under ZeRO >= 2 a gradient leaf
        # must resolve to EXACTLY the spec of the mu moment that consumes
        # it — the reduce-scattered gradient is the shard-local Adam
        # update's input with zero re-layout. Gradients are addressed by
        # the bare param tail (rules.grad_shardings), moments by
        # their full "opt/<net>/.../mu/<tail>" path; a rule row that keys
        # on either prefix silently splits the two resolutions, so audit
        # them against each other on the canonical 2-way mesh.
        mesh_shape = {"data": CANONICAL_DEVICES, "model": 1}
        for net in ("gen", "disc"):
            for leaf_path, leaf in jax.tree_util.tree_flatten_with_path(
                    shapes["params"][net])[0]:
                tail = rules.path_str(leaf_path)
                shape = tuple(getattr(leaf, "shape", ()))
                try:
                    gspec = rules.resolve_spec(
                        rules.logical_spec(tail, len(shape)), shape,
                        mesh_shape, zero=True)
                    mspec = rules.resolve_spec(
                        rules.logical_spec(f"opt/{net}/1/0/mu/{tail}",
                                           len(shape)), shape,
                        mesh_shape, zero=True)
                except ValueError:
                    continue  # unmatched leaves are already flagged above
                if gspec != mspec:
                    findings.append(Finding(
                        check="DCG011", path=path, line=0,
                        symbol=f"{variant}::grads",
                        key=f"grad-spec-drift:{variant}:{net}/{tail}",
                        message=f"[{variant}] gradient leaf "
                                f"{net}/{tail!r} resolves to {gspec} but "
                                f"its mu moment resolves to {mspec} — a "
                                "rule row keys on the opt/ or params/ "
                                "prefix, so the reduce-scattered gradient "
                                "and the shard-local Adam state disagree "
                                "on layout under zero_stage >= 2"))
    return findings


def check_manifest(records: Sequence[manifest_lib.ProgramRecord],
                   manifest_path: str) -> List[Finding]:
    """DCG008 (drift half): live records vs the committed manifest."""
    if not os.path.exists(manifest_path):
        return [Finding(
            check="DCG008", path="dcgan_tpu/analysis/programs.lock.jsonl",
            line=0, symbol="<manifest>", key="manifest-missing",
            message=f"no committed program manifest at {manifest_path} — "
                    "generate one with `python -m dcgan_tpu.analysis "
                    "--semantic --write-manifest`")]
    return manifest_lib.diff(records, manifest_lib.load_path(manifest_path))


def run_semantic(checks: Optional[Sequence[str]] = None,
                 manifest_path: Optional[str] = None,
                 compare_manifest: bool = True,
                 ) -> Tuple[List[Finding],
                            List[manifest_lib.ProgramRecord]]:
    """The full semantic tier: enumerate + audit + every requested checker
    (default: all five). Returns (findings, manifest records); the CLI
    applies the shared baseline on top, exactly like the AST tier."""
    if checks:
        checks = [c.upper() for c in checks]
        unknown = sorted(set(checks) - set(SEMANTIC_CHECKS))
        if unknown:
            raise ValueError(f"unknown semantic check ID(s) {unknown}; "
                             f"valid: {list(SEMANTIC_CHECKS)}")
    active = set(checks or SEMANTIC_CHECKS)
    # DCG011 is eval_shape-only — a `--checks DCG011` run (the command the
    # rule engine's unmatched-leaf error names) must not pay the full
    # trace+lower enumeration it never reads. Manifest regeneration
    # (compare_manifest=False is the CLI's --write-manifest mode) always
    # enumerates: the records ARE its output.
    if active - {"DCG011"} or not compare_manifest:
        audits, coverage = enumerate_audits()
        records = records_from(audits)
    else:
        audits, coverage, records = [], [], []
    findings: List[Finding] = []
    if "DCG007" in active:
        findings += check_donation(audits)
    if "DCG008" in active:
        findings += check_transports()
        if compare_manifest:
            findings += check_manifest(
                records,
                manifest_path or manifest_lib.default_manifest_path())
    if "DCG009" in active:
        findings += check_warmup_coverage(coverage)
        findings += check_retrace(audits)
    if "DCG010" in active:
        findings += check_hygiene(audits)
    if "DCG011" in active:
        findings += check_spec_coverage()
    findings.sort(key=lambda f: (f.path, f.symbol, f.check, f.key))
    return findings, records
