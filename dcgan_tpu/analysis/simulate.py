"""Protocol simulator: N virtual processes through the REAL coordination
decision code (ISSUE 14 tentpole).

Every multi-host recovery decision in this repo is supposed to be a
deterministic collective (DESIGN.md §6c.1): anomaly consensus, the
coordinated stop, the rollback restore/delete ordering, the elastic
restore path choice. The classic SPMD failure is one asymmetric branch —
a host that enters a barrier or allgather its peers skip — and until now
the only defense was a handful of hand-picked 2-process chaos scenarios.
This module makes the lockstep property *checkable*: it runs N virtual
processes (threads) through the REAL decision code —
`coordination.anomaly_consensus`, `CoordinatedStop.poll`,
`warmup_barrier`, `fleet_health_gather`, `RollbackManager` restore (with
its `on_restore` drain ordering), `Checkpointer.delete_steps_after`'s
barrier+verdict protocol, and `elastic.sidecar.restore_decision` — with
the process-level transports replaced by an in-process rendezvous, and
records every process's collective schedule.

How the shim works: the real coordination/checkpoint code bottoms out in
exactly two jax primitives — `multihost_utils.process_allgather` and
`multihost_utils.sync_global_devices` — plus `jax.process_count()` /
`jax.process_index()`. The simulator patches those four (thread-local
process identity, rendezvous transports) so every *decision* line between
the trainer mirror and the wire is the production code, not a model of
it. `SIM_TRANSPORTS` declares which coordination entry points are driven
through their real bodies; tests/test_protocol.py pins it in three-way
set equality against `tripwire.WRAPPED_TRANSPORTS` and the transport
functions named by `coordination.TRANSPORT_CENSUS`, so a new transport
added to any one of the three fails loudly in the other two (and
`verify_transport_registry()` repeats the check at every `--protocol`
run).

The explored lattice is (knob config) x (one-shot fault), with faults
expressed as real `testing/chaos.FaultPlan` instances (one per virtual
process — the exact per-process one-shot semantics the chaos drill
arms through DCGAN_CHAOS). Termination semantics:

- an interleaving TERMINATES when every virtual process finishes
  (completed / stopped / aborted), or — when the config arms the
  hung-collective watchdog — when a deadlock resolves as a watchdog trip
  on every blocked process (the hung process's schedule must be a prefix
  of its peers');
- a deadlock with NO watchdog armed, or any divergence between
  per-process schedules, is a DCG012 finding (analysis/protocol.py).

Deliberately NOT modeled (documented, not hidden): coord_stop=false
multi-host SIGTERM (no handler is installed there by design — the
process dies and jax's coordination service reaps its peers; there is no
lockstep schedule to audit), and the watchdog's mesh-warm arming
exemptions (the simulator treats a config's watchdog as armed for the
whole run — phase-granular arming is a liveness optimization, not a
schedule change).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import io
import os
import shutil
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

#: coordination entry points the simulator drives through their REAL
#: bodies (the rendezvous shim sits UNDER them, at the multihost_utils
#: primitives). Three-way set equality with tripwire.WRAPPED_TRANSPORTS
#: and coverage of coordination.TRANSPORT_CENSUS's transport functions is
#: enforced by verify_transport_registry() + tests/test_protocol.py.
SIM_TRANSPORTS = ("_allgather_i32", "_allgather_f32", "fleet_health_gather",
                  "anomaly_consensus", "warmup_barrier")

#: logical collective ops that coordination.py also logs to
#: DCGAN_PROTOCOL_LOG in live multi-host runs — the replay-comparison
#: subset of a simulated schedule (tools/chaos_drill.py mh-sigterm-stop).
COORD_LOG_OPS = ("stop_consensus", "anomaly_consensus", "fleet_health",
                 "warmup_barrier", "notice_consensus")

#: how long the engine waits on a rendezvous before declaring itself
#: wedged — an ENGINE bug guard, never part of the audited semantics
#: (deadlocks between virtual processes are detected structurally, by the
#: last runnable thread blocking, not by timeout).
_ENGINE_WEDGE_SECS = 60.0


class SimProtocolError(RuntimeError):
    """The simulator itself failed (engine wedge, crashed virtual
    process) — distinct from a detected protocol violation, which is a
    DCG012 finding, not an exception."""


class _SimExit(Exception):
    """Internal: unwinds a virtual process whose outcome is already
    recorded (hang, watchdog trip, deadlock)."""


@dataclasses.dataclass(frozen=True)
class Knobs:
    """One knob configuration — the sim's mirror of the TrainConfig
    fields that change the collective schedule."""

    name: str
    n_proc: int = 2
    total_steps: int = 6
    nan_policy: str = "abort"          # "abort" | "rollback"
    nan_check_steps: int = 2
    coord_stop: bool = True
    zero_stage: int = 1
    pipeline_gd: bool = False
    fleet_health_steps: int = 0
    aot_warmup: bool = False
    collective_timeout_secs: float = 0.0
    rollback_snapshot_steps: int = 2
    max_rollbacks: int = 2
    restore: str = "none"              # none|same|mesh|procs — which saved
                                       # topology the run "resumes" from
                                       # (sidecar.restore_decision input)
    progressive_switch_at: int = 0     # >0: a progressive-resolution
                                       # phase switch at this boundary
                                       # (ISSUE 15) — pending flush,
                                       # services/pipeline drains, state
                                       # carry, loader re-bucket, fresh
                                       # rollback snapshot; all step-keyed
                                       # and host-local, so the audited
                                       # schedules must stay symmetric
    live_elastic: bool = False         # arm the live-elasticity notice
                                       # plane (ISSUE 18): one
                                       # notice_consensus per boundary;
                                       # an agreed verdict drives the
                                       # drain->reshard->snapshot switch
                                       # sequence (notices land through
                                       # FaultPlan preempt/grow fields)
    fleet_replicas: int = 0            # >0: run the SERVING-FLEET program
                                       # (ISSUE 19) instead of the virtual
                                       # trainer — each virtual process is
                                       # one replica's dispatch thread;
                                       # must equal n_proc
    fleet_promote_at: int = 0          # >0: a fleet-wide weight promotion
                                       # after this dispatch index — the
                                       # drain->swap->prime->resume
                                       # lattice rows

    def to_json(self) -> Dict[str, object]:
        d = dataclasses.asdict(self)
        d.pop("name")
        return d


@dataclasses.dataclass(frozen=True)
class Fault:
    """One lattice point: per-virtual-process FaultPlan fields (the
    real testing/chaos.FaultPlan one-shot semantics) plus an optional
    process-global transient-IO site (retry_io's chaos selector)."""

    name: str
    plans: Tuple[Tuple[int, Tuple[Tuple[str, int], ...]], ...] = ()
    io_site: str = ""

    @classmethod
    def make(cls, name: str, plans: Optional[Dict[int, Dict[str, int]]]
             = None, io_site: str = "") -> "Fault":
        frozen = tuple(sorted(
            (pid, tuple(sorted(fields.items())))
            for pid, fields in (plans or {}).items()))
        return cls(name=name, plans=frozen, io_site=io_site)

    def plan_for(self, pid: int):
        from dcgan_tpu.testing.chaos import FaultPlan

        for p, fields in self.plans:
            if p == pid:
                return FaultPlan(**dict(fields))
        return None


@dataclasses.dataclass
class ScenarioResult:
    knobs: Knobs
    fault: Fault
    schedules: List[List[str]]
    outcomes: List[Optional[str]]
    statuses: List[str]
    failure: Optional[Dict[str, object]]   # deadlock snapshot, or None
    watchdog_armed: bool
    crash: Optional[BaseException] = None

    @property
    def terminated(self) -> bool:
        """No virtual process left blocked forever: every process is done
        or hung-by-fault or resolved by a watchdog trip."""
        if self.crash is not None:
            return False
        return all(s in ("done", "hung", "trip") for s in self.statuses)


class VirtualMesh:
    """N virtual processes + rendezvous transports + schedule recorder.

    A collective completes only when ALL N processes enter the same
    (entry, occurrence) point — exactly a real job's semantics, where a
    process that exited or hung leaves its peers blocked forever. The
    last thread to leave the runnable pool performs the structural
    deadlock check; a detected deadlock resolves every blocked process as
    a watchdog trip when the scenario arms one, else marks the scenario
    failed (the DCG012 raw material)."""

    def __init__(self, n_proc: int, *, watchdog_armed: bool = False):
        self.n = n_proc
        self.watchdog_armed = watchdog_armed
        self.schedules: List[List[str]] = [[] for _ in range(n_proc)]
        self.statuses = ["running"] * n_proc
        self.outcomes: List[Optional[str]] = [None] * n_proc
        self.crash: Optional[BaseException] = None
        self._cond = threading.Condition()
        self._pids: Dict[int, int] = {}
        self._phases = [""] * n_proc
        self._blocked_at: List[Optional[tuple]] = [None] * n_proc
        self._occ = [collections.Counter() for _ in range(n_proc)]
        self._waiters: Dict[tuple, Dict[int, object]] = {}
        self._results: Dict[tuple, list] = {}
        self.failure: Optional[Dict[str, object]] = None

    # -- virtual process identity --------------------------------------------

    def register(self, pid: int) -> None:
        with self._cond:
            self._pids[threading.get_ident()] = pid

    def pid(self) -> int:
        # unregistered threads (the orchestrating main thread) read as the
        # chief — matches jax.process_index()'s single-process default
        return self._pids.get(threading.get_ident(), 0)

    @contextlib.contextmanager
    def phase(self, label: str):
        """Name the protocol phase for the enclosed collectives — the sim
        counterpart of the trainer's watchdog guard labels; schedule
        entries carry it."""
        pid = self.pid()
        prev = self._phases[pid]
        self._phases[pid] = label
        try:
            yield
        finally:
            self._phases[pid] = prev

    # -- schedule recording ---------------------------------------------------

    def local(self, label: str) -> None:
        """A host-local decision that must still be lockstep (recorded,
        never blocking): restore path choice, pipeline drains."""
        self.schedules[self.pid()].append(f"local:{label}")

    def collective(self, kind: str, label: str):
        """A named mesh-synchronous point that is not one of the patched
        transports (program dispatch, the final collective save)."""
        with self.phase(label):
            return self.gather(kind, None)

    # -- the rendezvous transport --------------------------------------------

    def gather(self, kind: str, value, fallback_label: str = "") -> list:
        """Block until every virtual process enters this same point; the
        per-pid values come back index-ordered (process_allgather
        semantics). On structural deadlock: watchdog-armed scenarios
        resolve every blocked process as a trip; unarmed ones mark the
        scenario failed. Either way the blocked thread unwinds."""
        pid = self.pid()
        with self._cond:
            label = self._phases[pid] or fallback_label or kind
            entry = f"{kind}:{label}"
            self.schedules[pid].append(entry)
            occ = self._occ[pid][entry]
            self._occ[pid][entry] += 1
            key = (entry, occ)
            self._waiters.setdefault(key, {})[pid] = value
            self.statuses[pid] = "blocked"
            self._blocked_at[pid] = key
            if len(self._waiters[key]) == self.n:
                self._results[key] = [self._waiters[key][i]
                                      for i in range(self.n)]
                self._cond.notify_all()
            else:
                self._check_stuck_locked()
            deadline = time.monotonic() + _ENGINE_WEDGE_SECS
            while key not in self._results and self.failure is None \
                    and self.crash is None:
                if not self._cond.wait(timeout=1.0) \
                        and time.monotonic() > deadline:
                    raise SimProtocolError(
                        f"simulator wedged: process {pid} waited "
                        f"{_ENGINE_WEDGE_SECS:.0f}s at {entry!r} without "
                        "structural resolution — engine bug")
            if key in self._results:
                self.statuses[pid] = "running"
                self._blocked_at[pid] = None
                return self._results[key]
            self._blocked_at[pid] = None
            if self.crash is not None:
                self.statuses[pid] = "done"
                self.outcomes[pid] = f"unwound:{label}"
                raise _SimExit()
            if self.watchdog_armed:
                # the deadline guard around this phase fires on every
                # blocked process: the job dies loudly instead of hanging
                # (coordination.CollectiveWatchdog's contract)
                self.statuses[pid] = "trip"
                self.outcomes[pid] = f"watchdog-trip:{label}"
            else:
                self.statuses[pid] = "deadlocked"
                self.outcomes[pid] = f"deadlocked:{label}"
            raise _SimExit()

    def _check_stuck_locked(self) -> None:
        """Structural deadlock check, run by the last thread to leave the
        runnable pool. No rendezvous can complete once a process is done
        or hung (it will never arrive), or when the blocked set is split
        across different points (the asymmetric-branch signature)."""
        if self.failure is not None or self.crash is not None:
            return
        blocked = {}
        for i in range(self.n):
            st = self.statuses[i]
            if st == "running":
                return
            if st == "blocked":
                if self._blocked_at[i] in self._results:
                    return  # resolved, just hasn't woken yet
                blocked[i] = self._blocked_at[i]
        if not blocked:
            return  # everyone finished or hung — nothing waiting
        self.failure = {
            "waiting": {i: k[0] for i, k in sorted(blocked.items())},
            "absent": sorted(i for i in range(self.n)
                             if self.statuses[i] in ("done", "hung")),
            "hung": sorted(i for i in range(self.n)
                           if self.statuses[i] == "hung"),
        }
        self._cond.notify_all()

    # -- terminal states -------------------------------------------------------

    def finish(self, outcome: str) -> None:
        pid = self.pid()
        with self._cond:
            self.statuses[pid] = "done"
            self.outcomes[pid] = outcome
            self._check_stuck_locked()

    def hang(self, label: str) -> None:
        """The chaos hang fault: this virtual process goes silent — it
        never enters another collective, exactly `maybe_hang`'s peer-gone
        semantics. Unwinds the thread after recording the state."""
        pid = self.pid()
        with self._cond:
            self.schedules[pid].append(f"local:{label}")
            self.statuses[pid] = "hung"
            self.outcomes[pid] = label
            self._check_stuck_locked()
        raise _SimExit()

    def record_crash(self, exc: BaseException) -> None:
        with self._cond:
            if self.crash is None:
                self.crash = exc
            self._cond.notify_all()


# -- transport patching -------------------------------------------------------

#: env knob coordination.py logs live collective sequences under — the
#: simulator must run with it cleared so the REAL transport bodies it
#: drives don't append sim traffic to a drill's replay log.
_SCHED_LOG_ENV = "DCGAN_PROTOCOL_LOG"


@contextlib.contextmanager
def patched_transports(mesh: VirtualMesh):
    """Swap the four process-level primitives for the rendezvous mesh:
    `jax.process_count`/`jax.process_index` (thread-local virtual
    identity) and `multihost_utils.process_allgather`/
    `sync_global_devices` (the two wires every SIM_TRANSPORTS entry's
    real body bottoms out in — coordination.py, and
    Checkpointer.delete_steps_after's verdict barrier, import them at
    call time, so a module-attribute patch reaches every call site)."""
    import jax
    from jax.experimental import multihost_utils as mh

    saved = (jax.process_count, jax.process_index,
             mh.process_allgather, mh.sync_global_devices,
             os.environ.pop(_SCHED_LOG_ENV, None))

    def _allgather(x, tiled=False):
        vals = mesh.gather("ag", np.asarray(x))
        return np.stack([np.asarray(v) for v in vals])

    def _sync(name: str = "sync") -> None:
        mesh.gather("bar", None, fallback_label=str(name))

    jax.process_count = lambda: mesh.n
    jax.process_index = mesh.pid
    mh.process_allgather = _allgather
    mh.sync_global_devices = _sync
    try:
        yield
    finally:
        (jax.process_count, jax.process_index,
         mh.process_allgather, mh.sync_global_devices) = saved[:4]
        if saved[4] is not None:
            os.environ[_SCHED_LOG_ENV] = saved[4]


def verify_transport_registry() -> None:
    """The three-way transport cross-check, run before every lattice
    exploration (and pinned as a test): SIM_TRANSPORTS ==
    tripwire.WRAPPED_TRANSPORTS, every TRANSPORT_CENSUS row's transport
    function is simulated, and every declared name exists in
    coordination. A transport added to any one registry fails here."""
    from dcgan_tpu.analysis import tripwire
    from dcgan_tpu.train import coordination

    sim = set(SIM_TRANSPORTS)
    wrapped = set(tripwire.WRAPPED_TRANSPORTS)
    if sim != wrapped:
        raise SimProtocolError(
            f"transport registries diverged: simulator shims {sorted(sim)} "
            f"but the runtime tripwire wraps {sorted(wrapped)} — add the "
            "new transport to BOTH (analysis/simulate.SIM_TRANSPORTS, "
            "analysis/tripwire.WRAPPED_TRANSPORTS)")
    census_fns = {row[0] for row in coordination.TRANSPORT_CENSUS.values()}
    if not census_fns <= sim:
        raise SimProtocolError(
            f"TRANSPORT_CENSUS names transport function(s) "
            f"{sorted(census_fns - sim)} the simulator does not drive — "
            "add them to analysis/simulate.SIM_TRANSPORTS (and teach the "
            "virtual trainer to exercise them)")
    for name in sorted(sim):
        if not callable(getattr(coordination, name, None)):
            raise SimProtocolError(
                f"SIM_TRANSPORTS entry {name!r} is not a coordination "
                "callable — registry drifted from the code")


# -- the sidecar decision's target tree ---------------------------------------

_SIDECAR_STATE = None


def _sidecar_state():
    """A 1-leaf sharded tree on a real 1-device mesh, built ONCE before
    any transport patching (device placement must not run under a
    patched process_index). `sidecar.restore_decision` reads only its
    mesh axes/sizes plus jax.process_count() — which IS patched, so the
    decision sees the virtual process census."""
    global _SIDECAR_STATE
    if _SIDECAR_STATE is None:
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
        _SIDECAR_STATE = {"w": jax.device_put(
            np.zeros(2, np.float32), NamedSharding(mesh, PartitionSpec()))}
    return _SIDECAR_STATE


def _restore_payload(knobs: Knobs) -> Dict[str, object]:
    """The saved-topology sidecar payload each restore variant resumes
    from, crafted against the 1-device live mesh above: `same` matches,
    `mesh` changes the axis sizes only (device path), `procs` changes the
    process count (host path)."""
    if knobs.restore == "same":
        return {"mesh": {"axes": ["data"], "sizes": [1]},
                "process_count": knobs.n_proc}
    if knobs.restore == "mesh":
        return {"mesh": {"axes": ["data"], "sizes": [2]},
                "process_count": knobs.n_proc}
    if knobs.restore == "procs":
        return {"mesh": {"axes": ["data"], "sizes": [1]},
                "process_count": knobs.n_proc + 1}
    raise ValueError(f"unknown restore variant {knobs.restore!r}")


# -- the checkpoint-delete protocol's real executor ---------------------------

class _FakeMgr:
    """The minimal CheckpointManager surface delete_steps_after touches:
    wait/reload are host-local no-ops here, single-process delete is the
    real directory removal."""

    def __init__(self, directory: str):
        self._dir = directory

    def wait_until_finished(self) -> None:
        pass

    def reload(self) -> None:
        pass

    def delete(self, step: int) -> None:
        shutil.rmtree(os.path.join(self._dir, str(step)),
                      ignore_errors=True)


def make_sim_checkpointer(directory: str):
    """A Checkpointer whose `delete_steps_after` is the REAL method —
    real chief-only rmtree + retry_io + the unconditional verdict
    allgather/barrier — against a plain directory of integer step dirs,
    with the Orbax manager faked out (no async machinery, no device
    arrays). The simulator audits the delete ORDERING contract through
    the production code path, not a model of it."""
    from dcgan_tpu.utils import checkpoint as ckpt_mod

    c = ckpt_mod.Checkpointer.__new__(ckpt_mod.Checkpointer)
    c.directory = directory
    c._mgr = _FakeMgr(directory)
    c._pending_sidecars = {}
    return c


# -- the virtual trainer ------------------------------------------------------

def _virtual_trainer(mesh: VirtualMesh, pid: int, knobs: Knobs,
                     plan, ckpt) -> str:
    """One virtual process's run: the trainer's boundary-poll branch
    structure (train/trainer.py `_train_run` loop — see the PROTOCOL
    anchor comment there) with every protocol DECISION taken by the real
    coordination/rollback/checkpoint/sidecar code, every collective a
    rendezvous, and host-local work elided. Returns the termination tag.

    Field-for-field mapping to _train_run (kept in lockstep with the
    trainer; protocol.lock.jsonl drift is the tripwire for edits there):
    boundary order = self-signal fault -> stop poll -> hang fault ->
    dispatch -> lag-by-one consume (deferred default) -> fleet-health
    cadence -> snapshot-certify (forced gate + early consume + snapshot)
    -> next boundary; loop exit -> final lag-by-one flush (a trip here
    aborts under BOTH nan policies) -> final collective save.
    """
    import signal as _signal

    from dcgan_tpu.elastic import sidecar
    from dcgan_tpu.train import coordination
    from dcgan_tpu.train.rollback import RollbackManager

    n = mesh.n
    chief = pid == 0
    state = {"w": np.zeros(2, np.float32)}
    step_num = 0
    total = knobs.total_steps

    # elastic restore decision (Checkpointer.restore_latest's first act:
    # sidecar read -> path choice, zero payload bytes) — host-local and
    # mesh-uniform by construction; recorded so an asymmetric choice
    # would break the lockstep audit
    if knobs.restore != "none":
        path, _mismatch = sidecar.restore_decision(
            _restore_payload(knobs), _sidecar_state())
        mesh.local(f"restore:{path}")

    # AOT warmup proof barrier (trainer setup, --aot_warmup)
    if knobs.aot_warmup:
        with mesh.phase("warmup_barrier@start"):
            coordination.warmup_barrier()

    stop = coordination.CoordinatedStop()
    rollback = None
    if knobs.nan_policy == "rollback":
        rollback = RollbackManager(
            every=knobs.rollback_snapshot_steps,
            max_rollbacks=knobs.max_rollbacks, chief=chief,
            device_resident=False)  # host-mode over numpy leaves: the
        # REAL ordering contract (budget check -> on_restore drain ->
        # restore) with zero device dispatches
        if knobs.pipeline_gd:
            # the trainer parks the pipelined-stack drain on the
            # manager's restore hook (ISSUE 7) — the sim records the
            # drain so its ordering is part of the audited schedule
            rollback.on_restore = \
                lambda: mesh.local("pipeline-drain:rollback")
        rollback.snapshot(step_num, state)

    primed = False
    pending: Optional[dict] = None
    phase_idx = 0   # progressive phase (0 = first/only; the switch bumps)
    topo_idx = 0    # live-elastic topology (0 = launch mesh, 1 = submesh)

    def _gate(rec: dict, *, force: bool = False) -> None:
        """_nan_gate's protocol skeleton: cadence/force keying, the
        chaos one-shot poisoning of THIS process's view, then the real
        anomaly_consensus — a raise is mesh-symmetric by construction."""
        s = rec["step"]
        if not force and not (knobs.nan_check_steps
                              and s % knobs.nan_check_steps == 0):
            return
        local_bad = bool(plan and plan.nan_at_step
                         and plan.nan_at_step == s
                         and plan.fire_once("nan_at_step"))
        with mesh.phase(f"anomaly_consensus@{s}"):
            bad, trippers = coordination.anomaly_consensus(local_bad)
        if bad:
            err = FloatingPointError(
                f"non-finite metrics at step {s} (process(es) {trippers})")
            err.step = s
            raise err

    def _do_rollback(e: FloatingPointError) -> None:
        """The trainer's _do_rollback collective half: real restore
        (budget check, on_restore drain, snapshot copy-back), then the
        real delete_steps_after barrier+verdict protocol."""
        nonlocal state, step_num, pending, primed
        state, step_num = rollback.restore(e)
        pending = None
        if knobs.pipeline_gd:
            primed = False  # drained: refills at the next dispatch
        with mesh.phase(f"rollback_delete@{getattr(e, 'step', step_num)}"):
            ckpt.delete_steps_after(step_num)

    stop_sig = None
    while step_num < total:
        # chaos.maybe_self_signal: the one-shot handler's only effect is
        # the process-local flag (threads cannot take real signals)
        if plan and plan.sigterm_at_step \
                and plan.sigterm_at_step == step_num \
                and plan.fire_once("sigterm_at_step"):
            stop._signal_num = _signal.SIGTERM
        stop_sig = None
        if n == 1:
            stop_sig, _origins = stop.poll()
        elif knobs.coord_stop:
            with mesh.phase(f"stop_consensus@{step_num}"):
                stop_sig, _origins = stop.poll()
        if stop_sig is not None:
            if knobs.pipeline_gd and primed:
                mesh.local("pipeline-drain:coordinated-stop")
            break
        # live-elasticity notice poll (ISSUE 18, trainer's boundary poll):
        # the local one-shot notice sources fold into the REAL
        # notice_consensus — the verdict is mesh-uniform, so the switch
        # sequence below is taken (or skipped) identically everywhere.
        # Mirror order matches the trainer: pending flush (its gate may
        # trip and roll back BEHIND the boundary; the consumed notice is
        # NOT re-raised) -> services drain -> pipeline drain -> live
        # reshard (a mesh program over the target surface, recorded as
        # one swap collective) -> loader rebuild -> fresh rollback
        # snapshot of the re-scattered tree.
        if knobs.live_elastic:
            from dcgan_tpu.testing import chaos as _chaos

            local_v = _chaos.NOTICE_NONE
            if plan and plan.preempt_notice_at_step \
                    and step_num >= plan.preempt_notice_at_step \
                    and plan.fire_once("preempt_notice_at_step"):
                local_v = _chaos.NOTICE_SHRINK
            elif plan and plan.grow_notice_at_step \
                    and step_num >= plan.grow_notice_at_step \
                    and plan.fire_once("grow_notice_at_step"):
                local_v = _chaos.NOTICE_GROW
            with mesh.phase(f"notice_consensus@{step_num}"):
                verdict, _raisers = coordination.notice_consensus(local_v)
            target = {_chaos.NOTICE_SHRINK: 1,
                      _chaos.NOTICE_GROW: 0}.get(verdict)
            if target is not None and target != topo_idx:
                if pending is not None:
                    prev, pending = pending, None
                    try:
                        _gate(prev)
                    except FloatingPointError as e:
                        if rollback is None:
                            raise
                        _do_rollback(e)
                        continue
                mesh.local("services-drain:elastic-switch")
                if knobs.pipeline_gd and primed:
                    mesh.local("pipeline-drain:elastic-switch")
                    primed = False
                with mesh.phase(f"live-switch@{step_num}"):
                    mesh.collective("prog", f"live_reshard@{step_num}")
                mesh.local("data-rebuild:elastic-switch")
                topo_idx = target
                if rollback is not None:
                    rollback.snapshot(step_num, state)
        # progressive phase switch (ISSUE 15, trainer's phase-boundary
        # step): a pure function of step_num and the schedule — every
        # process takes it at the same boundary with ZERO extra
        # transports. Mirror order: lag-by-one flush (its gate may trip
        # and roll back BEHIND the boundary, re-evaluating the switch) ->
        # services drain -> pipeline drain -> state carry onto the next
        # phase's surface (the per-phase init + identity copies are mesh
        # programs, recorded as one swap collective) -> loader re-bucket
        # -> fresh rollback snapshot of the NEW tree.
        if knobs.progressive_switch_at and phase_idx == 0 \
                and step_num >= knobs.progressive_switch_at:
            if pending is not None:
                prev, pending = pending, None
                try:
                    _gate(prev)
                except FloatingPointError as e:
                    if rollback is None:
                        raise
                    _do_rollback(e)
                    continue
            mesh.local("services-drain:phase-switch")
            if knobs.pipeline_gd and primed:
                mesh.local("pipeline-drain:phase-switch")
                primed = False
            with mesh.phase(f"phase-switch@{step_num}"):
                mesh.collective("prog", f"phase_carry@{step_num}")
            mesh.local("rebucket:phase-switch")
            phase_idx = 1
            if rollback is not None:
                rollback.snapshot(step_num, state)
        # chaos.maybe_hang: this process goes silent inside the guarded
        # dispatch window; peers block in the next collective
        if plan and plan.hang_at_step \
                and plan.hang_at_step == step_num \
                and plan.fire_once("hang_at_step"):
            mesh.hang(f"hang@{step_num}")
        # step dispatch: SPMD programs are mesh-synchronous — the
        # schedule entry names which program the stream runs (the ZeRO
        # stage changes its collective content, DESIGN §6i; a progressive
        # run's stream switches to the new phase's programs)
        zs = f"@zero{knobs.zero_stage}" if knobs.zero_stage > 1 else ""
        if knobs.progressive_switch_at:
            zs += f"@phase{phase_idx}"
        if knobs.live_elastic:
            # the dispatch stream names the ACTIVE topology's programs —
            # a post-switch asymmetry (one host still dispatching the old
            # surface) breaks the lockstep audit right here
            zs += f"@topo{topo_idx}"
        if knobs.pipeline_gd:
            if not primed:
                mesh.collective("prog", f"gen_fakes{zs}@{step_num}")
                primed = True
            mesh.collective("prog", f"d_update{zs}@{step_num}")
            mesh.collective("prog", f"g_update{zs}@{step_num}")
        else:
            mesh.collective("prog", f"train_step{zs}@{step_num}")
        new_step = step_num + 1
        cur = {"step": new_step}
        # deferred lag-by-one consume (async services default): the
        # PREVIOUS record's gate runs after this step's dispatch
        if pending is not None:
            prev, pending = pending, None
            try:
                _gate(prev)
            except FloatingPointError as e:
                if rollback is None:
                    raise
                _do_rollback(e)
                continue
        pending = cur
        # fleet health cadence (dispatch thread, new_step keyed)
        if knobs.fleet_health_steps \
                and new_step % knobs.fleet_health_steps == 0:
            vec = np.asarray([new_step, 0, 0, 0, 0, 0, 0, 0], np.float32)
            with mesh.phase(f"fleet_health@{new_step}"):
                coordination.fleet_health_gather(vec)
        # snapshot-certify (trainer: forced gate + early lag-by-one
        # flush + snapshot, all inside one guarded window)
        if rollback is not None and rollback.due(new_step):
            try:
                _gate(cur, force=True)
                if pending is not None:
                    _gate(pending)
                    pending = None
                rollback.snapshot(new_step, state)
            except FloatingPointError as e:
                _do_rollback(e)
                continue
        step_num = new_step
    # final lag-by-one flush: a NaN in the last window aborts under BOTH
    # policies (the trainer calls _consume_metrics directly — a poisoned
    # state must never reach the final save)
    if pending is not None:
        _gate(pending)
        pending = None
    # final forced collective save (stop and completion exits both land
    # here; exception exits never do)
    mesh.collective("save", f"final_save@{step_num}")
    return f"stopped@{step_num}" if stop_sig is not None \
        else f"completed@{step_num}"


def _fleet_health_view(knobs: Knobs, fault: Optional[Fault]) -> dict:
    """The promotion controller's health view at the promote boundary,
    derived deterministically from the fault: replica r has been drained
    from rotation iff its plan kills or hangs it STRICTLY BEFORE the
    boundary dispatch (the heartbeat monitor needs one beat interval to
    notice a death — a replica dying exactly AT the boundary is still
    seen healthy, which is the stale-health-view lattice row)."""
    health = {}
    for r in range(knobs.n_proc):
        plan = fault.plan_for(r) if fault is not None else None
        dead_at = 0
        if plan is not None:
            hits = [d for d in (plan.replica_kill_at_dispatch,
                                plan.replica_hang_at_dispatch) if d]
            dead_at = min(hits) if hits else 0
        health[r] = not (dead_at and dead_at < knobs.fleet_promote_at)
    return health


def _virtual_fleet(mesh: "VirtualMesh", pid: int, knobs: Knobs,
                   plan, fault: Optional[Fault] = None) -> str:
    """The serving-fleet protocol skeleton (ISSUE 19): each virtual
    process is one replica's dispatch thread working through
    `total_steps` dispatches, with a fleet-wide weight promotion after
    dispatch `fleet_promote_at`.

    The promotion targets come from the REAL decision code —
    serve/router.promotion_targets over a health view derived from the
    fault (see _fleet_health_view) — and the drain->swap->prime->resume
    sequence is the REAL serve/fleet.PROMOTION_SEQUENCE, so a regression
    in either shows up here as lock drift or a structural deadlock:

    - targets == every replica: the promotion is modeled as a barrier
      per phase (the all-healthy rendezvous). If promotion_targets ever
      regressed to include a replica the fault killed, the survivors
      block at promote-drain forever -> structural deadlock / watchdog
      trip instead of the committed "done" schedules.
    - targets excludes dead replicas: survivors promote replica-locally
      (the real fleet never holds a cross-replica barrier once a peer is
      drained — each surviving worker drains and swaps independently).
    - a kill exactly AT the boundary: the controller's view is stale
      (all replicas look healthy), survivors enter the phase barrier,
      the dead replica never arrives -> the committed watchdog-trip row,
      mirroring fleet.promote()'s bounded ticket waits.
    """
    from dcgan_tpu.serve.fleet import PROMOTION_SEQUENCE
    from dcgan_tpu.serve.router import promotion_targets

    targets = promotion_targets(_fleet_health_view(knobs, fault))
    all_healthy = len(targets) == knobs.n_proc
    for d in range(1, knobs.total_steps + 1):
        # replica faults fire BEFORE the dispatch they are armed at
        # (chaos hook placement in serve/worker.ServeWorker._run)
        if plan and plan.replica_kill_at_dispatch \
                and d >= plan.replica_kill_at_dispatch \
                and plan.fire_once("replica_kill_at_dispatch"):
            mesh.hang(f"hang-replica-kill@{d}")
        if plan and plan.replica_hang_at_dispatch \
                and d >= plan.replica_hang_at_dispatch \
                and plan.fire_once("replica_hang_at_dispatch"):
            mesh.hang(f"hang-replica-hang@{d}")
        mesh.local(f"dispatch@{d}")
        if knobs.fleet_promote_at and d == knobs.fleet_promote_at:
            for phase in PROMOTION_SEQUENCE:
                if all_healthy:
                    mesh.collective("bar", f"promote-{phase}@{d}")
                else:
                    mesh.local(f"promote-{phase}@{d}")
    tag = f"served@{knobs.total_steps}"
    if knobs.fleet_promote_at:
        tag += f"+promoted[{','.join(str(t) for t in targets)}]"
    return tag


def _virtual_process_main(mesh: VirtualMesh, pid: int, fn: Callable[[], str]
                          ) -> None:
    """Thread body for one virtual process: each sim thread IS the
    dispatch thread of its virtual process (declared in
    analysis/core.Config.dispatch_thread_targets — DCG001's allowlist —
    exactly like the serve worker)."""
    mesh.register(pid)
    try:
        mesh.finish(fn())
    except _SimExit:
        pass
    except FloatingPointError as e:
        # mesh-symmetric abort: the gate verdict came from consensus, so
        # every process raises at the same schedule position
        mesh.finish(f"aborted@{getattr(e, 'step', '?')}")
    except BaseException as e:  # engine/caller bug — surface loudly
        mesh.record_crash(e)


def run_scenario(knobs: Knobs, fault: Fault,
                 program: Optional[Callable] = None) -> ScenarioResult:
    """Run one (knobs, fault) interleaving to completion. `program`
    overrides the virtual trainer (fixture scenarios: deliberate
    asymmetric protocols for the DCG012 self-test); it is called as
    program(mesh, pid, knobs, plan) and returns the outcome tag."""
    from dcgan_tpu.testing import chaos

    mesh = VirtualMesh(knobs.n_proc,
                       watchdog_armed=knobs.collective_timeout_secs > 0)
    workdir = tempfile.mkdtemp(prefix="dcgan-protosim-")
    prev_plan = chaos.active_plan()
    sink = io.StringIO()
    try:
        if knobs.nan_policy == "rollback":
            # a pre-existing newer step dir (as if saved before the run
            # died) so a rollback's delete protocol has real work: the
            # chief rmtrees it, the verdict allgather reports success
            os.makedirs(os.path.join(workdir, str(knobs.total_steps - 2)),
                        exist_ok=True)
        chaos.set_plan(chaos.FaultPlan(io_error_once=fault.io_site)
                       if fault.io_site else None)
        with patched_transports(mesh), contextlib.redirect_stdout(sink):
            threads = []
            for pid in range(knobs.n_proc):
                ckpt = make_sim_checkpointer(workdir)
                if program is not None:
                    fn = (lambda p=pid, f=fault:
                          program(mesh, p, knobs, f.plan_for(p)))
                elif knobs.fleet_replicas:
                    # the fleet program needs the FULL fault (not just
                    # its own plan) to derive the controller's health
                    # view deterministically
                    fn = (lambda p=pid, f=fault:
                          _virtual_fleet(mesh, p, knobs, f.plan_for(p),
                                         fault=f))
                else:
                    fn = (lambda p=pid, f=fault, c=ckpt:
                          _virtual_trainer(mesh, p, knobs, f.plan_for(p),
                                           c))
                t = threading.Thread(
                    target=_virtual_process_main, args=(mesh, pid, fn),
                    name=f"dcgan-protosim-p{pid}", daemon=True)
                threads.append(t)
                t.start()
            for t in threads:
                t.join(timeout=_ENGINE_WEDGE_SECS + 30)
                if t.is_alive():
                    raise SimProtocolError(
                        f"virtual process thread {t.name} did not "
                        "terminate — engine bug")
    finally:
        chaos.set_plan(prev_plan)
        shutil.rmtree(workdir, ignore_errors=True)
    if mesh.crash is not None:
        raise SimProtocolError(
            f"virtual process crashed in scenario "
            f"{knobs.name}/{fault.name}: {mesh.crash!r}") from mesh.crash
    return ScenarioResult(
        knobs=knobs, fault=fault, schedules=mesh.schedules,
        outcomes=mesh.outcomes, statuses=mesh.statuses,
        failure=mesh.failure, watchdog_armed=mesh.watchdog_armed)


# -- the explored lattice -----------------------------------------------------

def configs() -> List[Knobs]:
    """The knob matrix. `drill-defaults` mirrors tools/chaos_drill.py's
    multi-host scenario config exactly (trainer-default cadences) — its
    sigterm@p1@3 row is the committed schedule the live drill replays
    against."""
    return [
        Knobs("drill-defaults", nan_check_steps=100),
        Knobs("consensus-abort", nan_check_steps=2, fleet_health_steps=2),
        Knobs("rollback", nan_policy="rollback", nan_check_steps=1,
              aot_warmup=True, restore="same"),
        Knobs("pipelined-zero2", nan_policy="rollback", nan_check_steps=2,
              zero_stage=2, pipeline_gd=True, aot_warmup=True,
              rollback_snapshot_steps=3),
        Knobs("zero3-fleet", zero_stage=3, fleet_health_steps=2,
              restore="mesh"),
        Knobs("elastic-host-restore", total_steps=4,
              nan_policy="rollback", restore="procs"),
        Knobs("watchdog", nan_policy="rollback",
              collective_timeout_secs=8.0),
        Knobs("local-stop", coord_stop=False),
        Knobs("single-proc", n_proc=1, total_steps=5,
              nan_policy="rollback", nan_check_steps=1),
        # progressive phase switch at a boundary (ISSUE 15): the
        # drain->carry->rebucket->snapshot sequence must be symmetric
        # across hosts, including a NaN tripping right AFTER the switch
        # (rollback restores the post-switch snapshot) and inside the
        # pre-switch pending flush (rollback behind the boundary, switch
        # re-evaluates)
        Knobs("progressive-switch", nan_policy="rollback",
              nan_check_steps=1, progressive_switch_at=3,
              pipeline_gd=True, aot_warmup=True),
        # live-elasticity notice at a boundary (ISSUE 18): the
        # notice_consensus poll runs EVERY boundary; an agreed verdict
        # drives flush->drain->reshard->snapshot, and the audited
        # schedules must stay symmetric whichever single host the notice
        # lands on — including a shrink-then-grow round trip and a NaN
        # tripping right AFTER the switch (rollback restores the
        # post-switch re-scattered tree). The trainer restricts the
        # switch itself to single-controller runs; this config proves the
        # CONSENSUS half holds lockstep on a multi-host mesh.
        Knobs("live-elastic-switch", nan_policy="rollback",
              nan_check_steps=1, live_elastic=True,
              pipeline_gd=True, aot_warmup=True),
        # serving-fleet promotion drain (ISSUE 19): three replica
        # dispatch threads, a fleet-wide weight promotion after dispatch
        # 3. promotion_targets (the REAL router decision code) must
        # exclude every replica the heartbeat monitor has drained — the
        # lattice proves drain->swap->prime->resume completes under
        # replica kills/hangs before, at, and after the boundary, and
        # that the one genuinely racy shape (a kill exactly AT the
        # boundary, stale health view) resolves as a bounded watchdog
        # trip, never a silent wedge
        Knobs("fleet-promotion", n_proc=3, total_steps=6,
              nan_check_steps=100, fleet_replicas=3, fleet_promote_at=3,
              collective_timeout_secs=8.0),
    ]


def faults_for(k: Knobs) -> List[Fault]:
    """The one-shot fault lattice for one config, keyed by the real
    FaultPlan fields (nan_at_step / sigterm_at_step / hang_at_step /
    io_error_once). Gate-cadence-aligned NaN steps so every armed fault
    actually fires; sigterm excluded under coord_stop=False multi-host
    (no handler installed there — see the module docstring)."""
    F = Fault.make
    if k.fleet_replicas:
        # serving-fleet configs run the replica-fault lattice only: the
        # trainer faults (nan/sigterm/io) have no hook sites in the
        # fleet program. `p` is the promotion boundary; kills strictly
        # before it are drained (survivors promote locally), a kill
        # exactly AT it is the stale-health-view watchdog row, kills
        # after it die post-swap. The slow-beat fault is deliberately
        # excluded — it is timing-dependent recovery, not protocol
        # structure (covered by tools/chaos_drill.py instead).
        p = k.fleet_promote_at
        out = [F("clean")]
        if p and k.n_proc >= 3:
            out += [
                F(f"replica-kill@r1@{p - 2}",
                  {1: {"fault_replica": 1,
                       "replica_kill_at_dispatch": p - 2}}),
                F(f"replica-kill@r0@{p - 1}",
                  {0: {"fault_replica": 0,
                       "replica_kill_at_dispatch": p - 1}}),
                F(f"replica-hang@r2@{p - 1}",
                  {2: {"fault_replica": 2,
                       "replica_hang_at_dispatch": p - 1}}),
                F(f"replica-kill@r1@{p}",
                  {1: {"fault_replica": 1,
                       "replica_kill_at_dispatch": p}}),
                F(f"replica-kill@r1@{p + 2}",
                  {1: {"fault_replica": 1,
                       "replica_kill_at_dispatch": p + 2}}),
                F(f"replica-kill@r1@{p - 2}+r2@{p - 1}",
                  {1: {"fault_replica": 1,
                       "replica_kill_at_dispatch": p - 2},
                   2: {"fault_replica": 2,
                       "replica_kill_at_dispatch": p - 1}}),
            ]
        return out
    gate = k.nan_check_steps if k.nan_check_steps <= k.total_steps else 0
    out = [F("clean")]
    if gate:
        s = max(gate, 2)
        s -= s % k.nan_check_steps
        s = s or k.nan_check_steps
        late = (k.total_steps // k.nan_check_steps) * k.nan_check_steps
        out += [F(f"nan@p0@{s}", {0: {"nan_at_step": s}})]
        if k.n_proc > 1:
            out += [
                F(f"nan@p1@{s}", {1: {"nan_at_step": s}}),
                F(f"nan@both@{s}", {0: {"nan_at_step": s},
                                    1: {"nan_at_step": s}}),
            ]
        if late != s:
            out.append(F(f"nan@p0@{late}", {0: {"nan_at_step": late}}))
            if k.n_proc > 1:
                out.append(F(f"nan@p1@{late}",
                             {1: {"nan_at_step": late}}))
        if k.nan_policy == "rollback":
            out.append(F(f"nan@p0@{s}+io-ckpt-delete",
                         {0: {"nan_at_step": s}}, io_site="ckpt-delete"))
            if k.n_proc > 1 and late != s:
                # two independent rollbacks in one run, tripped by
                # different hosts at different gate steps
                out.append(F(f"nan@p0@{s}-then-p1@{late}",
                             {0: {"nan_at_step": s},
                              1: {"nan_at_step": late}}))
    if k.coord_stop or k.n_proc == 1:
        mid = min(3, k.total_steps - 1)
        out.append(F(f"sigterm@p0@{mid}", {0: {"sigterm_at_step": mid}}))
        if k.n_proc == 1:
            out.append(F("sigterm@p0@1", {0: {"sigterm_at_step": 1}}))
        if k.n_proc > 1:
            out += [
                F(f"sigterm@p1@{mid}", {1: {"sigterm_at_step": mid}}),
                F(f"sigterm@both@{mid}", {0: {"sigterm_at_step": mid},
                                          1: {"sigterm_at_step": mid}}),
            ]
            # step 0 cannot arm (FaultPlan's zero fields are unarmed, the
            # chaos-hook truthiness contract) — step 1 is the earliest
            out.append(F("sigterm@p0@1", {0: {"sigterm_at_step": 1}}))
        if k.name == "drill-defaults":
            out.append(F(f"sigterm@p1@{k.total_steps - 1}",
                         {1: {"sigterm_at_step": k.total_steps - 1}}))
    if k.progressive_switch_at and gate:
        # the drill scenario's shape: the gate trips at the FIRST step
        # after the phase switch — rollback must restore the post-switch
        # snapshot, on every host
        s = k.progressive_switch_at + 1
        out.append(F(f"nan@p0@{s}", {0: {"nan_at_step": s}}))
        if k.n_proc > 1:
            out.append(F(f"nan@p1@{s}", {1: {"nan_at_step": s}}))
    if k.live_elastic:
        mid = min(3, k.total_steps - 1)
        # a notice on either single host (and on both at once — the
        # consensus max resolves it) must produce identical switch
        # schedules; the grow-back row round-trips submesh -> launch mesh
        out += [
            F(f"notice@p0@{mid}", {0: {"preempt_notice_at_step": mid}}),
            F(f"notice@p1@{mid}", {1: {"preempt_notice_at_step": mid}}),
            F(f"notice@both@{mid}", {0: {"preempt_notice_at_step": mid},
                                     1: {"preempt_notice_at_step": mid}}),
            F(f"notice@p0@{mid}+grow@{mid + 2}",
              {0: {"preempt_notice_at_step": mid,
                   "grow_notice_at_step": mid + 2}}),
            # shrink raised on one host, grow on the other at the SAME
            # boundary: the consensus max must resolve to shrink (losing
            # capacity is honored) on every host
            F(f"notice@p0@{mid}+grow@p1@{mid}",
              {0: {"preempt_notice_at_step": mid},
               1: {"grow_notice_at_step": mid}}),
        ]
        if gate:
            # the drill scenario's shape: the gate trips at the FIRST
            # step after the live switch — rollback must restore the
            # post-switch snapshot (the re-scattered tree), on every host
            out.append(F(f"notice@p0@{mid}+nan@p1@{mid + 1}",
                         {0: {"preempt_notice_at_step": mid},
                          1: {"nan_at_step": mid + 1}}))
    if k.collective_timeout_secs > 0 and k.n_proc > 1:
        out += [
            F("hang@p1@3", {1: {"hang_at_step": 3}}),
            F("hang@p0@1", {0: {"hang_at_step": 1}}),
            F("hang@p1@5", {1: {"hang_at_step": 5}}),
            F("hang@p0@2", {0: {"hang_at_step": 2}}),
        ]
    # de-duplicate by name (cadence arithmetic can collide), keep order
    seen, unique = set(), []
    for f in out:
        if f.name not in seen:
            seen.add(f.name)
            unique.append(f)
    return unique


def run_lattice() -> List[ScenarioResult]:
    """Explore every (config, fault) interleaving. Deterministic: the
    rendezvous transports force the only schedule the protocol admits,
    so two runs produce byte-identical results."""
    verify_transport_registry()
    _sidecar_state()  # built before any transport patching
    results = []
    for k in configs():
        for f in faults_for(k):
            results.append(run_scenario(k, f))
    return results
