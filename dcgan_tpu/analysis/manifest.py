"""The committed program manifest: programs.lock.jsonl (ISSUE 11).

The semantic tier's output is a *contract*, not a report: one JSONL row
per program the repo can dispatch — name, call-shape signature, jaxpr
fingerprint, explicit-collective census, donation map — committed next to
the baseline and regenerated only deliberately
(`python -m dcgan_tpu.analysis --semantic --write-manifest`). A check run
recomputes every row on the canonical CPU topology and reports any
difference as findings (DCG008), so the §6c.1 dispatch-stream table, the
donation-aliasing story, and the program inventory can no longer drift
from the code without failing tier-1.

Byte-identity is part of the contract (tests/test_tools.py pins it): rows
are sorted by name, keys are sorted, floats never appear, and the header
carries no timestamps — regenerating an unchanged repo reproduces the
file exactly.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

from dcgan_tpu.analysis.core import Finding

#: manifest rows are either lowered jit programs ("program") or the
#: host-side coordination transports ("transport") declared in
#: train/coordination.py::TRANSPORT_CENSUS — process_allgather is opaque
#: to `.lower()`, so its census is declared next to the transport code and
#: cross-checked against the live module by the semantic tier.
KINDS = ("program", "transport")

_HEADER = (
    "# Program manifest (ISSUE 11): every program the repo can dispatch,",
    "# lowered on the canonical topology (CPU, 2-device 'data' mesh, small",
    "# preset, partitionable threefry) — name -> call shapes -> jaxpr",
    "# fingerprint -> explicit-collective census -> donation map. DO NOT",
    "# EDIT BY HAND: regenerate with",
    "#   python -m dcgan_tpu.analysis --semantic --write-manifest",
    "# A check run (`--semantic`) recomputes every row and reports any",
    "# difference as DCG008 findings; unexplained drift fails tier-1.",
)


@dataclasses.dataclass(frozen=True)
class ProgramRecord:
    """One manifest row. `collectives` counts explicit jaxpr collective
    primitives only — GSPMD-backend programs legitimately census 0 because
    the partitioner inserts their collectives at compile time (the census
    is the *hand-written* collective stream, which is exactly the part
    that can silently drift). `donation` is None for non-donating
    programs, else {donated, aliased, pruned, unaliased:[leaf labels]}
    from the compiled executable's input_output_alias map. `cadence` is
    non-empty only for rows that appear in DESIGN §6c.1's dispatch-stream
    table (when this program/transport runs at default knobs)."""

    name: str
    kind: str
    path: str
    args: tuple            # per-argument short signature strings
    fingerprint: str       # sha256[:16] of the traced jaxpr text
    collectives: Dict[str, int]
    donation: Optional[Dict[str, object]] = None
    cadence: str = ""

    def to_json(self) -> Dict[str, object]:
        return {
            "name": self.name, "kind": self.kind, "path": self.path,
            "args": list(self.args), "fingerprint": self.fingerprint,
            "collectives": dict(sorted(self.collectives.items())),
            "donation": self.donation, "cadence": self.cadence,
        }

    @classmethod
    def from_json(cls, obj: Dict[str, object]) -> "ProgramRecord":
        return cls(name=str(obj["name"]), kind=str(obj["kind"]),
                   path=str(obj["path"]), args=tuple(obj["args"]),
                   fingerprint=str(obj["fingerprint"]),
                   collectives={str(k): int(v) for k, v in
                                dict(obj["collectives"]).items()},
                   donation=obj.get("donation"),
                   cadence=str(obj.get("cadence", "")))


def dumps(records: Sequence[ProgramRecord]) -> str:
    """Serialize to the committed JSONL form — deterministic by
    construction (sorted rows, sorted keys, no timestamps)."""
    lines = list(_HEADER)
    for rec in sorted(records, key=lambda r: r.name):
        lines.append(json.dumps(rec.to_json(), sort_keys=True,
                                separators=(",", ":")))
    return "\n".join(lines) + "\n"


def loads(text: str, origin: str = "<manifest>") -> List[ProgramRecord]:
    records: List[ProgramRecord] = []
    for i, line in enumerate(text.splitlines(), start=1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            records.append(ProgramRecord.from_json(json.loads(line)))
        except (ValueError, KeyError, TypeError) as e:
            raise ValueError(
                f"{origin}:{i}: unparseable manifest row: {e}") from e
    return records


def load_path(path: str) -> List[ProgramRecord]:
    with open(path, encoding="utf-8") as f:
        return loads(f.read(), origin=path)


def default_manifest_path() -> str:
    from dcgan_tpu.analysis.core import default_root

    return os.path.join(default_root(), "dcgan_tpu", "analysis",
                        "programs.lock.jsonl")


def _census_str(collectives: Dict[str, int]) -> str:
    if not collectives:
        return "0 explicit"
    return ", ".join(f"{op} ×{n}"
                     for op, n in sorted(collectives.items()))


def diff(live: Sequence[ProgramRecord],
         committed: Sequence[ProgramRecord]) -> List[Finding]:
    """Live recomputation vs the committed manifest -> DCG008 findings.

    Every difference is a finding: a vanished or new program, a changed
    jaxpr fingerprint, a changed collective census, a changed donation
    map or call signature. The message always names the escape hatch —
    regenerate the manifest if the drift is intentional — because the
    point is *unexplained* drift, not frozen code.
    """
    regen = ("regenerate with `python -m dcgan_tpu.analysis --semantic "
             "--write-manifest` if intentional")
    by_live = {r.name: r for r in live}
    by_committed = {r.name: r for r in committed}
    findings: List[Finding] = []

    def _f(rec: ProgramRecord, key: str, message: str) -> None:
        findings.append(Finding(
            check="DCG008", path=rec.path, line=0, symbol=rec.name,
            key=key, message=message))

    for name in sorted(set(by_committed) - set(by_live)):
        rec = by_committed[name]
        _f(rec, f"missing:{name}",
           f"program {name!r} is in the committed manifest but the live "
           f"enumeration no longer produces it — {regen}")
    for name in sorted(set(by_live) - set(by_committed)):
        rec = by_live[name]
        _f(rec, f"uncommitted:{name}",
           f"program {name!r} is dispatchable but absent from the "
           f"committed manifest — {regen}")
    for name in sorted(set(by_live) & set(by_committed)):
        a, b = by_live[name], by_committed[name]
        if a.collectives != b.collectives:
            _f(a, f"census:{name}",
               f"collective census of {name!r} drifted: live "
               f"[{_census_str(a.collectives)}] vs committed "
               f"[{_census_str(b.collectives)}] — the §6c.1 dispatch "
               f"stream is a contract; {regen}")
        if a.donation != b.donation:
            _f(a, f"donation:{name}",
               f"donation map of {name!r} drifted: live {a.donation} vs "
               f"committed {b.donation} — {regen}")
        if a.args != b.args:
            _f(a, f"shapes:{name}",
               f"call shapes of {name!r} drifted: live {list(a.args)} vs "
               f"committed {list(b.args)} — {regen}")
        if a.fingerprint != b.fingerprint:
            _f(a, f"fingerprint:{name}",
               f"jaxpr fingerprint of {name!r} drifted "
               f"({b.fingerprint} -> {a.fingerprint}) — the traced "
               f"program changed; {regen}")
    return findings


#: markers delimiting the generated dispatch-stream table in DESIGN §6c.1;
#: tests/test_analysis.py pins the block between them to
#: `render_stream_table(load_path(default_manifest_path()))`, so the doc
#: cannot drift from the committed census.
STREAM_TABLE_BEGIN = "<!-- DCG008:stream-table:begin (generated) -->"
STREAM_TABLE_END = "<!-- DCG008:stream-table:end -->"


def render_stream_table(records: Sequence[ProgramRecord]) -> str:
    """The §6c.1 default-knob collective dispatch stream as a markdown
    table, generated from manifest rows that carry a cadence. Regenerate
    via `python -m dcgan_tpu.analysis --semantic --stream-table`."""
    rows = sorted((r for r in records if r.cadence),
                  key=lambda r: (r.kind != "transport", r.name))
    lines = [
        "| program | explicit collectives (jaxpr census) | dispatched |",
        "|---------|-------------------------------------|------------|",
    ]
    for r in rows:
        census = _census_str(r.collectives)
        if r.kind == "transport":
            census += " (host transport)"
        lines.append(f"| `{r.name}` | {census} | {r.cadence} |")
    return "\n".join(lines)
