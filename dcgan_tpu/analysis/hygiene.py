"""DCG003/005/006: the smaller mechanical contracts.

- **DCG003** — raw shard_map references (the `shard_map` attribute on
  `jax` or `jax.experimental`, or imports of the experimental module)
  outside `utils/backend.py`. This container's jax 0.4.37 only ships the
  experimental form (with `check_rep`); the modern form takes
  `check_vma`. Every call site must route through the
  `utils/backend.shard_map` compat shim or the explicit-collective layer
  breaks at first use on one side of the API graduation. Docstrings are
  checked too (for the literal modern-API claim) — a doc that names the
  wrong API is how the next call site gets written against it.

- **DCG005** — traced-body hygiene: wall-clock (`time.time`,
  `datetime.now`, ...) and host RNG (`random.*`, `np.random.*`) calls
  inside functions that are jitted / shard_mapped / pallas_called. Traced
  code runs ONCE at trace time; a clock or host-RNG call bakes one
  arbitrary value into the compiled program — and with the persistent
  compile cache it also poisons reproducibility across restarts.
  Detection covers decorator jits and functions passed by name (or as
  lambdas) to `jax.jit` / `shard_map` / `smap` / `pallas_call`; values
  jitted through intermediate namespaces (e.g. `jax.jit(fns.train_step)`)
  are out of static reach and covered by the parity suites.

- **DCG006** — mutating filesystem IO in the retry-scoped modules
  (services/checkpoint/metrics paths) that is neither wrapped in
  `utils/retry.retry_io` nor explicitly fenced by a `try/except OSError`.
  One transient NFS hiccup must not kill a multi-hour run (DESIGN.md
  §6c); reads are exempt (they are either retried by their callers or
  best-effort by design), as is anything lexically inside a callable
  handed to `retry_io`.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set

from dcgan_tpu.analysis.core import (
    Config,
    Finding,
    SourceFile,
    call_name,
    dotted,
    iter_calls,
    lexical_def,
)


# -- DCG003 ------------------------------------------------------------------

def check_raw_shard_map(sources: Sequence[SourceFile],
                        config: Config) -> List[Finding]:
    findings: List[Finding] = []
    for sf in sources:
        if sf.path in config.shard_map_exempt:
            continue
        for node in ast.walk(sf.tree):
            chain = None
            if isinstance(node, ast.Attribute):
                chain = dotted(node)
            if chain in ("jax.shard_map", "jax.experimental.shard_map") or \
                    (chain or "").endswith("experimental.shard_map"):
                findings.append(_sm_finding(sf, node, chain))
            elif isinstance(node, ast.ImportFrom) and node.module and (
                    node.module == "jax.experimental.shard_map"
                    or (node.module == "jax.experimental"
                        and any(a.name == "shard_map"
                                for a in node.names))):
                findings.append(_sm_finding(sf, node, node.module))
            elif isinstance(node, ast.Import) and any(
                    a.name.startswith("jax.experimental.shard_map")
                    for a in node.names):
                findings.append(_sm_finding(
                    sf, node, "jax.experimental.shard_map"))
            elif isinstance(node, (ast.Module, ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                doc = ast.get_docstring(node, clean=False)
                if doc and "jax.shard_map" in doc:
                    line = node.body[0].lineno if node.body else 1
                    findings.append(Finding(
                        check="DCG003", path=sf.path, line=line,
                        symbol=sf.enclosing_symbol(node.body[0])
                        if node.body else "<module>",
                        key="docstring:jax.shard_map",
                        message=(
                            "docstring claims `jax.shard_map` — this "
                            "container only has jax.experimental."
                            "shard_map behind the utils/backend.shard_map "
                            "shim; name the shim so the next call site "
                            "is written against the API that exists")))
    return findings


def _sm_finding(sf: SourceFile, node: ast.AST, chain: Optional[str]
                ) -> Finding:
    return Finding(
        check="DCG003", path=sf.path, line=node.lineno,
        symbol=sf.enclosing_symbol(node), key=chain or "shard_map",
        message=(f"raw {chain!r} reference outside utils/backend.py — "
                 "route through utils/backend.shard_map (the check_vma/"
                 "check_rep API-graduation shim); a raw reference breaks "
                 "on one side of the graduation"))


# -- DCG005 ------------------------------------------------------------------

_JIT_CALLERS = frozenset({"jit", "shard_map", "smap", "pallas_call"})

_TIME_ATTRS = frozenset({"time", "monotonic", "perf_counter", "time_ns",
                         "process_time", "monotonic_ns"})
_RANDOM_ATTRS = frozenset({"random", "randint", "uniform", "randrange",
                           "choice", "choices", "shuffle", "sample",
                           "gauss", "normalvariate", "getrandbits",
                           "Random", "rand", "randn", "normal",
                           "permutation", "default_rng", "seed"})


def _banned_call(call: ast.Call, sf: Optional[SourceFile] = None
                 ) -> Optional[str]:
    name, receiver = call_name(call)
    if name is None:
        return None
    if receiver == "" and sf is not None and name in sf.from_imports:
        # `from time import time; time()` is still time.time
        receiver, name = sf.from_imports[name]
    segments = receiver.split(".") if receiver else []
    if receiver == "time" and name in _TIME_ATTRS:
        return f"time.{name}"
    if name in ("now", "utcnow", "today") and segments and \
            segments[-1] in ("datetime", "date"):
        return f"{receiver}.{name}"
    if segments and segments[0] in ("np", "numpy") and \
            segments[-1] == "random":
        return f"{receiver}.{name}"
    if receiver == "random" and name in _RANDOM_ATTRS:
        return f"random.{name}"
    return None


def _is_jit_decorator(dec: ast.AST) -> bool:
    chain = dotted(dec)
    if chain in ("jit", "jax.jit"):
        return True
    if isinstance(dec, ast.Call):
        name, receiver = call_name(dec)
        if name == "jit":
            return True
        if name == "partial" and dec.args:
            return dotted(dec.args[0]) in ("jit", "jax.jit")
    return False


def _traced_nodes(sf: SourceFile) -> List[ast.AST]:
    """Function/lambda nodes whose bodies run under a trace."""
    traced: List[ast.AST] = []
    # decorator form
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                any(_is_jit_decorator(d) for d in node.decorator_list):
            traced.append(node)
    # passed-by-name / inline-lambda form
    for call in iter_calls(sf.tree):
        name, _ = call_name(call)
        if name not in _JIT_CALLERS or not call.args:
            continue
        arg = call.args[0]
        if isinstance(arg, ast.Lambda):
            traced.append(arg)
        elif isinstance(arg, ast.Name):
            node = lexical_def(sf, call, arg.id)
            if node is not None:
                traced.append(node)
    return traced




def check_traced_body_hygiene(sources: Sequence[SourceFile],
                              config: Config) -> List[Finding]:
    findings: List[Finding] = []
    for sf in sources:
        seen: Set[int] = set()
        for fn in _traced_nodes(sf):
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            fn_name = getattr(fn, "name", "<lambda>")
            for call in iter_calls(fn):
                banned = _banned_call(call, sf)
                if banned is None:
                    continue
                findings.append(Finding(
                    check="DCG005", path=sf.path, line=call.lineno,
                    symbol=sf.enclosing_symbol(call),
                    key=f"{fn_name}:{banned}",
                    message=(
                        f"{banned}() inside traced body {fn_name!r}: "
                        "traced code runs once at trace time, so the "
                        "value is baked into the compiled program (and "
                        "cached across restarts by the persistent "
                        "compile cache) — pass timestamps in as "
                        "arguments and draw randomness from the jax PRNG "
                        "key stream")))
    return findings


# -- DCG006 ------------------------------------------------------------------

_FS_MUTATORS = {
    ("replace", "os"), ("rename", "os"), ("remove", "os"),
    ("unlink", "os"), ("makedirs", "os"), ("mkdir", "os"),
    ("rmtree", "shutil"), ("truncate", "os"),
}
_CATCHING = frozenset({"OSError", "IOError", "EnvironmentError",
                       "FileNotFoundError", "PermissionError",
                       "Exception", "BaseException"})


def _is_write_open(call: ast.Call) -> bool:
    name, receiver = call_name(call)
    if name != "open" or receiver not in ("", "io"):
        return False
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if not isinstance(mode, str):
        return False  # default "r": a read
    return any(c in mode for c in "wax+")


def _mutator(call: ast.Call, sf: Optional[SourceFile] = None
             ) -> Optional[str]:
    name, receiver = call_name(call)
    if name is None:
        return None
    if receiver == "" and sf is not None and name in sf.from_imports:
        # `from os import replace; replace(...)` is still os.replace
        receiver, name = sf.from_imports[name]
    head = receiver.split(".")[0] if receiver else ""
    for mname, mmod in _FS_MUTATORS:
        if name == mname and head == mmod:
            return f"{receiver}.{name}"
    if _is_write_open(call):
        return "open(w)"
    return None


def _retry_protected_nodes(sf: SourceFile) -> Set[int]:
    """ids of def/lambda nodes passed (by name or inline) to retry_io."""
    protected: Set[int] = set()
    for call in iter_calls(sf.tree):
        name, _ = call_name(call)
        if name != "retry_io" or not call.args:
            continue
        arg = call.args[0]
        if isinstance(arg, ast.Lambda):
            protected.add(id(arg))
        elif isinstance(arg, ast.Name):
            node = lexical_def(sf, call, arg.id)
            if node is not None:
                protected.add(id(node))
    return protected


def _fenced(sf: SourceFile, node: ast.AST, protected: Set[int]) -> bool:
    """Inside a retry_io-protected callable, or a try/except that catches
    OSError (or broader)?"""
    cur: Optional[ast.AST] = node
    prev = node
    while cur is not None:
        if id(cur) in protected:
            return True
        if isinstance(cur, ast.Try) and prev in cur.body:
            for handler in cur.handlers:
                if handler.type is None:
                    return True
                types = handler.type.elts \
                    if isinstance(handler.type, ast.Tuple) \
                    else [handler.type]
                for t in types:
                    chain = dotted(t) or ""
                    if chain.split(".")[-1] in _CATCHING:
                        return True
        if isinstance(cur, ast.stmt) or isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            prev = cur
        cur = sf.parents.get(cur)
    return False


def check_bare_io(sources: Sequence[SourceFile],
                  config: Config) -> List[Finding]:
    findings: List[Finding] = []
    for sf in sources:
        if sf.path not in config.io_modules:
            continue
        protected = _retry_protected_nodes(sf)
        for call in iter_calls(sf.tree):
            what = _mutator(call, sf)
            if what is None:
                continue
            if _fenced(sf, call, protected):
                continue
            findings.append(Finding(
                check="DCG006", path=sf.path, line=call.lineno,
                symbol=sf.enclosing_symbol(call), key=what,
                message=(
                    f"bare {what} in a retry-scoped module: one "
                    "transient OSError here kills the run — wrap the "
                    "operation in utils/retry.retry_io (bounded jittered "
                    "backoff) or fence it with an explicit try/except "
                    "OSError if best-effort is the intent")))
    return findings
