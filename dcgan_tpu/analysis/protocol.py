"""Protocol tier (ISSUE 14): DCG012 lockstep audit + DCG013 divergence lint.

DCG012 — exhaustive lockstep audit of the multi-host coordination layer.
`python -m dcgan_tpu.analysis --protocol` runs the simulator
(analysis/simulate.py): N virtual processes through the REAL decision
code over every (knob config x one-shot fault) interleaving, then audits

- termination: no virtual process left blocked in a transport a peer
  never enters (a deadlock under an armed watchdog resolves as a trip on
  every blocked process — the job dies loudly, which counts as
  terminating; a deadlock with no watchdog is a finding);
- lockstep: every process's collective schedule (op + tag + cadence
  position) is byte-identical — for watchdog interleavings, identical
  across the surviving processes with the hung process a strict prefix;
- drift: the canonical schedules are committed as
  `analysis/protocol.lock.jsonl` (same contract as programs.lock.jsonl);
  ANY difference between a fresh exploration and the committed lock is a
  finding naming the regen command (`--protocol --write-lock`).

DCG013 — static divergence lint (AST tier, import-free, runs with
DCG001-006 in the default invocation). Within the multi-host protocol
modules (`Config.protocol_modules`), any branch conditioned on
host-local state — wall clock, `jax.process_index()`, a caught
exception, a counter advanced inside an exception handler — that
directly calls a collective sink (the DCG001 sink set: coordination
transports, `pt.*` programs, Checkpointer collectives) is flagged: the
branch can be taken on a strict subset of hosts, and a collective
entered asymmetrically is the canonical SPMD deadlock. The blessed
pattern is taint SANITIZATION: gather the local state first
(`anomaly_consensus`, `stop.poll`, `process_allgather`, ...) and branch
on the gathered — mesh-uniform — verdict; names assigned from a
consensus call are never tainted. Function-local only (cross-function
divergence is the simulator's job); attribute state is not tracked.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dcgan_tpu.analysis.core import (
    Config,
    Finding,
    SourceFile,
    call_name,
)
from dcgan_tpu.analysis.threads import _is_sink

PROTOCOL_CHECKS = ("DCG012",)
LOCK_CHECK = "DCG012"
DIVERGENCE_CHECK = "DCG013"

#: repo-relative path DCG012 findings anchor on (the committed contract)
LOCK_REL_PATH = "dcgan_tpu/analysis/protocol.lock.jsonl"

_REGEN_CMD = "python -m dcgan_tpu.analysis --protocol --write-lock"

_HEADER = (
    "# Protocol lock (ISSUE 14): the canonical per-process collective",
    "# schedules of the simulated coordination protocol — every explored",
    "# (knob config x one-shot fault) interleaving, terminated and",
    "# lockstep-audited (analysis/simulate.py). Regenerate deliberately:",
    f"#   {_REGEN_CMD}",
    "# Any drift between a fresh exploration and this file is a DCG012",
    "# finding; review the diff like a contract change, because it is one.",
)


# -- DCG013: static divergence lint -------------------------------------------

#: terminal callee names that read host-local state. Receiver-gated where
#: the bare name is too generic ("now" is datetime-only, "time" must be
#: the time module's).
_TAINT_CALLS: Dict[str, Tuple[str, ...]] = {
    # wall clock
    "time": ("time",), "monotonic": ("time",), "perf_counter": ("time",),
    "process_time": ("time",), "time_ns": ("time",),
    "monotonic_ns": ("time",), "perf_counter_ns": ("time",),
    "now": ("datetime",), "utcnow": ("datetime",),
    # process identity
    "process_index": ("jax", ""), "getpid": ("os", ""),
    "gethostname": ("socket", ""), "uuid4": ("uuid", ""),
}

#: consensus calls whose RESULT is mesh-uniform: assignment from one of
#: these sanitizes the target name (the blessed gather-then-branch shape)
_SANITIZERS = frozenset({
    "anomaly_consensus", "notice_consensus", "process_allgather",
    "_allgather_i32", "_allgather_f32", "fleet_health_gather",
    "broadcast_one_to_all",
})


def _taint_call_reason(sf: SourceFile, call: ast.Call) -> Optional[str]:
    name, receiver = call_name(call)
    if name is None:
        return None
    gates = _TAINT_CALLS.get(name)
    if gates is None:
        return None
    head = receiver.split(".")[-1] if receiver else ""
    if head in gates:
        return f"{receiver + '.' if receiver else ''}{name}()"
    if "" in gates and not receiver:
        return f"{name}()"
    if not receiver:
        imp = sf.from_imports.get(name)
        if imp is not None and imp[0].split(".")[-1] in gates:
            return f"{imp[0]}.{imp[1]}()"
    return None


def _is_sanitizer(call: ast.Call) -> bool:
    name, receiver = call_name(call)
    if name in _SANITIZERS:
        return True
    # stop.poll() / notice.poll(): the coordinated-stop and
    # live-elasticity notice consensus polls — receiver-gated like the
    # DCG001 table (`opt.poll` / `selector.poll` never match)
    return name == "poll" and any("stop" in seg or "notice" in seg
                                  for seg in receiver.split("."))


def _expr_taint(sf: SourceFile, node: ast.AST,
                tainted: Dict[str, str]) -> Optional[str]:
    """Why `node`'s value is host-local, or None. A sanitizer call
    anywhere in the expression wins: its result is mesh-uniform even
    when its arguments were tainted. Tainted NAMES propagate only
    outside call-argument position — `rollback.restore(e)`'s result is
    not host-local just because a (consensus-symmetric) exception rode
    in as an argument; flow THROUGH calls is the simulator's job, not
    this lint's. Host-local SOURCE calls taint from anywhere."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _is_sanitizer(n):
            return None

    def visit(n: ast.AST, in_call_args: bool) -> Optional[str]:
        if isinstance(n, ast.Call):
            reason = _taint_call_reason(sf, n)
            if reason is not None:
                return reason
            for child in ast.iter_child_nodes(n):
                r = visit(child, True)
                if r is not None:
                    return r
            return None
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                and n.id in tainted and not in_call_args:
            return tainted[n.id]
        for child in ast.iter_child_nodes(n):
            r = visit(child, in_call_args)
            if r is not None:
                return r
        return None

    return visit(node, False)


def _function_taint(sf: SourceFile, fn: ast.AST) -> Dict[str, str]:
    """name -> reason: direct host-local sources, names assigned from
    tainted expressions, exception-handler bindings, and counters
    advanced inside exception handlers. STRONG updates: an assignment
    whose value is untainted — a sanitizing consensus call included —
    KILLS the target's taint, so the blessed gather-then-branch shape
    works even when it reuses the pre-gather name (`bad = local(); bad,
    who = anomaly_consensus(bad)`). Events process in source order and
    repeat to a bounded fixpoint so loop-carried chains resolve."""
    events: List[Tuple[int, str, object]] = []
    for n in ast.walk(fn):
        if isinstance(n, ast.ExceptHandler):
            if n.name:
                events.append((n.lineno, "seed",
                               (n.name,
                                f"exception caught as {n.name!r}")))
            for sub in ast.walk(n):
                if isinstance(sub, ast.AugAssign) \
                        and isinstance(sub.target, ast.Name):
                    events.append((sub.lineno, "seed",
                                   (sub.target.id,
                                    f"counter {sub.target.id!r} advanced "
                                    "in an exception handler")))
        targets: List[ast.expr] = []
        value: Optional[ast.AST] = None
        if isinstance(n, ast.Assign):
            targets, value = n.targets, n.value
        elif isinstance(n, ast.AnnAssign) and n.value is not None:
            targets, value = [n.target], n.value
        elif isinstance(n, ast.NamedExpr):
            targets, value = [n.target], n.value
        if value is not None:
            names = []
            for t in targets:
                names += [t.id] if isinstance(t, ast.Name) else [
                    e.id for e in ast.walk(t) if isinstance(e, ast.Name)]
            events.append((n.lineno, "assign", (tuple(names), value)))
    events.sort(key=lambda e: e[0])

    tainted: Dict[str, str] = {}
    for _ in range(8):  # bounded fixpoint (loop-carried chains)
        before = dict(tainted)
        for _line, kind, payload in events:
            if kind == "seed":
                name, reason = payload
                tainted[name] = reason
                continue
            names, value = payload
            reason = _expr_taint(sf, value, tainted)
            for nm in names:
                if reason is not None:
                    tainted[nm] = reason
                else:
                    # strong update: a mesh-uniform (or simply
                    # host-global) value overwrites the host-local one
                    tainted.pop(nm, None)
        if tainted == before:
            break
    return tainted


def _in_scope(path: str, config: Config) -> bool:
    prefixes = getattr(config, "protocol_modules", ())
    return any(path == p or path.startswith(p) for p in prefixes)


def check_divergent_branch(sources: Sequence[SourceFile],
                           config: Config) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for sf in sources:
        if not _in_scope(sf.path, config):
            continue
        fns = [n for n in ast.walk(sf.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in fns:
            tainted = _function_taint(sf, fn)
            regions: List[Tuple[ast.AST, List[ast.AST], str]] = []
            for n in ast.walk(fn):
                if isinstance(n, (ast.If, ast.While)):
                    reason = _expr_taint(sf, n.test, tainted)
                    if reason is not None:
                        regions.append(
                            (n, list(n.body) + list(n.orelse),
                             f"branch on host-local state ({reason})"))
                elif isinstance(n, ast.IfExp):
                    reason = _expr_taint(sf, n.test, tainted)
                    if reason is not None:
                        regions.append((n, [n.body, n.orelse],
                                        "conditional expression on "
                                        f"host-local state ({reason})"))
                elif isinstance(n, ast.ExceptHandler):
                    regions.append(
                        (n, list(n.body),
                         "exception handler (exceptions are host-local "
                         "events)"))
            for anchor, body, why in regions:
                hit = _first_sink(body)
                if hit is None:
                    continue
                call, sink = hit
                dedup = (sf.path, call.lineno, sink)
                if dedup in seen:
                    continue
                seen.add(dedup)
                findings.append(Finding(
                    check=DIVERGENCE_CHECK, path=sf.path, line=call.lineno,
                    symbol=sf.enclosing_symbol(call), key=sink,
                    message=(
                        f"collective sink {sink!r} dominated by a {why}: "
                        "a subset of hosts can enter this collective "
                        "while the rest never do — the canonical SPMD "
                        "deadlock (DESIGN.md §6c.1). Gather the local "
                        "state first (anomaly_consensus / stop.poll / "
                        "process_allgather) and branch on the "
                        "mesh-uniform verdict")))
    return findings


def _first_sink(body: Sequence[ast.AST]
                ) -> Optional[Tuple[ast.Call, str]]:
    """First direct collective-sink call in the region, in source order
    (one finding per region: past the first asymmetric collective the
    mesh has already diverged — reporting the rest is noise). Nested
    defs/lambdas are PRUNED as whole subtrees (manual recursion —
    ast.walk cannot prune): code textually inside a region but only
    DEFINED there runs elsewhere, e.g. a drain callback parked on
    `rollback.on_restore` inside an except handler."""
    def scan(n: ast.AST) -> Optional[Tuple[ast.Call, str]]:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return None
        if isinstance(n, ast.Call):
            name, receiver = call_name(n)
            sink = _is_sink(name, receiver)
            if sink is not None:
                return n, sink
        for child in ast.iter_child_nodes(n):
            hit = scan(child)
            if hit is not None:
                return hit
        return None

    for stmt in body:
        hit = scan(stmt)
        if hit is not None:
            return hit
    return None


# -- the protocol lock --------------------------------------------------------

def default_lock_path() -> str:
    from dcgan_tpu.analysis.core import default_root

    return os.path.join(default_root(), "dcgan_tpu", "analysis",
                        "protocol.lock.jsonl")


def _scenario_status(result) -> str:
    if any(s == "trip" for s in result.statuses):
        return "watchdog"
    tags = {str(o).split("@")[0] for o in result.outcomes}
    if tags == {"completed"}:
        return "completed"
    if tags == {"stopped"}:
        return "stopped"
    if tags == {"aborted"}:
        return "aborted"
    return "mixed:" + ",".join(sorted(tags))


def _canonical(result) -> List[str]:
    """The canonical schedule: the longest among NON-hung processes (a
    hung process's schedule ends in its hang marker and may tie the
    survivors on length), falling back to the longest overall."""
    alive = [s for s, st in zip(result.schedules, result.statuses)
             if st != "hung"]
    return list(max(alive or result.schedules, key=len))


def _canonical_schedule(result) -> Tuple[List[str], Dict[str, int]]:
    """(canonical schedule, {pid: prefix length} for processes whose
    schedule is a shorter/divergent tail — the hung process of a
    watchdog interleaving)."""
    longest = _canonical(result)
    truncated = {str(i): len(s) for i, s in enumerate(result.schedules)
                 if list(s) != longest}
    return longest, truncated


def rows_from_results(results) -> List[Dict[str, object]]:
    rows: List[Dict[str, object]] = []
    seen_cfg = set()
    for r in results:
        if r.knobs.name not in seen_cfg:
            seen_cfg.add(r.knobs.name)
            rows.append({"kind": "config", "name": r.knobs.name,
                         "knobs": r.knobs.to_json()})
        schedule, truncated = _canonical_schedule(r)
        row: Dict[str, object] = {
            "kind": "scenario", "config": r.knobs.name,
            "fault": r.fault.name, "n_proc": r.knobs.n_proc,
            "status": _scenario_status(r),
            "outcomes": [str(o) for o in r.outcomes],
            "schedule": schedule,
        }
        if truncated:
            row["truncated"] = truncated
        rows.append(row)
    return rows


def _row_key(row: Dict[str, object]) -> Tuple[str, str, str]:
    if row.get("kind") == "config":
        return ("config", str(row.get("name")), "")
    return ("scenario", str(row.get("config")), str(row.get("fault")))


def dumps(rows: Sequence[Dict[str, object]]) -> str:
    lines = list(_HEADER)
    for row in sorted(rows, key=_row_key):
        lines.append(json.dumps(row, sort_keys=True,
                                separators=(",", ":")))
    return "\n".join(lines) + "\n"


def loads(text: str, origin: str = "<lock>") -> List[Dict[str, object]]:
    rows: List[Dict[str, object]] = []
    for i, line in enumerate(text.splitlines(), start=1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            row = json.loads(line)
        except ValueError as e:
            raise ValueError(f"{origin}:{i}: unparseable lock row: {e}") \
                from e
        if not isinstance(row, dict) or row.get("kind") not in (
                "config", "scenario"):
            raise ValueError(f"{origin}:{i}: lock row must be an object "
                             "with kind config|scenario")
        rows.append(row)
    return rows


def load_path(path: str) -> List[Dict[str, object]]:
    with open(path, encoding="utf-8") as f:
        return loads(f.read(), origin=path)


def lock_diff(live: Sequence[Dict[str, object]],
              committed: Sequence[Dict[str, object]]) -> List[Finding]:
    """Fresh exploration vs the committed lock -> DCG012 drift findings.
    Every difference is a finding naming the regen command — drift is a
    protocol change and must be reviewed as one."""
    findings: List[Finding] = []

    def _f(key: str, symbol: str, message: str) -> Finding:
        return Finding(check=LOCK_CHECK, path=LOCK_REL_PATH, line=0,
                       symbol=symbol, key=key,
                       message=message + f" — if intentional, regenerate "
                       f"with `{_REGEN_CMD}` and review the diff")

    live_by = {_row_key(r): r for r in live}
    comm_by = {_row_key(r): r for r in committed}
    for key in sorted(set(comm_by) - set(live_by)):
        findings.append(_f(
            "missing-row", "/".join(k for k in key if k),
            f"committed lock row {key} no longer explored (the lattice "
            "shrank or a config/fault was renamed)"))
    for key in sorted(set(live_by) - set(comm_by)):
        findings.append(_f(
            "uncommitted-row", "/".join(k for k in key if k),
            f"explored interleaving {key} has no committed lock row"))
    for key in sorted(set(live_by) & set(comm_by)):
        if live_by[key] != comm_by[key]:
            changed = sorted(
                k for k in set(live_by[key]) | set(comm_by[key])
                if live_by[key].get(k) != comm_by[key].get(k))
            findings.append(_f(
                "schedule-drift", "/".join(k for k in key if k),
                f"interleaving {key} drifted from the committed lock "
                f"(changed field(s): {changed}) — the collective "
                "schedule of the coordination protocol moved"))
    return findings


# -- DCG012 audit -------------------------------------------------------------

def audit_results(results) -> List[Finding]:
    """Termination + lockstep findings over one lattice exploration."""
    findings: List[Finding] = []

    def _f(r, key: str, message: str) -> Finding:
        return Finding(check=LOCK_CHECK, path=LOCK_REL_PATH, line=0,
                       symbol=f"{r.knobs.name}/{r.fault.name}", key=key,
                       message=message)

    for r in results:
        if r.failure is not None and not r.watchdog_armed:
            findings.append(_f(
                r, "deadlock",
                f"interleaving deadlocked with no watchdog armed: "
                f"blocked {r.failure['waiting']}, "
                f"absent {r.failure['absent']} — a process is stuck in a "
                "transport a peer never enters"))
            continue
        if r.failure is not None:
            # watchdog resolution: the blocked survivors must all have
            # been waiting at ONE point (peers of a hung process stay
            # lockstep with each other); a split is a real divergence
            # the trip merely masked
            points = set(r.failure["waiting"].values())
            if len(points) > 1:
                findings.append(_f(
                    r, "divergence",
                    f"processes blocked at DIFFERENT collectives "
                    f"{r.failure['waiting']} — an asymmetric branch, not "
                    "a hang (the watchdog trip hides a protocol bug)"))
                continue
            if not r.failure["hung"]:
                findings.append(_f(
                    r, "deadlock",
                    f"watchdog tripped with no hung process: blocked "
                    f"{r.failure['waiting']} while "
                    f"{r.failure['absent']} exited — an exit path left "
                    "peers in a collective"))
                continue
        if not r.terminated:
            findings.append(_f(
                r, "non-termination",
                f"statuses {r.statuses} — a virtual process neither "
                "finished nor resolved"))
            continue
        findings.extend(
            _f(r, "lockstep", m) for m in _lockstep_issues(r))
    return findings


def _lockstep_issues(r) -> List[str]:
    issues: List[str] = []
    canonical = _canonical(r)
    for pid, (sched, st) in enumerate(zip(r.schedules, r.statuses)):
        compare = sched
        if st == "hung" and compare and compare[-1].startswith("local:hang"):
            compare = compare[:-1]  # the hang marker itself is expected
        if st == "hung":
            if compare != canonical[:len(compare)]:
                issues.append(
                    f"hung process {pid}'s schedule is not a prefix of "
                    f"its peers' (diverged before the hang): "
                    f"{_first_diff(compare, canonical)}")
            continue
        if sched != canonical:
            issues.append(
                f"process {pid}'s schedule diverges from the canonical: "
                f"{_first_diff(sched, canonical)}")
    done_outcomes = {str(o) for o, st in zip(r.outcomes, r.statuses)
                     if st == "done"}
    if len(done_outcomes) > 1:
        issues.append(f"processes terminated with different outcomes: "
                      f"{sorted(done_outcomes)}")
    return issues


def _first_diff(a: List[str], b: List[str]) -> str:
    for i, (x, y) in enumerate(zip(a, b)):
        if x != y:
            return f"position {i}: {x!r} vs {y!r}"
    return f"length {len(a)} vs {len(b)}"


# -- driver -------------------------------------------------------------------

def run_protocol(checks: Optional[Sequence[str]] = None,
                 lock_path: Optional[str] = None,
                 compare_lock: bool = True
                 ) -> Tuple[List[Finding], List[Dict[str, object]],
                            Dict[str, object]]:
    """(findings, lock rows, stats) for one lattice exploration. Stats
    carry the explored-interleaving counts the CI pin prints — silent
    lattice shrinkage must be visible in logs (the committed lock also
    catches it as missing-row findings)."""
    if checks:
        unknown = sorted({c.upper() for c in checks} - set(PROTOCOL_CHECKS))
        if unknown:
            raise ValueError(
                f"unknown protocol check ID(s) {unknown}; valid: "
                f"{list(PROTOCOL_CHECKS)} (DCG013 is an AST-tier check — "
                "run the default invocation)")
    from dcgan_tpu.analysis import simulate

    results = simulate.run_lattice()
    findings = audit_results(results)
    rows = rows_from_results(results)
    per_config: Dict[str, int] = {}
    for r in results:
        per_config[r.knobs.name] = per_config.get(r.knobs.name, 0) + 1
    stats = {
        "configs": len(per_config),
        "interleavings": len(results),
        "per_config": dict(sorted(per_config.items())),
    }
    if compare_lock:
        path = lock_path or default_lock_path()
        if not os.path.exists(path):
            findings.append(Finding(
                check=LOCK_CHECK, path=LOCK_REL_PATH, line=0,
                symbol="<lock>", key="missing-lock",
                message=f"no committed protocol lock at {path} — run "
                        f"`{_REGEN_CMD}` and commit the result"))
        else:
            findings.extend(lock_diff(rows, load_path(path)))
    findings.sort(key=lambda f: (f.symbol, f.key))
    return findings, rows, stats


#: the committed scenario the live 2-process drill replays against
#: (tools/chaos_drill.py mh-sigterm-stop logs its coordination-transport
#: sequence under DCGAN_PROTOCOL_LOG and compares it to this row)
DRILL_REPLAY_SCENARIO = ("drill-defaults", "sigterm@p1@3")


def coord_ops(schedule: Sequence[str]) -> List[str]:
    """A simulated schedule filtered to the logical coordination ops the
    live transports log (coordination.py DCGAN_PROTOCOL_LOG lines)."""
    from dcgan_tpu.analysis.simulate import COORD_LOG_OPS

    out: List[str] = []
    for entry in schedule:
        kind, _, label = entry.partition(":")
        if kind not in ("ag", "bar"):
            continue
        op = label.split("@")[0]
        if op in COORD_LOG_OPS:
            out.append(op)
    return out


def drill_replay_ops(lock_path: Optional[str] = None) -> List[str]:
    """The committed coordination-op sequence for the drill's
    mh-sigterm-stop scenario — what a live run's DCGAN_PROTOCOL_LOG must
    reproduce exactly."""
    rows = load_path(lock_path or default_lock_path())
    config, fault = DRILL_REPLAY_SCENARIO
    for row in rows:
        if row.get("kind") == "scenario" and row.get("config") == config \
                and row.get("fault") == fault:
            return coord_ops([str(e) for e in row["schedule"]])
    raise ValueError(
        f"committed protocol lock has no {config}/{fault} scenario — the "
        "drill replay contract is broken (regenerate the lock)")
