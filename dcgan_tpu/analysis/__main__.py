"""CLI: `python -m dcgan_tpu.analysis [--semantic] [--json] [paths...]`.

Two tiers behind one entry point and one exit contract (exit 1 on any
non-baselined finding — tests/test_tools.py pins both clean):

- default: the import-free AST tier (DCG001-006) over the package or the
  given paths, milliseconds per run;
- `--semantic`: the lowered-program tier (DCG007-010, ISSUE 11) — builds
  and `.lower()`s every dispatchable program on the canonical CPU
  topology, audits donation aliasing / collective census / retrace
  hazards / traced-body hygiene, and compares the result against the
  committed program manifest (analysis/programs.lock.jsonl).

Semantic workflow:
    python -m dcgan_tpu.analysis --semantic                  # check (CI pin)
    python -m dcgan_tpu.analysis --semantic --write-manifest # regenerate the
                                                             # committed lock
    python -m dcgan_tpu.analysis --semantic --stream-table   # DESIGN §6c.1's
                                                             # generated table

`--write-baseline FILE` drafts baseline entries for the current findings
(with `why` left as a TODO each entry must replace before review); the
baseline file is shared by both tiers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from dcgan_tpu.analysis import core


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dcgan_tpu.analysis",
        description="invariant analyzer: concurrency/donation/parity "
                    "contract lint (AST tier) and lowered-program "
                    "contract audit (--semantic)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (default: the "
                        "dcgan_tpu package; AST tier only)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one JSON object per finding + a summary line")
    p.add_argument("--baseline", default=None,
                   help="baseline JSONL of accepted findings (default: "
                        "dcgan_tpu/analysis/baseline.jsonl; pass '' to "
                        "ignore the baseline)")
    p.add_argument("--checks", nargs="+", default=None,
                   metavar="DCGXXX", help="run only these checker IDs")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write the current findings as draft baseline "
                        "entries to FILE and exit 0")
    p.add_argument("--semantic", action="store_true",
                   help="run the lowered-program tier (DCG007-010) "
                        "instead of the AST tier")
    p.add_argument("--manifest", default=None, metavar="FILE",
                   help="program manifest to check against (default: "
                        "dcgan_tpu/analysis/programs.lock.jsonl)")
    p.add_argument("--write-manifest", nargs="?", const="", default=None,
                   metavar="FILE",
                   help="with --semantic: (re)write the program manifest "
                        "(default: the committed "
                        "analysis/programs.lock.jsonl) — drift findings "
                        "are moot while regenerating, every other "
                        "finding still gates the exit code")
    p.add_argument("--stream-table", action="store_true",
                   help="with --semantic: print DESIGN §6c.1's generated "
                        "dispatch-stream table from the live census and "
                        "exit")
    args = p.parse_args(argv)

    if (args.write_manifest is not None or args.stream_table
            or args.manifest) and not args.semantic:
        p.error("--write-manifest/--stream-table/--manifest require "
                "--semantic")
    if args.stream_table and args.write_manifest is not None:
        # --stream-table is a pure printer (its stdout is pasted into
        # DESIGN §6c.1) and returns 0 unconditionally; silently swallowing
        # --write-manifest's finding-gated exit under it would let a
        # DCG007-010 regression ship — run the two steps separately
        p.error("--stream-table and --write-manifest cannot be combined "
                "(the table printer exits 0 regardless of findings); run "
                "--write-manifest first, then --stream-table")
    if args.semantic and args.paths:
        p.error("--semantic audits the dispatchable-program enumeration, "
                "not source paths")

    if args.semantic:
        return _run_semantic(p, args)
    return _run_ast(p, args)


def _run_ast(p: argparse.ArgumentParser, args) -> int:
    root = core.default_root()
    paths = args.paths or [os.path.join(root, "dcgan_tpu")]
    try:  # bad path / unknown --checks ID: usage error, not a traceback
        sources = core.collect_sources(paths, root)
        findings = core.run_checks(sources, core.Config(),
                                   checks=args.checks)
    except ValueError as e:
        p.error(str(e))

    if args.write_baseline is not None:
        return _write_baseline(args.write_baseline, findings)

    new, old = _apply_baseline(p, args, findings)
    if args.as_json:
        for finding in new:
            print(json.dumps(finding.to_json()))
        print(json.dumps({
            "label": "dcgan-analysis", "files": len(sources),
            "findings": len(findings), "baselined": len(old),
            "new_findings": len(new)}))
    else:
        for finding in new:
            print(f"{finding.path}:{finding.line}: {finding.check} "
                  f"[{finding.symbol}] {finding.message}")
        print(f"[dcgan_tpu.analysis] {len(sources)} file(s), "
              f"{len(new)} new finding(s), {len(old)} baselined"
              + ("" if new else " — clean"))
    return 1 if new else 0


def _run_semantic(p: argparse.ArgumentParser, args) -> int:
    # topology first, BEFORE anything can initialize jax: the census needs
    # >= 2 CPU devices (collectives over a size-1 axis trace away) and the
    # committed fingerprints assume partitionable threefry
    from dcgan_tpu.analysis import semantic

    semantic.ensure_semantic_platform()
    from dcgan_tpu.analysis import manifest as manifest_lib

    writing = args.write_manifest is not None
    try:
        findings, records = semantic.run_semantic(
            checks=args.checks, manifest_path=args.manifest,
            # drift against the old manifest is moot while regenerating it
            compare_manifest=not writing)
    except (ValueError, RuntimeError) as e:
        p.error(str(e))

    if args.stream_table:  # pure printer (mutually exclusive with writing)
        print(manifest_lib.render_stream_table(records))
        return 0
    if writing:
        path = args.write_manifest or manifest_lib.default_manifest_path()
        with open(path, "w", encoding="utf-8") as f:
            f.write(manifest_lib.dumps(records))
        print(f"wrote {len(records)} manifest row(s) to {path}")
    if args.write_baseline is not None:
        return _write_baseline(args.write_baseline, findings)

    new, old = _apply_baseline(p, args, findings)
    if args.as_json:
        for finding in new:
            print(json.dumps(finding.to_json()))
        print(json.dumps({
            "label": "dcgan-analysis-semantic", "programs": len(records),
            "findings": len(findings), "baselined": len(old),
            "new_findings": len(new)}))
    else:
        for finding in new:
            print(f"{finding.path}: {finding.check} "
                  f"[{finding.symbol}] {finding.message}")
        print(f"[dcgan_tpu.analysis --semantic] {len(records)} "
              f"program(s), {len(new)} new finding(s), {len(old)} "
              f"baselined" + ("" if new else " — clean"))
    return 1 if new else 0


def _write_baseline(path: str, findings) -> int:
    with open(path, "w", encoding="utf-8") as f:
        for finding in findings:
            f.write(json.dumps(finding.baseline_entry()) + "\n")
    print(f"wrote {len(findings)} draft baseline entr"
          f"{'y' if len(findings) == 1 else 'ies'} to "
          f"{path} (fill in each 'why')")
    return 0


def _apply_baseline(p: argparse.ArgumentParser, args, findings):
    baseline_path = args.baseline if args.baseline is not None \
        else core.default_baseline_path()
    try:  # malformed entry / draft TODO why: a clean error, not a dump
        baseline = core.load_baseline(baseline_path) if baseline_path \
            else []
    except ValueError as e:
        p.error(str(e))
    return core.split_baselined(findings, baseline)


if __name__ == "__main__":
    sys.exit(main())
