"""CLI: `python -m dcgan_tpu.analysis [--semantic|--protocol|--all] ...`.

Three tiers behind one entry point and one exit contract (exit 1 on any
non-baselined finding — tests/test_tools.py pins the umbrella clean):

- default: the import-free AST tier (DCG001-006 + the DCG013 divergence
  lint) over the package or the given paths, milliseconds per run; a
  full run also audits stale `# dcg: disable` suppressions (DCG014) and
  stale baseline rows (DCG015);
- `--semantic`: the lowered-program tier (DCG007-011, ISSUE 11) — builds
  and `.lower()`s every dispatchable program on the canonical CPU
  topology and compares against the committed program manifest
  (analysis/programs.lock.jsonl);
- `--protocol`: the lockstep tier (DCG012, ISSUE 14) — N virtual
  processes through the REAL coordination decision code over the
  (knob x fault) lattice, audited for termination + lockstep and
  compared against the committed analysis/protocol.lock.jsonl;
- `--all`: the umbrella — AST + semantic + protocol in one invocation
  with per-tier timing and a single exit code (the consolidated tier-1
  pin). Also the full-strength home of `--prune-baseline`.

Lock workflows:
    python -m dcgan_tpu.analysis --semantic --write-manifest   # programs
    python -m dcgan_tpu.analysis --protocol --write-lock       # schedules

`--write-baseline FILE` drafts baseline entries for the current findings
(with `why` left as a TODO each entry must replace before review); the
baseline file is shared by all tiers. `--prune-baseline` rewrites it
minus rows whose fingerprint no longer matches any finding of the
check(s) that ran.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from dcgan_tpu.analysis import core


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dcgan_tpu.analysis",
        description="invariant analyzer: concurrency/donation/parity "
                    "contract lint (AST tier), lowered-program contract "
                    "audit (--semantic), and coordination-protocol "
                    "lockstep audit (--protocol); --all runs the three "
                    "as one gate")
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (default: the "
                        "dcgan_tpu package; AST tier only)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one JSON object per finding + a summary line")
    p.add_argument("--baseline", default=None,
                   help="baseline JSONL of accepted findings (default: "
                        "dcgan_tpu/analysis/baseline.jsonl; pass '' to "
                        "ignore the baseline)")
    p.add_argument("--checks", nargs="+", default=None,
                   metavar="DCGXXX", help="run only these checker IDs")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write the current findings as draft baseline "
                        "entries to FILE and exit 0")
    p.add_argument("--prune-baseline", action="store_true",
                   help="rewrite the baseline file minus rows whose "
                        "fingerprint matches no current finding of the "
                        "check(s) that ran (full strength under --all)")
    p.add_argument("--semantic", action="store_true",
                   help="run the lowered-program tier (DCG007-011) "
                        "instead of the AST tier")
    p.add_argument("--protocol", action="store_true",
                   help="run the coordination-protocol lockstep tier "
                        "(DCG012) instead of the AST tier")
    p.add_argument("--all", action="store_true", dest="run_all",
                   help="run AST + semantic + protocol tiers in one "
                        "invocation (per-tier timing, one exit code)")
    p.add_argument("--manifest", default=None, metavar="FILE",
                   help="program manifest to check against (default: "
                        "dcgan_tpu/analysis/programs.lock.jsonl)")
    p.add_argument("--write-manifest", nargs="?", const="", default=None,
                   metavar="FILE",
                   help="with --semantic/--all: (re)write the program "
                        "manifest (default: the committed "
                        "analysis/programs.lock.jsonl) — drift findings "
                        "are moot while regenerating, every other "
                        "finding still gates the exit code")
    p.add_argument("--lock", default=None, metavar="FILE",
                   help="protocol lock to check against (default: "
                        "dcgan_tpu/analysis/protocol.lock.jsonl)")
    p.add_argument("--write-lock", nargs="?", const="", default=None,
                   metavar="FILE",
                   help="with --protocol/--all: (re)write the protocol "
                        "lock (default: the committed "
                        "analysis/protocol.lock.jsonl) — drift findings "
                        "are moot while regenerating, termination/"
                        "lockstep findings still gate the exit code")
    p.add_argument("--stream-table", action="store_true",
                   help="with --semantic: print DESIGN §6c.1's generated "
                        "dispatch-stream table from the live census and "
                        "exit")
    args = p.parse_args(argv)

    tiers = sum((args.semantic, args.protocol, args.run_all))
    if tiers > 1:
        p.error("--semantic / --protocol / --all are mutually exclusive "
                "(--all already includes the other two)")
    if (args.write_manifest is not None or args.stream_table
            or args.manifest) and not (args.semantic or args.run_all):
        p.error("--write-manifest/--manifest require --semantic or "
                "--all; --stream-table requires --semantic")
    if args.stream_table and args.run_all:
        p.error("--stream-table is a pure printer — run it under "
                "--semantic, separately from the --all gate")
    if (args.write_lock is not None or args.lock) \
            and not (args.protocol or args.run_all):
        p.error("--write-lock/--lock require --protocol or --all")
    if args.stream_table and args.write_manifest is not None:
        # --stream-table is a pure printer (its stdout is pasted into
        # DESIGN §6c.1) and returns 0 unconditionally; silently swallowing
        # --write-manifest's finding-gated exit under it would let a
        # DCG007-011 regression ship — run the two steps separately
        p.error("--stream-table and --write-manifest cannot be combined "
                "(the table printer exits 0 regardless of findings); run "
                "--write-manifest first, then --stream-table")
    if (args.semantic or args.protocol or args.run_all) and args.paths:
        p.error("--semantic/--protocol/--all audit fixed enumerations, "
                "not source paths")
    if args.run_all and (args.checks or args.write_baseline is not None):
        p.error("--all runs every tier's full check set; use the "
                "per-tier flags for --checks/--write-baseline")

    if args.run_all:
        return _run_all(p, args)
    if args.semantic:
        return _run_semantic(p, args)
    if args.protocol:
        return _run_protocol(p, args)
    return _run_ast(p, args)


# -- tier executors (findings + tier metadata; baseline applied by caller) ----

def _ast_tier(p, args, full_registry: bool):
    root = core.default_root()
    paths = args.paths or [os.path.join(root, "dcgan_tpu")]
    suppressed: List[core.Finding] = []
    sources = core.collect_sources(paths, root)
    findings = core.run_checks(sources, core.Config(), checks=args.checks,
                               suppressed_out=suppressed)
    if full_registry:
        # only a full-registry run can prove a suppression dead
        findings = findings + core.audit_stale_suppressions(sources,
                                                            suppressed)
        findings.sort(key=lambda f: (f.path, f.line, f.check))
    ran = tuple(c.upper() for c in args.checks) if args.checks else (
        core.AST_CHECK_IDS + (core.STALE_SUPPRESSION_CHECK,))
    # a path-scoped run cannot prove a baseline row dead: rows anchor on
    # files that may simply not have been scanned — only full-package
    # runs feed the DCG015 audit (and --prune-baseline)
    return findings, {"files": len(sources), "ran_checks": ran,
                      "audit_baseline": not args.paths}


def _semantic_tier(p, args):
    from dcgan_tpu.analysis import manifest as manifest_lib
    from dcgan_tpu.analysis import semantic

    writing = args.write_manifest is not None
    findings, records = semantic.run_semantic(
        checks=None if args.run_all else args.checks,
        manifest_path=args.manifest,
        # drift against the old manifest is moot while regenerating it
        compare_manifest=not writing)
    if writing:
        path = args.write_manifest or manifest_lib.default_manifest_path()
        with open(path, "w", encoding="utf-8") as f:
            f.write(manifest_lib.dumps(records))
        # stderr: stdout is the findings/summary JSON stream under --json
        print(f"wrote {len(records)} manifest row(s) to {path}",
              file=sys.stderr)
    ran = tuple(c.upper() for c in args.checks) \
        if (args.checks and not args.run_all) \
        else tuple(semantic.SEMANTIC_CHECKS)
    if writing:
        # drift findings are muted while regenerating — a baselined
        # DCG008 drift exemption must not be called stale by the very
        # run that rewrites the manifest
        ran = tuple(c for c in ran if c != "DCG008")
    return findings, records, {"programs": len(records), "ran_checks": ran}


def _protocol_tier(p, args):
    from dcgan_tpu.analysis import protocol

    writing = args.write_lock is not None
    findings, rows, stats = protocol.run_protocol(
        checks=None if args.run_all else args.checks,
        lock_path=args.lock,
        # drift against the old lock is moot while regenerating it
        compare_lock=not writing)
    if writing:
        path = args.write_lock or protocol.default_lock_path()
        with open(path, "w", encoding="utf-8") as f:
            f.write(protocol.dumps(rows))
        # stderr: stdout is the findings/summary JSON stream under --json
        print(f"wrote {len(rows)} protocol lock row(s) to {path}",
              file=sys.stderr)
    # explored-interleaving counts, ALWAYS printed: silent lattice
    # shrinkage must be visible in CI logs (the committed lock catches
    # it as missing-row findings; this line makes the scale auditable
    # at a glance)
    per = ", ".join(f"{k}={v}" for k, v in stats["per_config"].items())
    print(f"[dcgan_tpu.analysis --protocol] explored "
          f"{stats['interleavings']} interleaving(s) across "
          f"{stats['configs']} knob config(s): {per}", file=sys.stderr)
    ran = tuple(c.upper() for c in args.checks) \
        if (args.checks and not args.run_all) \
        else tuple(protocol.PROTOCOL_CHECKS)
    if writing:
        # lock-drift findings are muted while regenerating (as above for
        # the manifest) — DCG012 baseline rows stay un-audited this run
        ran = tuple(c for c in ran if c != "DCG012")
    return findings, rows, stats, {"ran_checks": ran}


# -- single-tier drivers ------------------------------------------------------

def _run_ast(p: argparse.ArgumentParser, args) -> int:
    try:  # bad path / unknown --checks ID: usage error, not a traceback
        findings, meta = _ast_tier(p, args, full_registry=not args.checks)
    except ValueError as e:
        p.error(str(e))

    if args.write_baseline is not None:
        return _write_baseline(args.write_baseline, findings)

    new, old, n_stale = _gate(p, args, findings, meta["ran_checks"],
                              audit_baseline=meta["audit_baseline"])
    return _emit(args, "dcgan-analysis", "",
                 {"files": meta["files"]}, f"{meta['files']} file(s)",
                 len(findings), new, old, n_stale)


def _run_semantic(p: argparse.ArgumentParser, args) -> int:
    # topology first, BEFORE anything can initialize jax: the census needs
    # >= 2 CPU devices (collectives over a size-1 axis trace away) and the
    # committed fingerprints assume partitionable threefry
    from dcgan_tpu.analysis import semantic

    semantic.ensure_semantic_platform()
    from dcgan_tpu.analysis import manifest as manifest_lib

    try:
        findings, records, meta = _semantic_tier(p, args)
    except (ValueError, RuntimeError) as e:
        p.error(str(e))

    if args.stream_table:  # pure printer (mutually exclusive with writing)
        print(manifest_lib.render_stream_table(records))
        return 0
    if args.write_baseline is not None:
        return _write_baseline(args.write_baseline, findings)

    new, old, n_stale = _gate(p, args, findings, meta["ran_checks"])
    return _emit(args, "dcgan-analysis-semantic", " --semantic",
                 {"programs": meta["programs"]},
                 f"{meta['programs']} program(s)",
                 len(findings), new, old, n_stale)


def _run_protocol(p: argparse.ArgumentParser, args) -> int:
    try:
        findings, rows, stats, meta = _protocol_tier(p, args)
    except (ValueError, RuntimeError) as e:
        p.error(str(e))

    if args.write_baseline is not None:
        return _write_baseline(args.write_baseline, findings)

    new, old, n_stale = _gate(p, args, findings, meta["ran_checks"])
    return _emit(args, "dcgan-analysis-protocol", " --protocol",
                 {"configs": stats["configs"],
                  "interleavings": stats["interleavings"]},
                 f"{stats['interleavings']} interleaving(s) / "
                 f"{stats['configs']} config(s)",
                 len(findings), new, old, n_stale)


# -- the umbrella -------------------------------------------------------------

def _run_all(p: argparse.ArgumentParser, args) -> int:
    # the semantic tier's canonical topology must be arranged before the
    # FIRST jax import in this process — the AST tier never imports jax
    # and the protocol tier only patches process identity, so one
    # arrangement up front serves all three
    from dcgan_tpu.analysis import semantic

    semantic.ensure_semantic_platform()

    tier_meta = {}
    findings: List[core.Finding] = []
    ran_checks: List[str] = []
    try:
        t0 = time.monotonic()
        ast_findings, meta = _ast_tier(p, args, full_registry=True)
        tier_meta["ast"] = {"files": meta["files"],
                            "findings": len(ast_findings),
                            "ms": round((time.monotonic() - t0) * 1e3, 1)}
        findings += ast_findings
        ran_checks += list(meta["ran_checks"])

        t0 = time.monotonic()
        sem_findings, records, meta = _semantic_tier(p, args)
        tier_meta["semantic"] = {
            "programs": meta["programs"], "findings": len(sem_findings),
            "ms": round((time.monotonic() - t0) * 1e3, 1)}
        findings += sem_findings
        ran_checks += list(meta["ran_checks"])

        t0 = time.monotonic()
        proto_findings, rows, stats, meta = _protocol_tier(p, args)
        tier_meta["protocol"] = {
            "configs": stats["configs"],
            "interleavings": stats["interleavings"],
            "findings": len(proto_findings),
            "ms": round((time.monotonic() - t0) * 1e3, 1)}
        findings += proto_findings
        ran_checks += list(meta["ran_checks"])
    except (ValueError, RuntimeError) as e:
        p.error(str(e))

    new, old, n_stale = _gate(p, args, findings, ran_checks)
    timing = ", ".join(f"{t} {m['ms']:.0f} ms ({m['findings']} "
                       f"finding(s))" for t, m in tier_meta.items())
    return _emit(args, "dcgan-analysis-all", " --all",
                 {"tiers": tier_meta}, timing,
                 len(findings), new, old, n_stale)


# -- shared plumbing ----------------------------------------------------------

def _emit(args, label: str, flag: str, extra: dict, human_stats: str,
          n_findings: int, new, old, n_stale: int) -> int:
    """ONE output/exit contract for every tier: finding rows + a summary
    (JSON object stream under --json — nothing else may print to stdout
    there), `:line` suffix only when a finding has a source line, exit 1
    on any new finding."""
    if args.as_json:
        for finding in new:
            print(json.dumps(finding.to_json()))
        print(json.dumps({
            "label": label, **extra, "findings": n_findings,
            "baselined": len(old), "stale_baseline_rows": n_stale,
            "new_findings": len(new)}))
    else:
        for finding in new:
            where = f":{finding.line}" if finding.line else ""
            print(f"{finding.path}{where}: {finding.check} "
                  f"[{finding.symbol}] {finding.message}")
        print(f"[dcgan_tpu.analysis{flag}] {human_stats}, "
              f"{len(new)} new finding(s), {len(old)} baselined"
              + ("" if new else " — clean"))
    return 1 if new else 0


def _write_baseline(path: str, findings) -> int:
    with open(path, "w", encoding="utf-8") as f:
        for finding in findings:
            f.write(json.dumps(finding.baseline_entry()) + "\n")
    print(f"wrote {len(findings)} draft baseline entr"
          f"{'y' if len(findings) == 1 else 'ies'} to "
          f"{path} (fill in each 'why')")
    return 0


def _gate(p: argparse.ArgumentParser, args, findings, ran_checks,
          audit_baseline: bool = True):
    """Apply the baseline, then the stale-row audit over the checks that
    ran (DCG015); `--prune-baseline` resolves stale rows by rewriting the
    file instead of reporting them. Returns (new, baselined, n_stale).
    `audit_baseline=False` (path-scoped AST runs) skips the stale audit
    entirely: a row anchored on an unscanned file is not dead, just out
    of view."""
    baseline_path = args.baseline if args.baseline is not None \
        else core.default_baseline_path()
    try:  # malformed entry / draft TODO why: a clean error, not a dump
        entries = core.load_baseline(baseline_path) if baseline_path \
            else []
    except ValueError as e:
        p.error(str(e))
    new, old = core.split_baselined(findings, entries)
    rel = os.path.relpath(baseline_path, core.default_root()).replace(
        os.sep, "/") if baseline_path else "<none>"
    if not audit_baseline:
        return new, old, 0
    stale_findings, stale_rows = core.audit_stale_baseline(
        entries, old, ran_checks, rel)
    if args.prune_baseline and stale_rows:
        dropped = core.prune_baseline_file(baseline_path, stale_rows)
        # stderr: stdout is the findings/summary JSON stream under --json
        print(f"pruned {dropped} stale baseline row(s) from "
              f"{baseline_path}", file=sys.stderr)
    elif stale_findings:
        # stale-audit findings never pass through the baseline: the fix
        # is deleting the dead row, not exempting the exemption
        new = list(new) + stale_findings
    return new, old, len(stale_rows)


if __name__ == "__main__":
    sys.exit(main())
