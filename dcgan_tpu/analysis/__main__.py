"""CLI: `python -m dcgan_tpu.analysis [--json] [--baseline FILE] [paths...]`.

Runs the six invariant checkers over the package (or the given paths),
applies per-line suppressions and the committed baseline, prints the
findings, and exits 1 if any NON-baselined finding remains — the tier-1
contract (tests/test_tools.py pins a clean run).

`--write-baseline FILE` drafts baseline entries for the current findings
(with `why` left as a TODO each entry must replace before review).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from dcgan_tpu.analysis import core


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dcgan_tpu.analysis",
        description="invariant analyzer: concurrency/donation/parity "
                    "contract lint over the dcgan_tpu package")
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (default: the "
                        "dcgan_tpu package)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one JSON object per finding + a summary line")
    p.add_argument("--baseline", default=None,
                   help="baseline JSONL of accepted findings (default: "
                        "dcgan_tpu/analysis/baseline.jsonl; pass '' to "
                        "ignore the baseline)")
    p.add_argument("--checks", nargs="+", default=None,
                   metavar="DCGXXX", help="run only these checker IDs")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write the current findings as draft baseline "
                        "entries to FILE and exit 0")
    args = p.parse_args(argv)

    root = core.default_root()
    paths = args.paths or [os.path.join(root, "dcgan_tpu")]
    try:  # bad path / unknown --checks ID: usage error, not a traceback
        sources = core.collect_sources(paths, root)
        findings = core.run_checks(sources, core.Config(),
                                   checks=args.checks)
    except ValueError as e:
        p.error(str(e))

    if args.write_baseline is not None:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            for finding in findings:
                f.write(json.dumps(finding.baseline_entry()) + "\n")
        print(f"wrote {len(findings)} draft baseline entr"
              f"{'y' if len(findings) == 1 else 'ies'} to "
              f"{args.write_baseline} (fill in each 'why')")
        return 0

    baseline_path = args.baseline if args.baseline is not None \
        else core.default_baseline_path()
    try:  # malformed entry / draft TODO why: a clean error, not a dump
        baseline = core.load_baseline(baseline_path) if baseline_path \
            else []
    except ValueError as e:
        p.error(str(e))
    new, old = core.split_baselined(findings, baseline)

    if args.as_json:
        for finding in new:
            print(json.dumps(finding.to_json()))
        print(json.dumps({
            "label": "dcgan-analysis", "files": len(sources),
            "findings": len(findings), "baselined": len(old),
            "new_findings": len(new)}))
    else:
        for finding in new:
            print(f"{finding.path}:{finding.line}: {finding.check} "
                  f"[{finding.symbol}] {finding.message}")
        print(f"[dcgan_tpu.analysis] {len(sources)} file(s), "
              f"{len(new)} new finding(s), {len(old)} baselined"
              + ("" if new else " — clean"))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
