"""DCG002: donated buffers must be XLA-owned when the compile cache is on.

The jaxlib 0.4.37 hazard PR 5 shipped guards for (utils/checkpoint.py):
executables DESERIALIZED from the persistent compilation cache donate
buffers in place with none of the safety fresh-compiled ones have —
donating a tensorstore-restored or `device_put` buffer corrupts the heap,
and `device_get`'s zero-copy views silently mutate under a later donated
dispatch. Every value that flows from `device_get` / `device_put` /
an Orbax `_mgr.restore(...)` into a donating jit argument must first pass
through `owned_host_copy` / `_rebase_onto_xla_buffers` / `device_copy`.

Scope: function-local taint tracking, statements in textual order.

- sources: any expression whose subtree calls `device_get`/`device_put`
  (any receiver) or `restore` on a `*_mgr` receiver;
- sanitizers: `owned_host_copy`, `_rebase_onto_xla_buffers`,
  `device_copy` — an expression containing a sanitizer call is clean
  (the sanitizer's output is what flows onward);
- propagation: direct aliasing only (`x = tainted_name`, conditional
  expressions, tuples) — routing taint through arbitrary calls would flag
  every `int(device_get(step))` derived scalar;
- sinks: calls to names bound from `jax.jit(..., donate_argnums=...)`
  anywhere in the module, and `pt.step/multi_step/d_update/g_update`
  style dispatches (attr gated on a `pt` receiver).

Cross-function flows are out of static reach; the committed guards at the
module boundaries (restore/rollback/snapshot paths) plus the parity and
chaos suites own those.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set

from dcgan_tpu.analysis.core import (
    Config,
    Finding,
    SourceFile,
    call_name,
    iter_calls,
)

CHECK_ID = "DCG002"

SANITIZERS = frozenset({
    "owned_host_copy", "_rebase_onto_xla_buffers", "device_copy",
})
_DONATING_ATTRS = frozenset({"step", "multi_step", "d_update", "g_update"})


def _is_source_call(call: ast.Call) -> bool:
    name, receiver = call_name(call)
    if name in ("device_get", "device_put"):
        return True
    return name == "restore" and receiver.split(".")[-1].endswith("_mgr")


def _expr_state(expr: ast.AST, tainted: Set[str]) -> Optional[bool]:
    """True = tainted, False = clean, None = neither (untracked)."""
    for call in iter_calls(expr):
        name, _ = call_name(call)
        if name in SANITIZERS:
            return False
    for call in iter_calls(expr):
        if _is_source_call(call):
            return True
    # direct aliasing only
    if isinstance(expr, ast.Name):
        return True if expr.id in tainted else None
    if isinstance(expr, ast.IfExp):
        a = _expr_state(expr.body, tainted)
        b = _expr_state(expr.orelse, tainted)
        if a or b:
            return True
        return None
    if isinstance(expr, ast.Tuple):
        states = [_expr_state(e, tainted) for e in expr.elts]
        if any(s is True for s in states):
            return True
        return None
    return None


def _donating_names(tree: ast.AST) -> Set[str]:
    """Names assigned from jax.jit(..., donate_argnums=...) calls."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        name, receiver = call_name(value)
        if name != "jit":
            continue
        if not any(kw.arg == "donate_argnums" for kw in value.keywords):
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                out.add(target.id)
    return out


def _is_donating_call(call: ast.Call, donating: Set[str]) -> Optional[str]:
    name, receiver = call_name(call)
    if name is None:
        return None
    if receiver == "" and name in donating:
        return name
    # whole-segment receiver match: `pt.step` donates, `opt.step` is an
    # optimizer and must never trip the heuristic
    if name in _DONATING_ATTRS and any(
            seg in ("pt", "pt_backoff") for seg in receiver.split(".")):
        return f"{receiver}.{name}"
    return None


def _statements(body: List[ast.stmt]):
    """Statements in textual order, descending into compound blocks but
    NOT into nested function/class scopes (each def gets its own taint
    pass — mixing scopes would smear taint across unrelated functions)."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield stmt
        for attr in ("body", "orelse", "finalbody", "handlers"):
            sub = getattr(stmt, attr, None)
            if not sub:
                continue
            for item in sub:
                if isinstance(item, ast.ExceptHandler):
                    yield from _statements(item.body)
                elif isinstance(item, ast.stmt):
                    yield from _statements([item])


def _stmt_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions evaluated by the statement ITSELF (not by the
    sub-statements of its blocks, which get their own turn)."""
    if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.Expr, ast.Return)):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, ast.AnnAssign):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, (ast.If, ast.While, ast.Assert)):
        return [stmt.test]
    if isinstance(stmt, ast.For):
        return [stmt.iter]
    if isinstance(stmt, ast.With):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Raise) and stmt.exc is not None:
        return [stmt.exc]
    return []


def check_donation_hazard(sources: Sequence[SourceFile],
                          config: Config) -> List[Finding]:
    findings: List[Finding] = []
    for sf in sources:
        donating = _donating_names(sf.tree)
        funcs = [n for n in ast.walk(sf.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in funcs + [sf.tree]:
            body = fn.body if hasattr(fn, "body") else []
            tainted: Set[str] = set()
            for stmt in _statements(body):
                # a donating call in this statement fed a tainted value?
                stmt_calls = [c for expr in _stmt_exprs(stmt)
                              for c in iter_calls(expr)]
                for call in stmt_calls:
                    sink = _is_donating_call(call, donating)
                    if sink is None:
                        continue
                    for arg in list(call.args) + [kw.value
                                                  for kw in call.keywords]:
                        state = _expr_state(arg, tainted)
                        if state is True:
                            key = arg.id if isinstance(arg, ast.Name) \
                                else "<expr>"
                            findings.append(Finding(
                                check=CHECK_ID, path=sf.path,
                                line=call.lineno,
                                symbol=sf.enclosing_symbol(call),
                                key=f"{sink}({key})",
                                message=(
                                    f"value {key!r} flows from device_get/"
                                    f"device_put/Orbax restore into "
                                    f"donating call {sink!r} without "
                                    "passing through owned_host_copy/"
                                    "_rebase_onto_xla_buffers — under the "
                                    "persistent compile cache a "
                                    "deserialized executable donates this "
                                    "buffer in place and corrupts the "
                                    "heap (utils/checkpoint.py)")))
                # then update taint from assignments in this statement
                if isinstance(stmt, ast.Assign):
                    state = _expr_state(stmt.value, tainted)
                    for target in stmt.targets:
                        if isinstance(target, ast.Name):
                            if state is True:
                                tainted.add(target.id)
                            elif state is False:
                                tainted.discard(target.id)
                elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                    if isinstance(stmt.target, ast.Name):
                        state = _expr_state(stmt.value, tainted)
                        if state is True:
                            tainted.add(stmt.target.id)
                        elif state is False:
                            tainted.discard(stmt.target.id)
    # module-level pass double-counts function statements; dedupe
    seen = set()
    out = []
    for f in findings:
        k = (f.path, f.line, f.key)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out
