"""Runtime thread-discipline tripwire: DCG001's dynamic complement.

The static call-graph checker (analysis/threads.py) terminates at every
dynamic call (`task.fn()`, `self._hook(...)`) — exactly the indirection
the services worker and the watchdog are built from. This module closes
that gap at runtime: with `DCGAN_THREAD_CHECKS=1`, the known collective
entry points are wrapped to assert they execute on the dispatch thread —
the thread that entered `train()` — and any off-thread collective raises
`ThreadDisciplineError` naming the entry point and both threads, instead
of deadlocking a mesh some minutes later.

Zero cost when off: nothing is wrapped unless the env var is set, so the
default trainer runs the original callables with no indirection at all.

Wrapped entry points (install()):
- coordination's collective transports and helpers (`_allgather_i32`,
  `_allgather_f32`, `fleet_health_gather`, `anomaly_consensus`,
  `warmup_barrier`),
- Checkpointer's collective methods (save / restore_latest /
  delete_steps_after / wait — Orbax array gathers),
- every compiled ParallelTrain program (`pt.step`, `pt.sample`, ... —
  wrapped at construction by `wrap_parallel_train`, called from
  ParallelTrain.__post_init__ so both backends are covered; the wrapper
  object forwards attribute access, so AOT warmup's `.lower()` path is
  untouched).

The assertion is scoped: checks fire only inside a `dispatch_scope()` —
entered by trainer.train() on its calling thread — so unit tests and
tools that legitimately call collectives from their own (single) thread
outside a training run are never tripped. Tier-1 runs the whole test
suite with the tripwire armed (tests/conftest.py) and must record zero
trips at default knobs; `tools/chaos_drill.py thread-checks` proves the
same end to end through a real trainer subprocess.
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading
from typing import Optional

ENV_VAR = "DCGAN_THREAD_CHECKS"


class ThreadDisciplineError(AssertionError):
    """A mesh-wide collective entry point ran off the dispatch thread."""


_installed = False
_wrapped_count = 0
#: the set of threads currently inside a dispatch_scope(). A SET, not a
#: single slot: the serving fleet (ISSUE 19) runs N replica dispatch
#: threads in one process, each a legitimate owner of ITS replica's
#: programs — a single global owner would make replica A's collectives
#: trip the moment replica B entered its scope. Membership is per-thread
#: (add on enter, remove on outermost exit), guarded by _owners_lock;
#: check() reads the set without the lock (a stale read can only happen
#: during scope enter/exit, where the caller by definition owns or owned
#: the scope).
_dispatch_owners: set = set()
_owners_lock = threading.Lock()

#: coordination-module collective entry points install() wraps. Module
#: constant (not an install()-local literal) because the semantic tier
#: cross-checks coordination.TRANSPORT_CENSUS against it (DCG008): every
#: declared transport must also be thread-policed here.
WRAPPED_TRANSPORTS = ("_allgather_i32", "_allgather_f32",
                      "fleet_health_gather", "anomaly_consensus",
                      "warmup_barrier")


def enabled() -> bool:
    """Whether the env knob asks for runtime thread checks."""
    return os.environ.get(ENV_VAR, "") == "1"


def installed() -> bool:
    return _installed


def check(what: str) -> None:
    """Assert the caller is a dispatch thread (no-op while no
    dispatch_scope is active — tools and tests own their single
    thread)."""
    owners = _dispatch_owners
    if not owners:
        return
    cur = threading.current_thread()
    if cur not in owners:
        names = sorted(t.name for t in owners)
        raise ThreadDisciplineError(
            f"collective entry point {what!r} called from thread "
            f"{cur.name!r} while the dispatch thread owner(s) are "
            f"{names} — mesh-wide collectives must stay on the dispatch "
            "thread (DESIGN.md §6b): a background thread's collectives "
            "have no cross-process ordering against the dispatch stream "
            "and two processes interleaving them differently deadlock "
            "the mesh")


def dispatch_owners() -> frozenset:
    """The current dispatch-scope owner threads (empty = no active
    scope). Read surface for tests; never mutate through this."""
    return frozenset(_dispatch_owners)


@contextlib.contextmanager
def dispatch_scope():
    """Mark the current thread as A dispatch thread for the duration
    (re-entrant per thread: the outermost exit removes it). Each scoped
    thread is an independent owner — trainer.train() scopes its calling
    thread, and every serve replica's worker scopes its own dispatch
    thread. A no-op when the tripwire is off."""
    if not _installed:
        yield
        return
    cur = threading.current_thread()
    with _owners_lock:
        already_owner = cur in _dispatch_owners
        _dispatch_owners.add(cur)
    try:
        yield
    finally:
        if not already_owner:
            with _owners_lock:
                _dispatch_owners.discard(cur)


class _GuardedFn:
    """A callable wrapper that runs the thread check, then delegates —
    including attribute access, so jitted programs keep `.lower()` and
    friends for the AOT warmup path."""

    __slots__ = ("_fn", "_what")

    def __init__(self, fn, what: str):
        self._fn = fn
        self._what = what

    def __call__(self, *args, **kwargs):
        check(self._what)
        return self._fn(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __repr__(self):
        return f"<thread-checked {self._what}: {self._fn!r}>"


def _wrap_function(fn, what: str):
    """Plain-function wrapper (used for methods — a _GuardedFn object
    would not bind `self` through the descriptor protocol)."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        check(what)
        return fn(*args, **kwargs)

    wrapped.__dcgan_tripwire__ = True
    return wrapped


def install() -> int:
    """Wrap the module/class-level collective entry points; returns the
    number of wrapped callables. Idempotent — a second call is a no-op
    (re-wrapping would capture test shims installed in between)."""
    global _installed, _wrapped_count
    if _installed:
        return _wrapped_count
    from dcgan_tpu.train import coordination
    from dcgan_tpu.utils import checkpoint

    count = 0
    for name in WRAPPED_TRANSPORTS:
        setattr(coordination, name,
                _wrap_function(getattr(coordination, name),
                               f"coordination.{name}"))
        count += 1
    for name in ("save", "restore_latest", "delete_steps_after", "wait"):
        setattr(checkpoint.Checkpointer, name,
                _wrap_function(getattr(checkpoint.Checkpointer, name),
                               f"Checkpointer.{name}"))
        count += 1
    _installed = True
    _wrapped_count = count
    return count


def maybe_install() -> bool:
    """Env-gated install; prints one armed line so drills can assert the
    tripwire was live. Returns whether the tripwire is installed."""
    if not enabled():
        return _installed
    if not _installed:
        n = install()
        print(f"[dcgan_tpu] thread-discipline tripwire armed "
              f"({n} module entry points + ParallelTrain programs; "
              f"{ENV_VAR}=1)", flush=True)
    return True


#: ParallelTrain fields that dispatch compiled mesh programs
_PROGRAM_FIELDS = ("init", "step", "sample", "summarize", "eval_losses",
                   "multi_step", "gen_fakes", "d_update", "g_update")


def wrap_parallel_train(pt) -> None:
    """Wrap every program field of a ParallelTrain in place (frozen
    dataclass — object.__setattr__). Called from __post_init__ BEFORE the
    `programs` dict is derived, so the dict picks up the wrapped
    callables too. No-op unless the tripwire is installed."""
    if not _installed:
        return
    for name in _PROGRAM_FIELDS:
        fn = getattr(pt, name)
        if isinstance(fn, _GuardedFn):
            continue
        object.__setattr__(pt, name, _GuardedFn(fn, f"pt.{name}"))
