"""Analyzer plumbing: findings, suppressions, baselines, the check registry.

The invariant analyzer (ISSUE 8) is a plain-AST pass — no imports of the
code under analysis, no jax — so it runs in milliseconds on every tier-1
pass and cannot be broken by a module that fails to import. Each checker
is a function `(sources, config) -> [Finding]` registered in `CHECKS`
under its stable ID; this module owns everything the checkers share:

- `SourceFile`: one parsed file (AST + parent links + the per-line
  `# dcg: disable=DCGxxx` suppression map). Paths are repo-relative
  POSIX strings — the stable coordinate findings and baselines key on.
- `Finding.fingerprint()` deliberately EXCLUDES the line number: a
  baseline must survive unrelated edits above the finding, so identity is
  (check, file, enclosing symbol, detail key), not a line.
- Baselines are JSONL (one object per line) because JSON has no comments
  and every baselined finding must carry a one-line `why` justification —
  the file is the reviewed list of intentional exemptions, not a dumping
  ground (`python -m dcgan_tpu.analysis --write-baseline` drafts entries
  with `why` left as TODO).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, List, Optional, Sequence, Tuple

_SUPPRESS_RE = re.compile(r"#\s*dcg:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation at a concrete site."""

    check: str      # "DCG001".."DCG006"
    path: str       # repo-relative POSIX path
    line: int       # 1-based line of the offending node
    symbol: str     # enclosing function/class qualname, or "<module>"
    key: str        # stable detail (sink name, key literal, call name...)
    message: str

    def fingerprint(self) -> Tuple[str, str, str, str]:
        """Line-free identity — what suppression baselines match on."""
        return (self.check, self.path, self.symbol, self.key)

    def to_json(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def baseline_entry(self, why: str = "TODO: justify") -> Dict[str, str]:
        return {"check": self.check, "path": self.path,
                "symbol": self.symbol, "key": self.key, "why": why}


class SourceFile:
    """One parsed python file plus the lookup structure checkers need."""

    def __init__(self, path: str, source: str):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source)
        # module dotted name ("dcgan_tpu.train.services") — the call-graph
        # checker resolves cross-module imports through it
        self.module = self.path[:-3].replace("/", ".") \
            if self.path.endswith(".py") else self.path.replace("/", ".")
        # suppressions come from real COMMENT tokens only (ISSUE 14): the
        # old per-line regex also matched `# dcg: disable=...` mentions
        # inside docstrings, which both created phantom suppressions and
        # would have made the stale-suppression audit (DCG014) flag prose
        self.suppressed: Dict[int, set] = {}
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(source).readline))
        except (tokenize.TokenError, IndentationError):  # pragma: no cover
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                ids = {t.strip().upper() for t in m.group(1).split(",")
                       if t.strip()}
                self.suppressed[tok.start[0]] = ids
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        # local alias -> (module, original name) for `from X import y` —
        # checkers use it to see through un-qualified calls
        # (`from time import time; time()` is still time.time)
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = \
                        (node.module, alias.name)

    @classmethod
    def from_source(cls, source: str, path: str) -> "SourceFile":
        return cls(path, source)

    def is_suppressed(self, finding: Finding) -> bool:
        return finding.check in self.suppressed.get(finding.line, ())

    def enclosing_symbol(self, node: ast.AST) -> str:
        """Dotted qualname of the innermost enclosing def/class chain."""
        parts: List[str] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts)) or "<module>"

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None


@dataclasses.dataclass
class Config:
    """Checker knobs. The defaults describe THIS repo; fixture suites pass
    synthetic paths that land inside (or outside) the scopes below."""

    # DCG004: modules whose metric-key literals must appear in the
    # inventory (dcgan_tpu/train/event_keys.py unless overridden here)
    inventory: Optional[Dict[str, str]] = None
    parity_modules: Tuple[str, ...] = (
        "dcgan_tpu/train/trainer.py",
        "dcgan_tpu/train/coordination.py",
        "dcgan_tpu/serve/server.py",
        "dcgan_tpu/serve/__main__.py",
        # fleet report rows (ISSUE 19): serve/fleet_* and the drop split
        "dcgan_tpu/serve/fleet.py",
        "dcgan_tpu/serve/router.py",
        # emits the progressive/* scalar-row extras (ISSUE 15)
        "dcgan_tpu/progressive/phases.py",
    )
    # DCG001: thread targets that ARE a dispatch thread by design — a
    # subsystem whose single worker owns every collective/program dispatch
    # (the serving plane's ServeWorker). Collectives reachable from these
    # roots are on the right thread by definition; the runtime tripwire
    # still polices them (the worker enters dispatch_scope), so the
    # exemption is declared, not assumed. Format: "path::QualName".
    dispatch_thread_targets: Tuple[str, ...] = (
        "dcgan_tpu/serve/worker.py::ServeWorker._run",
        # each protocol-simulator thread IS the dispatch thread of its
        # virtual process (ISSUE 14) — it drives the real coordination
        # transports through rendezvous shims by design
        "dcgan_tpu/analysis/simulate.py::_virtual_process_main",
    )
    # DCG006: modules whose mutating filesystem calls must be retried
    # (utils/retry.retry_io) or explicitly fenced with try/except OSError
    io_modules: Tuple[str, ...] = (
        "dcgan_tpu/train/services.py",
        "dcgan_tpu/utils/checkpoint.py",
        "dcgan_tpu/utils/metrics.py",
    )
    # DCG003: the one file allowed to name jax's shard_map directly
    shard_map_exempt: Tuple[str, ...] = ("dcgan_tpu/utils/backend.py",)
    # DCG013: modules that participate in the multi-host lockstep
    # protocol — the divergence lint only makes sense where N processes
    # must issue identical collective streams (the serving plane is a
    # single-process surface by design and stays out)
    protocol_modules: Tuple[str, ...] = (
        "dcgan_tpu/train/",
        "dcgan_tpu/utils/checkpoint.py",
        "dcgan_tpu/elastic/",
        "dcgan_tpu/parallel/",
        "dcgan_tpu/evals/",
        # the progressive switch dispatches mesh programs (per-phase init,
        # the state-carry copies) at a step-keyed boundary — its decision
        # code must stay free of host-local-state branches (ISSUE 15)
        "dcgan_tpu/progressive/",
    )

    def load_inventory(self) -> Dict[str, str]:
        if self.inventory is not None:
            return self.inventory
        from dcgan_tpu.train.event_keys import EVENT_KEYS

        return EVENT_KEYS


def collect_sources(paths: Sequence[str], root: str) -> List[SourceFile]:
    """Every .py file under `paths`, parsed, with repo-relative names."""
    out: List[SourceFile] = []
    seen = set()
    for p in paths:
        p = os.path.abspath(p)
        files: List[str] = []
        if os.path.isdir(p):
            for dirpath, dirnames, names in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(os.path.join(dirpath, n)
                             for n in sorted(names) if n.endswith(".py"))
        elif p.endswith(".py") and os.path.isfile(p):
            files.append(p)
        else:
            raise ValueError(
                f"path {p!r} is not a directory or an existing .py file")
        for f in files:
            rel = os.path.relpath(f, root).replace(os.sep, "/")
            if rel in seen:
                continue
            seen.add(rel)
            with open(f, encoding="utf-8") as fh:
                out.append(SourceFile(rel, fh.read()))
    return out


# -- AST helpers shared by the checkers --------------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """'jax.lax.psum' for a pure Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Tuple[Optional[str], str]:
    """(terminal callee name, dotted receiver or '') for a Call node."""
    func = call.func
    if isinstance(func, ast.Name):
        return func.id, ""
    if isinstance(func, ast.Attribute):
        return func.attr, dotted(func.value) or ""
    return None, ""


def iter_calls(node: ast.AST):
    """Every Call in `node`'s subtree (nested defs and lambdas included —
    the conservative read: code textually inside a function is attributed
    to it, which is exactly right for worker closures and retry thunks)."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def lexical_def(sf: SourceFile, site: ast.AST,
                name: str) -> Optional[ast.AST]:
    """The def named `name` visible from `site`: innermost enclosing
    function scopes first, then module level — how thread-target
    closures, retry thunks, and jitted local bodies are resolved. Shared
    by the thread and hygiene checkers so their resolution semantics
    cannot drift."""
    cur: Optional[ast.AST] = site
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Module)):
            for child in ast.walk(cur):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) \
                        and child.name == name:
                    return child
        cur = sf.parents.get(cur)
    return None


# -- baseline ----------------------------------------------------------------

def load_baseline(path: str) -> List[Dict[str, str]]:
    entries: List[Dict[str, str]] = []
    if not path or not os.path.exists(path):
        return entries
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, start=1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                obj = json.loads(line)
            except ValueError as e:
                raise ValueError(
                    f"{path}:{i}: unparseable baseline line: {e}") from e
            missing = [k for k in ("check", "path", "symbol", "key", "why")
                       if k not in obj]
            if missing:
                raise ValueError(
                    f"{path}:{i}: baseline entry missing {missing} "
                    f"(every exemption needs a 'why' justification)")
            if str(obj["why"]).strip().upper().startswith("TODO"):
                # reject the --write-baseline draft placeholder: an entry
                # is an exemption only once a human wrote its reason
                raise ValueError(
                    f"{path}:{i}: baseline entry for {obj['key']!r} still "
                    "carries the draft 'TODO' justification — replace it "
                    "with the real reason before committing")
            obj["_line"] = i  # stale-audit/prune anchor (never written)
            entries.append(obj)
    return entries


def split_baselined(findings: Sequence[Finding],
                    baseline: Sequence[Dict[str, str]]
                    ) -> Tuple[List[Finding], List[Finding]]:
    """(new findings, baselined findings). Matching is MULTISET-wise:
    each baseline entry absorbs at most one finding, so a second
    violation landing on an already-exempted fingerprint (another bare
    write in the same function, say) still fails the run instead of
    hiding behind the reviewed entry."""
    import collections

    budget = collections.Counter(
        (e["check"], e["path"], e["symbol"], e["key"]) for e in baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget[fp] > 0:
            budget[fp] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


# -- driver ------------------------------------------------------------------

def run_checks(sources: Sequence[SourceFile], config: Optional[Config] = None,
               checks: Optional[Sequence[str]] = None,
               suppressed_out: Optional[List[Finding]] = None
               ) -> List[Finding]:
    """Run the requested checkers (default: all) over the parsed sources;
    per-line `# dcg: disable=` suppressions are already applied. Pass
    `suppressed_out` to receive the findings a suppression absorbed —
    the stale-suppression audit (DCG014) needs them to tell a working
    suppression from a dead one."""
    from dcgan_tpu.analysis import donation, hygiene, parity, protocol, \
        threads

    registry = {
        "DCG001": threads.check_collectives_off_dispatch,
        "DCG002": donation.check_donation_hazard,
        "DCG003": hygiene.check_raw_shard_map,
        "DCG004": parity.check_key_inventory,
        "DCG005": hygiene.check_traced_body_hygiene,
        "DCG006": hygiene.check_bare_io,
        "DCG013": protocol.check_divergent_branch,
    }
    config = config or Config()
    if checks:
        checks = [c.upper() for c in checks]
        unknown = sorted(set(checks) - set(registry))
        if unknown:
            from dcgan_tpu.analysis.protocol import PROTOCOL_CHECKS
            from dcgan_tpu.analysis.semantic import SEMANTIC_CHECKS

            if set(unknown) <= set(SEMANTIC_CHECKS):
                raise ValueError(
                    f"{unknown} are semantic-tier check ID(s) — run "
                    "`python -m dcgan_tpu.analysis --semantic --checks "
                    + " ".join(unknown) + "`")
            if set(unknown) <= set(PROTOCOL_CHECKS):
                raise ValueError(
                    f"{unknown} are protocol-tier check ID(s) — run "
                    "`python -m dcgan_tpu.analysis --protocol`")
            raise ValueError(
                f"unknown check ID(s) {unknown}; valid: {sorted(registry)}"
                f" (AST tier) + {list(SEMANTIC_CHECKS)} (--semantic) + "
                f"{list(PROTOCOL_CHECKS)} (--protocol)")
    by_path = {sf.path: sf for sf in sources}
    findings: List[Finding] = []
    for check_id in checks or sorted(registry):
        for f in registry[check_id](list(sources), config):
            sf = by_path.get(f.path)
            if sf is not None and sf.is_suppressed(f):
                if suppressed_out is not None:
                    suppressed_out.append(f)
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.check))
    return findings


AST_CHECK_IDS = ("DCG001", "DCG002", "DCG003", "DCG004", "DCG005",
                 "DCG006", "DCG013")

STALE_SUPPRESSION_CHECK = "DCG014"
STALE_BASELINE_CHECK = "DCG015"


def audit_stale_suppressions(sources: Sequence[SourceFile],
                             suppressed: Sequence[Finding]
                             ) -> List[Finding]:
    """DCG014: `# dcg: disable=DCGxxx` comments that suppress no current
    finding are findings themselves — a dead suppression is an exemption
    with no exempted violation, and it would silently swallow the NEXT
    real finding landing on its line. Only sound after a FULL AST run
    (the drivers skip it under `--checks` subsets); IDs belonging to the
    semantic/protocol tiers can never match a line suppression (those
    findings have no source line) and are therefore always stale."""
    used = {(f.path, f.line, f.check) for f in suppressed}
    findings: List[Finding] = []
    for sf in sources:
        for line, ids in sorted(sf.suppressed.items()):
            for check_id in sorted(ids):
                if (sf.path, line, check_id) in used:
                    continue
                findings.append(Finding(
                    check=STALE_SUPPRESSION_CHECK, path=sf.path, line=line,
                    symbol="<suppression>", key=check_id,
                    message=(f"suppression `# dcg: disable={check_id}` "
                             "matches no current finding on this line — "
                             "delete it (a dead suppression would "
                             "silently swallow the next real finding "
                             "here)")))
    return findings


def audit_stale_baseline(entries: Sequence[Dict[str, str]],
                         consumed: Sequence[Finding],
                         ran_checks: Sequence[str],
                         baseline_rel_path: str
                         ) -> Tuple[List[Finding], List[Dict[str, str]]]:
    """DCG015: baseline rows whose fingerprint no longer matches any
    finding of a check that RAN this invocation. Returns (findings,
    stale entries) — `--prune-baseline` rewrites the file minus the
    latter. Rows of tiers that did not run are left alone (a per-tier
    invocation must not call another tier's exemptions dead). Stale-audit
    findings are deliberately NOT baselinable — the fix is deleting the
    row, never exempting the exemption."""
    import collections

    ran = set(ran_checks)
    budget = collections.Counter(f.fingerprint() for f in consumed)
    findings: List[Finding] = []
    stale: List[Dict[str, str]] = []
    for e in entries:
        if e["check"] not in ran:
            continue
        fp = (e["check"], e["path"], e["symbol"], e["key"])
        if budget[fp] > 0:
            budget[fp] -= 1
            continue
        stale.append(e)
        findings.append(Finding(
            check=STALE_BASELINE_CHECK, path=baseline_rel_path,
            line=int(e.get("_line", 0)), symbol=e["symbol"],
            key=f"{e['check']}:{e['key']}",
            message=(f"baseline row ({e['check']}, {e['path']}, "
                     f"{e['symbol']}, {e['key']}) matches no current "
                     "finding — the exemption is dead; delete the row "
                     "or run --prune-baseline")))
    return findings, stale


def prune_baseline_file(path: str,
                        stale: Sequence[Dict[str, str]]) -> int:
    """Rewrite the baseline minus the given stale rows (matched by their
    load-time line numbers); comment/header lines survive. Returns the
    number of rows dropped."""
    dead_lines = {int(e["_line"]) for e in stale if "_line" in e}
    if not dead_lines:
        return 0
    with open(path, encoding="utf-8") as f:
        lines = f.readlines()
    kept = [line for i, line in enumerate(lines, start=1)
            if i not in dead_lines]
    with open(path, "w", encoding="utf-8") as f:
        f.writelines(kept)
    return len(lines) - len(kept)


def default_root() -> str:
    """The repo root (parent of the dcgan_tpu package directory)."""
    import dcgan_tpu

    return os.path.dirname(os.path.dirname(
        os.path.abspath(dcgan_tpu.__file__)))


def default_baseline_path() -> str:
    return os.path.join(default_root(), "dcgan_tpu", "analysis",
                        "baseline.jsonl")
