"""DCG001: no mesh-wide collective reachable from a non-dispatch thread.

The collective-thread rule (DESIGN.md §6b): collectives issued from a
per-process background thread have no cross-process ordering against the
dispatch thread's collectives — two processes interleaving them
differently deadlock the mesh. The rule lives in docstrings and review
discipline; this checker makes it mechanical.

Roots — code that runs OFF the dispatch thread:
- every `threading.Thread(target=X)` target (positional or keyword),
- the callable handed to any `.submit(X, ...)` call (the services-worker
  task submissions in the trainer; ThreadPoolExecutor.submit matches the
  same shape, which is correct — pool tasks are off-thread too).

From each root the checker walks a best-effort call graph (bounded BFS):
bare-name calls resolve within the defining module, through `from X
import f` / `import X as alias` edges into other scanned package modules,
and `self.method` resolves within the enclosing class. Dynamic calls
(`task.fn()`, `self._hook(...)`) simply terminate the walk — the runtime
tripwire (analysis/tripwire.py, `DCGAN_THREAD_CHECKS=1`) is the dynamic
complement that catches paths the AST cannot resolve.

Sinks — the known collective entry points:
- the collective primitives and multihost transports by terminal name
  (`psum`, `all_gather`, `process_allgather`, `sync_global_devices`, ...),
- this package's collective helpers (`_allgather_*`,
  `fleet_health_gather`, `anomaly_consensus`, `warmup_barrier`),
- Checkpointer's collective methods (`restore_latest`, `maybe_save`,
  `delete_steps_after` by name; the generic `save`/`wait` only when the
  receiver names a checkpointer — `ckpt.save` yes, `img.save` no),
- compiled ParallelTrain dispatches (`pt.step`, `pt.sample`, ... — attr
  names gated on a `pt`/`pt_backoff` receiver).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from dcgan_tpu.analysis.core import (
    Config,
    Finding,
    SourceFile,
    call_name,
    dotted,
    iter_calls,
)

CHECK_ID = "DCG001"

# collective callees flagged by terminal name alone (distinctive enough
# that a bare-name match is evidence, whatever the receiver)
TERMINAL_COLLECTIVES = frozenset({
    "process_allgather", "sync_global_devices", "broadcast_one_to_all",
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all", "ppermute",
    "_allgather_i32", "_allgather_f32", "fleet_health_gather",
    "anomaly_consensus", "warmup_barrier",
    "restore_latest", "maybe_save", "delete_steps_after",
})

# generic attr names that are collective only on specific receivers:
# attr -> (match mode, hints). "segment" matches a whole receiver segment
# exactly (so `pt.step` trips but `opt.step`/`script.step` never do);
# "substr" matches inside any segment (checkpointer handles are named
# ckpt/best_ckpt/checkpointer — all carry the token).
_PT = ("segment", ("pt", "pt_backoff"))
_CKPT = ("substr", ("ckpt",))
RECEIVER_GATED = {
    "save": _CKPT, "wait": _CKPT,
    "step": _PT, "multi_step": _PT, "gen_fakes": _PT,
    "d_update": _PT, "g_update": _PT, "sample": _PT,
    "summarize": _PT, "eval_losses": _PT, "init": _PT,
}

_MAX_DEPTH = 10


def _receiver_gate(attr: str, receiver: str) -> bool:
    gate = RECEIVER_GATED.get(attr)
    if not gate:
        return False
    mode, hints = gate
    segments = receiver.split(".") if receiver else []
    if mode == "segment":
        return any(seg in hints for seg in segments)
    return any(any(h in seg for h in hints) for seg in segments)


def _is_sink(name: Optional[str], receiver: str) -> Optional[str]:
    if name is None:
        return None
    if name in TERMINAL_COLLECTIVES:
        return name
    if _receiver_gate(name, receiver):
        return f"{receiver}.{name}" if receiver else name
    return None


class _Module:
    """Per-file function/import index for call resolution."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        # module-level functions by name
        self.functions: Dict[str, ast.AST] = {}
        # class name -> {method name -> node}
        self.methods: Dict[str, Dict[str, ast.AST]] = {}
        # local alias -> imported module dotted name
        self.mod_imports: Dict[str, str] = {}
        # local name -> (module dotted name, function name)
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.methods[node.name] = {
                    n.name: n for n in node.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.mod_imports[alias.asname
                                     or alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    local = alias.asname or alias.name
                    # could be a module OR a function; record both readings
                    self.mod_imports.setdefault(
                        local, f"{node.module}.{alias.name}")
                    self.from_imports[local] = (node.module, alias.name)


class _Graph:
    def __init__(self, sources: Sequence[SourceFile]):
        self.modules: Dict[str, _Module] = {}
        for sf in sources:
            self.modules[sf.module] = _Module(sf)

    def resolve(self, mod: _Module, cls: Optional[ast.ClassDef],
                name: str, receiver: str
                ) -> Optional[Tuple[_Module, Optional[ast.ClassDef],
                                    ast.AST]]:
        """Best-effort: the (module, class-context, node) a call lands in."""
        if receiver == "self" and cls is not None:
            target = mod.methods.get(cls.name, {}).get(name)
            if target is not None:
                return mod, cls, target
            return None
        if receiver == "":
            if name in mod.functions:
                return mod, None, mod.functions[name]
            imp = mod.from_imports.get(name)
            if imp is not None:
                other = self.modules.get(imp[0])
                if other is not None and imp[1] in other.functions:
                    return other, None, other.functions[imp[1]]
            return None
        # one-level module attribute: alias.func(...)
        head = receiver.split(".")[0]
        if "." not in receiver and head in mod.mod_imports:
            other = self.modules.get(mod.mod_imports[head])
            if other is not None and name in other.functions:
                return other, None, other.functions[name]
        return None


def _roots(sf: SourceFile) -> List[Tuple[ast.AST, str, ast.AST]]:
    """(root callable expr, description, call site) for every Thread target
    and .submit() payload in the file."""
    out = []
    for call in iter_calls(sf.tree):
        name, receiver = call_name(call)
        if name == "Thread" and (receiver in ("", "threading")
                                 or receiver.endswith("threading")):
            target = next((kw.value for kw in call.keywords
                           if kw.arg == "target"), None)
            if target is None and len(call.args) >= 2:
                # Thread(group, target, ...): the positional target is the
                # SECOND slot — args[0] is `group`
                target = call.args[1]
            if target is not None:
                out.append((target, "threading.Thread target", call))
        elif name == "submit" and call.args:
            out.append((call.args[0], f"{receiver or 'executor'}.submit "
                        "task", call))
    return out


def _resolve_local(sf: SourceFile, mod: _Module, site: ast.AST,
                   name: str) -> Optional[ast.AST]:
    """A def named `name` in the lexical scope chain of `site`, falling
    back to module level — how nested worker closures are found."""
    from dcgan_tpu.analysis.core import lexical_def

    return lexical_def(sf, site, name) or mod.functions.get(name)


def check_collectives_off_dispatch(sources: Sequence[SourceFile],
                                   config: Config) -> List[Finding]:
    graph = _Graph(sources)
    findings: List[Finding] = []
    for sf in sources:
        mod = graph.modules[sf.module]
        for target, kind, site in _roots(sf):
            cls = sf.enclosing_class(site)
            start: Optional[Tuple[_Module, Optional[ast.ClassDef],
                                  ast.AST]] = None
            root_name = "<lambda>"
            if isinstance(target, ast.Lambda):
                start = (mod, cls, target)
            elif isinstance(target, ast.Name):
                root_name = target.id
                node = _resolve_local(sf, mod, site, target.id)
                if node is not None:
                    start = (mod, cls, node)
                else:
                    # direct sink handed as the callable itself
                    sink = _is_sink(target.id, "")
                    if sink:
                        findings.append(_finding(sf, site, kind, target.id,
                                                 sink, [target.id]))
                    continue
            elif isinstance(target, ast.Attribute):
                attr = target.attr
                receiver = dotted(target.value) or ""
                root_name = f"{receiver}.{attr}" if receiver else attr
                sink = _is_sink(attr, receiver)
                if sink:
                    findings.append(_finding(sf, site, kind, root_name,
                                             sink, [root_name]))
                    continue
                start = None
                resolved = graph.resolve(mod, cls, attr, receiver)
                if resolved is not None:
                    start = resolved
            if start is None:
                continue
            if _declared_dispatch_owner(start, config):
                # the target IS a dispatch thread by design (the serving
                # plane's single worker, Config.dispatch_thread_targets):
                # collectives reached from it are exactly where the rule
                # wants them — the runtime tripwire still polices it
                continue
            hit = _walk(graph, start, root_name)
            if hit is not None:
                sink, chain = hit
                findings.append(_finding(sf, site, kind, root_name, sink,
                                         chain))
    return findings


def _declared_dispatch_owner(start: Tuple[_Module, Optional[ast.ClassDef],
                                          ast.AST],
                             config: Config) -> bool:
    """Whether the resolved thread target is a declared dispatch-thread
    owner ("path::QualName" in Config.dispatch_thread_targets)."""
    targets = getattr(config, "dispatch_thread_targets", ())
    if not targets:
        return False
    mod, cls, node = start
    name = getattr(node, "name", None)
    if name is None:
        return False
    qual = f"{cls.name}.{name}" if cls is not None else name
    return f"{mod.sf.path}::{qual}" in targets


def _walk(graph: _Graph,
          start: Tuple[_Module, Optional[ast.ClassDef], ast.AST],
          root_name: str) -> Optional[Tuple[str, List[str]]]:
    """BFS from the root callable; (sink, call chain) on the first hit."""
    queue: List[Tuple[_Module, Optional[ast.ClassDef], ast.AST,
                      List[str]]] = [(*start, [root_name])]
    seen = {id(start[2])}
    depth = 0
    while queue and depth < _MAX_DEPTH:
        depth += 1
        next_queue = []
        for mod, cls, node, chain in queue:
            for call in iter_calls(node):
                name, receiver = call_name(call)
                sink = _is_sink(name, receiver)
                if sink is not None:
                    return sink, chain + [sink]
                if name is None:
                    continue
                resolved = graph.resolve(mod, cls, name, receiver)
                if resolved is None or id(resolved[2]) in seen:
                    continue
                seen.add(id(resolved[2]))
                next_queue.append((*resolved, chain + [name]))
        queue = next_queue
    return None


def _finding(sf: SourceFile, site: ast.AST, kind: str, root: str,
             sink: str, chain: List[str]) -> Finding:
    return Finding(
        check=CHECK_ID, path=sf.path, line=site.lineno,
        symbol=sf.enclosing_symbol(site),
        key=f"{root}->{sink}",
        message=(f"{kind} {root!r} reaches collective entry point "
                 f"{sink!r} (call chain: {' -> '.join(chain)}); mesh-wide "
                 "collectives must stay on the dispatch thread "
                 "(DESIGN.md §6b) — move the collective to the dispatch "
                 "thread and queue only the host-local tail"))
