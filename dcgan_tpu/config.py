"""Configuration dataclasses — the single flat knob namespace of the reference
(`image_train.py:10-38` tf.app.flags) re-expressed as typed, validated dataclasses.

Unlike the reference, model hyperparameters here are *wired*: changing
`ModelConfig.output_size`/`c_dim` or `TrainConfig.batch_size` actually changes
the built model/step (the
reference's flags of the same names were disconnected from the module constants
actually used — SURVEY.md §2.4 #8, distriubted_model.py:7-12 vs image_train.py:15-18).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import sys
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """DCGAN architecture knobs (reference: distriubted_model.py:7-12, image_train.py:42).

    The reference hard-codes output_size=64, gf_dim=df_dim=64, c_dim=3, z_dim=100.
    Here output_size may be any power of two >= 8; the G/D stacks deepen
    automatically (128x128 config from BASELINE.json uses output_size=128).
    """

    arch: str = "dcgan"            # model family: "dcgan" (the reference's
                                   # stride-2 5x5 stacks) | "resnet" (the
                                   # WGAN-GP/SNGAN residual blocks,
                                   # models/resnet.py — BN-free critic,
                                   # upsample-conv G) | "stylegan"
                                   # (StyleGAN2-lite: mapping network +
                                   # modulated convs + skip tRGB,
                                   # models/stylegan.py, paired with the
                                   # resnet critic). All scale by
                                   # base_size*2^k; dcgan/resnet compose
                                   # with conditioning/cBN/attention/SN/
                                   # pallas, stylegan with conditioning and
                                   # spectral_norm="d" (no BN to condition,
                                   # no attention site wired)
    output_size: int = 64          # spatial size of generated images (H == W)
    gf_dim: int = 64               # generator base feature maps
    df_dim: int = 64               # discriminator base feature maps
    c_dim: int = 3                 # image channels
    z_dim: int = 100               # latent dimension (image_train.py:42)
    num_classes: int = 0           # >0 activates class-conditional G/D (the
                                   # reference's dead `y` arg, distriubted_model.py:83)
    conditional_bn: bool = False   # conditional models only: the generator's
                                   # BN affine becomes per-class [K, C] tables
                                   # (SAGAN/BigGAN cBN) instead of the z-concat
                                   # conditioning alone; moments stay shared.
                                   # cBN layers always take the jnp path (the
                                   # fused Pallas kernels are per-channel)
    base_size: int = 4             # spatial size of the first feature map
    bn_momentum: float = 0.9       # EMA decay (distriubted_model.py:18,23)
    bn_eps: float = 1e-5           # (distriubted_model.py:18)
    leak: float = 0.2              # lrelu slope (distriubted_model.py:156)
    kernel_size: int = 5           # conv / deconv kernel (distriubted_model.py:176,190)
    compute_dtype: str = "bfloat16"  # MXU-native compute precision
    param_dtype: str = "float32"     # parameter / BN-stat storage precision
    use_pallas: bool = False       # Pallas kernels: flash attention when
                                   # attn_res > 0 (a measured WIN at long
                                   # sequences — DESIGN.md §8b) plus the
                                   # fused BN+act kernels (capability only:
                                   # ~20% SLOWER at flagship shapes; XLA's
                                   # fusion already sits at the HBM roof)
    bn_pallas: Optional[bool] = None  # override the BN half of use_pallas
                                   # alone (None = follow use_pallas).
                                   # Set False by the gspmd backend under a
                                   # spatial mesh, where flash attention
                                   # composes (it runs in its own
                                   # shard_map, ring x flash) but the BN
                                   # kernels' full-channel-vector contract
                                   # does not survive height sharding
    pallas_fused: bool = False     # fuse each interior G/D stage end-to-end
                                   # (conv/deconv + bias + BN + act) into the
                                   # im2col Pallas blocks of
                                   # ops/pallas_fused.py instead of the
                                   # XLA-conv + Pallas-BN split. Requires
                                   # use_pallas (it widens the same routing);
                                   # dcgan arch only, and cBN layers are
                                   # excluded (same per-channel-vector
                                   # contract as bn_pallas). Narrowed to
                                   # False by the gspmd spatial mesh with
                                   # bn_pallas (parallel/api.py)
    quant: str = ""                # "" | "fp8": simulated-quantization
                                   # (amax-scaled float8_e4m3fn round-trip)
                                   # of conv/deconv GEMM operands at stages
                                   # with feature maps >= 64px — the large
                                   # progressive phases where the MXU fp8
                                   # path would bite. Normally set via
                                   # TrainConfig.precision="fp8", not
                                   # directly
    attn_res: int = 0              # >0 inserts a SAGAN-style self-attention
                                   # block (ops/attention.py) into both stacks
                                   # at the stage whose feature maps are
                                   # attn_res x attn_res (e.g. 32 for the
                                   # SAGAN-64 recipe). Under a spatial mesh the
                                   # block executes as sequence-parallel ring
                                   # attention. 0 = off (reference parity: the
                                   # reference is pure conv)
    attn_heads: int = 1            # heads for the attention block (1 = the
                                   # SAGAN paper's single head). Apply-time
                                   # split of the same projections — param
                                   # shapes and checkpoints are head-count
                                   # independent (ops/attention.py)
    attn_seq_strategy: str = "ring"  # sequence-parallel execution under a
                                     # spatial mesh: "ring" (ppermute k/v,
                                     # any head count) | "ulysses" (two
                                     # all_to_alls; attn_heads must be
                                     # divisible by the model-axis size —
                                     # arXiv:2309.14509). Exact either way;
                                     # a pure execution knob
    spectral_norm: str = "none"    # "d": spectral-normalize every
                                   # discriminator weight (SN-GAN,
                                   # arXiv:1802.05957); "gd": both nets (the
                                   # SAGAN recipe); "none" = reference parity.
                                   # Power-iteration state is explicit, like
                                   # BN moments (ops/spectral.py)

    @property
    def bn_use_pallas(self) -> bool:
        """Whether BatchNorm runs the fused Pallas kernels — use_pallas
        unless bn_pallas overrides it (model BN call sites read this; the
        attention sites read use_pallas directly)."""
        return self.use_pallas if self.bn_pallas is None else self.bn_pallas

    def __post_init__(self):
        if self.arch not in ("dcgan", "resnet", "stylegan"):
            raise ValueError(
                f"arch must be 'dcgan', 'resnet', or 'stylegan', got "
                f"{self.arch!r}")
        if self.bn_pallas and not self.use_pallas:
            # the field only NARROWS use_pallas (the spatial-mesh fallback);
            # letting it enable the BN kernels alone would route around the
            # backend's multi-device composition guards (parallel/api.py)
            raise ValueError(
                "bn_pallas=True requires use_pallas=True (bn_pallas only "
                "narrows the flag; to run the fused BN kernels alone use "
                "use_pallas=True with attn_res=0)")
        if self.pallas_fused:
            if not self.use_pallas:
                raise ValueError(
                    "pallas_fused=True requires use_pallas=True (the fused "
                    "conv blocks ride the same Pallas routing and backend "
                    "composition guards)")
            if self.arch != "dcgan":
                raise ValueError(
                    "pallas_fused=True supports arch='dcgan' only (the "
                    "resnet/stylegan stacks have no fused block wired)")
            if self.conditional_bn:
                raise ValueError(
                    "pallas_fused=True is incompatible with conditional_bn "
                    "(per-example affines break the fused blocks' "
                    "per-channel-vector contract, same as bn_pallas)")
        if self.quant not in ("", "fp8"):
            raise ValueError(
                f"model.quant must be '' or 'fp8', got {self.quant!r}")
        if self.arch == "stylegan":
            if self.conditional_bn:
                raise ValueError(
                    "arch='stylegan' has no BatchNorm to condition "
                    "(styles carry conditioning); drop conditional_bn")
            if self.attn_res:
                raise ValueError(
                    "arch='stylegan' has no attention site wired; use "
                    "arch='dcgan'/'resnet' for attn_res")
            if self.spectral_norm == "gd":
                raise ValueError(
                    "arch='stylegan' supports spectral_norm='d' (critic "
                    "only) — SN on a style-modulated generator is not "
                    "wired")
        n = self.num_up_layers
        if n < 1 or self.base_size * (2 ** n) != self.output_size:
            raise ValueError(
                f"output_size={self.output_size} must be base_size*2^k with "
                f"k >= 1 (base_size={self.base_size})")
        if self.attn_res:
            sites = {self.base_size * (2 ** j) for j in range(n)}
            if self.attn_res not in sites:
                raise ValueError(
                    f"attn_res={self.attn_res} is not a feature-map "
                    f"resolution of this stack; choose one of {sorted(sites)}")
        if self.spectral_norm not in ("none", "d", "gd"):
            raise ValueError(
                f"spectral_norm must be 'none', 'd', or 'gd', got "
                f"{self.spectral_norm!r}")
        if self.attn_heads < 1:
            raise ValueError(
                f"attn_heads must be >= 1, got {self.attn_heads}")
        if self.attn_seq_strategy not in ("ring", "ulysses"):
            raise ValueError(
                f"attn_seq_strategy must be 'ring' or 'ulysses', got "
                f"{self.attn_seq_strategy!r}")
        if self.conditional_bn and not self.num_classes:
            raise ValueError(
                "conditional_bn requires a conditional model "
                "(num_classes > 0)")

    @property
    def num_up_layers(self) -> int:
        """Number of stride-2 deconv (G) / conv (D) stages.

        output_size 64 -> 4 stages (matching the reference's fixed 4-deconv stack,
        distriubted_model.py:93-109); 128 -> 5 stages.
        """
        return int(round(math.log2(self.output_size / self.base_size)))


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh topology. Replaces ClusterSpec/Server/ps-role entirely
    (reference: image_train.py:52-67) — there is no parameter-server process;
    parameters are replicated (or model-sharded) per the sharding rules and
    gradients all-reduce over ICI.
    """

    data: int = -1                 # data-parallel axis size; -1 = all devices
    model: int = 1                 # second mesh axis size (1 = off)
    spatial: bool = False          # repurpose the "model" axis for spatial
                                   # partitioning: activations shard over image
                                   # height (GSPMD inserts conv halo exchanges)
                                   # and weights stay replicated — the image-
                                   # domain analogue of sequence/context
                                   # parallelism (SURVEY.md §2.5). False =
                                   # tensor parallelism (wide weights shard)
    shard_opt: bool = False        # ZeRO-1: shard Adam moments over the data
                                   # axis (each replica owns 1/N and updates
                                   # its slice; reduce-scatter/all-gather
                                   # inserted by GSPMD — arXiv:2004.13336).
                                   # gspmd backend only
    zero_stage: int = 1            # state-sharding stage (arXiv:2004.13336
                                   # generalized): 1 = today's behavior
                                   # (parity; shard_opt alone still gives
                                   # ZeRO-1 on the gspmd backend). 2 =
                                   # ZeRO-2: optimizer state AND gradients
                                   # shard over the data axis — the full-
                                   # gradient psum becomes a reduce-scatter,
                                   # the Adam update runs shard-local, and
                                   # one fused all-gather rebuilds the
                                   # replicated params per update (same
                                   # bytes on the wire as the all-reduce it
                                   # replaces). 3 = ZeRO-3: params and the
                                   # EMA copy additionally stay RESIDENT
                                   # sharded between steps, all-gathered
                                   # just in time inside each forward — the
                                   # per-chip memory floor for params+grads+
                                   # Adam state drops ~Nx on an N-way data
                                   # axis. Both backends (gspmd via sharding
                                   # constraints, shard_map via explicit
                                   # psum_scatter/all_gather); stages >= 2
                                   # need a data axis of size > 1 and reject
                                   # spatial meshes (DESIGN.md §6i)

    def __post_init__(self):
        if self.zero_stage not in (1, 2, 3):
            raise ValueError(
                f"zero_stage must be 1, 2, or 3, got {self.zero_stage}")
        if self.zero_stage >= 2 and self.spatial:
            raise ValueError(
                "zero_stage >= 2 does not compose with spatial meshes "
                "(spatial mode replicates all weights by policy — there is "
                "no per-leaf dim left for the data-axis state shards); use "
                "zero_stage=1 with spatial=True")
        if self.spatial and self.model <= 1:
            raise ValueError(
                "spatial=True repurposes the 'model' mesh axis to shard image "
                f"height, which needs model > 1 (got model={self.model}); "
                "with model=1 the run would silently be plain data "
                "parallelism")

    def axis_sizes(self, n_devices: int) -> Tuple[int, int]:
        if self.model < 1:
            raise ValueError(f"model axis must be >= 1, got {self.model}")
        model = self.model
        if self.data > 0:
            data = self.data
        else:
            if n_devices % model != 0:
                raise ValueError(
                    f"model axis {model} does not divide {n_devices} devices")
            data = n_devices // model
        if data * model != n_devices:
            raise ValueError(
                f"mesh {data}x{model} does not cover {n_devices} devices")
        return data, model


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Run knobs — same set as the reference's flags (image_train.py:10-38) plus
    the defect-fix gates from SURVEY.md §2.4.
    """

    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)

    # Optimization (image_train.py:11-13,109-112)
    learning_rate: float = 2e-4
    d_learning_rate: Optional[float] = None  # TTUR: per-net learning rates
    g_learning_rate: Optional[float] = None  # (None = learning_rate; the
                                             # reference uses one lr for both)
    lr_schedule: str = "constant"  # "constant" (reference) | "linear" decay
                                   # to 0 over max_steps | "cosine" to 0
    warmup_steps: int = 0          # linear warmup from 0 before the schedule
    beta1: float = 0.5
    batch_size: int = 64           # global batch (sharded over the data axis)
    max_steps: int = 1_200_000     # (image_train.py:150)
    loss: str = "gan"              # "gan" (BCE, image_train.py:91-96) |
                                   # "wgan-gp" | "hinge" (SAGAN-style)
    gp_weight: float = 10.0        # WGAN-GP gradient-penalty coefficient
    r1_gamma: float = 0.0          # >0 adds (gamma/2)*E[||grad_x D(x)||^2]
                                   # on real images to the D loss (R1,
                                   # arXiv:1801.04406) — composes with the
                                   # "gan"/"hinge" families; 0 = off
                                   # (reference parity)
    r1_interval: int = 1           # lazy regularization (StyleGAN2,
                                   # arXiv:1912.04958 §appendix B): compute
                                   # R1 only every k-th step with gamma
                                   # scaled by k — same regularization
                                   # pressure, ~1/k of the extra D cost.
                                   # 1 = every step (the R1 paper's form)
    n_critic: int = 1              # D updates per G update. 1 = the reference's
                                   # one-D-one-G step (image_train.py:156-158);
                                   # WGAN-GP canonically uses 5 (each critic
                                   # iteration draws fresh z against the same
                                   # real batch, scanned in-program)
    update_mode: str = "sequential"  # "sequential": D step then G step (intended
                                     # semantics); "fused": both grads from the same
                                     # params, applied together (reference parity,
                                     # SURVEY.md §2.4 #2, image_train.py:156-158)
    grad_accum: int = 1            # microbatches per optimizer update (beyond
                                   # reference). K>1 scans K microbatches of
                                   # batch_size/K through each loss at fixed
                                   # params, accumulating gradients, then
                                   # applies each Adam once — the full-batch
                                   # mean gradient at ~1/K the activation
                                   # memory. BN statistics are per-microbatch
                                   # with state chained (standard large-batch
                                   # emulation semantics, not bitwise equal to
                                   # one full-batch BN pass). With n_critic>1
                                   # each scanned critic iteration applies one
                                   # Adam update from its own K-microbatch
                                   # accumulation.
    diffaug: str = ""              # differentiable augmentation policy for
                                   # every D input (DiffAugment,
                                   # arXiv:2006.10738): comma-joined subset
                                   # of {color, translation, cutout}, e.g.
                                   # "color,translation,cutout" for small
                                   # datasets. "" = off (reference parity)
    grad_clip: float = 0.0         # >0 clips both nets' gradients by global
                                   # norm before Adam (optax chain); 0 = off
                                   # (reference parity: no clipping)
    label_smoothing: float = 0.0   # one-sided label smoothing (Salimans et
                                   # al. 2016): D's real target becomes
                                   # 1 - eps ("gan" loss family only);
                                   # 0 = off (reference parity)
    g_ema_decay: float = 0.0       # >0 keeps an EMA copy of generator weights
                                   # updated per step and samples from it —
                                   # a beyond-reference FID improvement
                                   # (typical 0.999); 0 = off (strict parity:
                                   # the reference samples live weights)

    # Data (image_input.py:11-16, image_train.py:19-26)
    data_dir: str = "train"
    sample_image_dir: str = "sample_data"
    dataset: str = "celebA"
    shuffle_buffer: int = 10_776   # 10% of epoch (image_input.py:134-136)
    num_loader_threads: int = 16   # (image_input.py:77)
    normalize_inputs: bool = True  # map reals to [-1,1]; the reference never does
                                   # (SURVEY.md §2.4 #1) — set False for strict parity
    record_dtype: str = "float64"  # on-disk pixel dtype (image_input.py:48)
    label_feature: str = "label"   # int64 per-example class feature, read when
                                   # model.num_classes > 0 (the schema the
                                   # reference comments out, image_input.py:44)
    prefetch_device_batches: int = 2  # depth of the background device-feed
                                   # queue (data/pipeline.DevicePrefetcher):
                                   # a transfer thread keeps this many
                                   # already-sharded device batches ready
                                   # ahead of the dispatch thread, so batch
                                   # assembly + H2D transfer overlap device
                                   # compute. 0 = legacy consumer-thread
                                   # double buffer (feed alternates with
                                   # dispatch)
    synthetic_device_cache: int = 0  # >0 (synthetic data only): pre-stage
                                   # this many sharded batches ON DEVICE and
                                   # cycle them — removes host->device feed
                                   # from the loop so the trainer's own hot-
                                   # loop machinery can be measured at chip
                                   # rate over transports that cannot sustain
                                   # the feed (tools/bench_trainer_loop.py)
    synthetic_global_stream: bool = False  # with synthetic data: every
                                   # process generates the FULL global batch
                                   # from one seed and cuts its own block, so
                                   # the global batch sequence is IDENTICAL
                                   # for every process layout over the same
                                   # mesh (2 proc x 1 dev == 1 proc x 2 dev,
                                   # bit-for-bit). The layout-invariance the
                                   # elastic shrink/grow drills replay losses
                                   # across (tools/chaos_drill.py); default
                                   # off — the block-seeded stream pays 1/P
                                   # of the host cost and stays byte-exact
                                   # with prior builds

    # Observability (image_train.py:37,129,179)
    async_services: bool = True    # run host-side observability (deferred
                                   # metric materialization, param/activation
                                   # histogram capture, sample-grid PNG
                                   # encode, JSONL/TB writes) on a background
                                   # single-worker executor with drop-oldest
                                   # backpressure (train/services.py), and
                                   # log step N's scalars while step N+1 runs
                                   # (lag-by-one). False = every service runs
                                   # inline on the dispatch thread at its
                                   # original call site — the pre-async loop
                                   # structure; the metrics JSONL matches the
                                   # pre-async trainer's up to the two new
                                   # perf/host_ms_mean + perf/
                                   # dispatch_occupancy timing keys (emitted
                                   # in both modes)
    checkpoint_dir: str = "checkpoint"
    sample_dir: str = "samples"
    tensorboard: bool = True       # mirror metrics into TensorBoard-native
                                   # event files (utils/tb_events.py) next to
                                   # the JSONL stream — the reference's
                                   # summary-file channel (image_train.py:118)
    save_summaries_secs: float = 10.0
    save_model_secs: float = 600.0   # single-process checkpoint cadence
    save_model_steps: int = 1000     # multi-host cadence (collective save
                                     # needs a clock-independent trigger)
    max_checkpoints: int = 5         # retained checkpoints (Orbax
                                     # max_to_keep; the reference's Saver
                                     # default was also 5)
    sample_every_steps: int = 100
    sample_grid: Tuple[int, int] = (8, 8)   # 8x8 grid (image_train.py:205)
    fid_every_steps: int = 0       # >0: periodic in-training surrogate
                                   # FID/KID probe (evals/ rig) against the
                                   # held-out sample pipeline — written as
                                   # eval/fid + eval/kid scalars. Single-process runs
                                   # only (multi-host scores offline via
                                   # `evals --multihost`); 0 = off
                                   # (reference parity: its only eval was
                                   # the human eyeballing grids)
    fid_num_samples: int = 2048    # samples per side for the probe (small
                                   # by design: KID is unbiased at small n,
                                   # and the probe's job is trend, not the
                                   # FID-50k headline)
    log_every_steps: int = 1
    nan_check_steps: int = 100     # every N steps all processes verify the
                                   # loss metrics are finite and abort with
                                   # step context if not (0 = off) — the
                                   # numerical-health hook SURVEY.md §5 names
                                   # as this design's sanitizer equivalent
    nan_policy: str = "abort"      # what a tripped NaN gate does: "abort"
                                   # (reference parity: raise with step
                                   # context) | "rollback" (fail-operational:
                                   # restore the last-good snapshot, skip
                                   # the offending batch window, keep
                                   # training — train/rollback.py. Multi-
                                   # host: gate verdicts are allgathered so
                                   # every process takes the same branch,
                                   # and the snapshot is a sharded device-
                                   # resident copy restored collectively —
                                   # train/coordination.py)
    coord_stop: bool = True        # multi-host: SIGTERM/SIGINT on ANY host
                                   # sets a local flag that is allgathered
                                   # at each step boundary, so the whole
                                   # job breaks together and runs the
                                   # collective final save (a preemption
                                   # notice becomes a resumable stop). One
                                   # tiny int32 allgather per step boundary
                                   # is the cost. False restores PR 3
                                   # semantics: default signal handling,
                                   # restart from the last periodic save.
                                   # Single-process stop handling is always
                                   # on and collective-free either way
    collective_timeout_secs: float = 0.0  # >0 arms the hung-collective
                                   # watchdog (train/coordination.py): a
                                   # daemon thread deadlines each dispatch/
                                   # save/consensus section and, on expiry,
                                   # dumps per-process stacks and exits
                                   # nonzero (43) so the launcher restarts
                                   # the job instead of hanging forever.
                                   # Set comfortably above the slowest
                                   # legitimate section (collective save
                                   # included; the first step's compile is
                                   # exempted). 0 = off
    rollback_snapshot_steps: int = 100  # nan_policy="rollback": keep a host-
                                   # side copy of the last gate-verified
                                   # state every K steps (the restore point;
                                   # one device_get of the full state per K
                                   # steps)
    max_rollbacks: int = 3         # rollbacks allowed per run before the
                                   # gate aborts anyway — persistent
                                   # divergence must still fail loudly, not
                                   # loop forever
    rollback_lr_backoff: float = 1.0  # <1.0: multiply both nets' base
                                   # learning rates by this on every
                                   # rollback (rebuilds the compiled step —
                                   # a recompile per rollback, acceptable
                                   # for a rare recovery event); 1.0 = off
    max_corrupt_records: int = 0   # >0: data-pipeline CRC/parse failures
                                   # quarantine the record (skip + log
                                   # file/offset + data/corrupt_records
                                   # counter) up to this many before hard-
                                   # failing; 0 = first corrupt record is
                                   # fatal (reference parity)
    activation_summary_steps: int = 500  # per-layer activation histogram +
                                         # sparsity cadence (0 = off). Step-
                                         # gated, not time-gated: the summary
                                         # program is a mesh collective, so
                                         # every process must agree on when it
                                         # runs (a per-process clock gate would
                                         # deadlock multi-host)

    # Warm start (DESIGN.md §6d): restart goodput — PRs 3-4 made restarts
    # the normal response to faults, so time-to-first-step is throughput
    # infrastructure, not a one-off cost
    compile_cache_dir: str = ""    # non-empty wires JAX's persistent
                                   # compilation cache at this directory
                                   # (DCGAN_COMPILE_CACHE_DIR env honored
                                   # when unset): a restart deserializes
                                   # every already-seen program instead of
                                   # recompiling it. Multi-host safe by
                                   # construction — JAX writes entries from
                                   # the chief only, every process reads.
                                   # Cache adoption is surfaced as
                                   # perf/compile_cache_* counters. "" = off
                                   # (reference parity)
    compile_cache_per_process: bool = False  # multi-host without a shared
                                   # filesystem: give each process its own
                                   # proc<i>/ subdirectory of
                                   # compile_cache_dir instead of the
                                   # chief-writes/all-read shared store
    aot_warmup: bool = False       # explicit AOT warmup phase before the
                                   # loop: .lower().compile() every program
                                   # and every known future call shape (the
                                   # k=1 n_critic tail, the steps_per_call
                                   # scan, sampler/probe/summarize, the
                                   # rollback LR-backoff rebuild variant)
                                   # with per-program perf/compile_ms
                                   # timings; with compile_cache_dir set the
                                   # loop's first dispatches deserialize
                                   # instead of compiling, and the hung-
                                   # collective watchdog arms from warmup
                                   # proof instead of waiting for first
                                   # live steps. False = compile lazily on
                                   # first dispatch (reference parity)

    # Profiling (SURVEY.md §5 — the reference has none; jax.profiler + step
    # timing is the named TPU-native equivalent)
    profile_dir: str = ""          # non-empty enables the scheduled trace
                                   # capture window
    profile_start_step: int = 10   # skip compile + warmup steps
    profile_num_steps: int = 5
    profile_trigger: str = ""      # non-empty: on-demand tracing (ISSUE 6)
                                   # — touch this file mid-run to capture
                                   # the next profile_num_steps steps, no
                                   # restart needed; the file is deleted as
                                   # the ack (touch again for another
                                   # capture). Each capture is digested
                                   # in-process on the services worker into
                                   # perf/device/* events (compute ms,
                                   # collective ms, idle-gap ms, devstep).
                                   # Traces land in profile_dir, or
                                   # checkpoint_dir/trace when unset
    timing_window: int = 50        # sliding window for step-time stats
    flight_recorder_steps: int = 64  # crash flight recorder (ISSUE 6):
                                   # ring of the last K per-step telemetry
                                   # records (step/host ms, losses, services
                                   # queue + drops, gate verdicts, recovery
                                   # counters), dumped as a standalone
                                   # JSONL file on watchdog trip, NaN
                                   # abort, coordinated stop, or uncaught
                                   # exception. Crash-path-only IO — the
                                   # default event stream is untouched.
                                   # 0 = off
    fleet_health_steps: int = 0    # >0: every N steps allgather a compact
                                   # per-host health vector on the dispatch
                                   # thread (collective-thread rule) and
                                   # chief-materialize fleet/* metrics —
                                   # straggler skew (max/min step_ms),
                                   # slowest host, queue/drop/recovery
                                   # totals; the slowest host is also named
                                   # in a watchdog trip header. One small
                                   # collective per N steps. 0 = off
                                   # (parity)

    # Misc
    seed: int = 0
    sample_size: int = 64          # fixed-z sample batch (image_train.py:43)
    steps_per_call: int = 1        # >1: dispatch K steps as one compiled
                                   # lax.scan program (ParallelTrain.
                                   # multi_step) — sheds per-dispatch RPC
                                   # overhead (~7ms over a tunneled
                                   # transport). Observability cadences
                                   # must be 0 or multiples of K; per-step
                                   # stdout logging (the reference's
                                   # every-step line) only reports each
                                   # call's last step
    progressive: str = ""          # progressive-resolution schedule
                                   # (ISSUE 15, ROADMAP item 5): a phase
                                   # table "RES:STEPS[,...],RES:*" — e.g.
                                   # "64:2000,128:2000,256:*" — making
                                   # resolution a scheduled training
                                   # dimension. Resolutions must be
                                   # ascending model-stack sites ending at
                                   # model.output_size (the base config
                                   # describes the FINAL model); the last
                                   # phase's '*' runs to max_steps. A
                                   # third ":BATCH" field per phase
                                   # shrinks the batch at high res. Phase
                                   # switches are zero-recompile after
                                   # --aot_warmup (every phase's programs
                                   # are pre-lowered AND primed at
                                   # startup), carry state across the
                                   # model-surface growth (new-at-phase
                                   # leaves init fresh, carried leaves
                                   # transfer), re-open the data pipeline
                                   # at the new decode resolution, and
                                   # persist a phase tag in the elastic
                                   # sidecar so restores resume into the
                                   # right phase. "" = off (parity)
    progressive_fade_steps: int = 0  # >0 with --progressive: a linear
                                   # fade-in over the first N steps of
                                   # each phase after the first — real
                                   # images blend alpha*x +
                                   # (1-alpha)*up(down(x)) through a tiny
                                   # jitted program (alpha is a traced
                                   # f32 scalar; one compile per phase),
                                   # ramping D's real distribution from
                                   # previous-resolution content to full
                                   # detail. 0 = hard switches
    elastic_target_devices: int = 0  # live in-run elasticity (ISSUE 18):
                                   # >0 arms a second pre-built topology
                                   # surface over the first N devices (N
                                   # divisible by mesh.model) and the
                                   # preemption-notice boundary poll. A
                                   # shrink notice (SIGUSR1, the notice
                                   # file, or a chaos plan) moves the LIVE
                                   # state onto the smaller mesh without a
                                   # restart — drain, reshard, resume from
                                   # pre-warmed executables (compile-
                                   # request delta 0 under --aot_warmup);
                                   # a grow notice moves back. Global
                                   # batch and model are unchanged (the
                                   # math is layout-invariant). Single-
                                   # controller runs only. 0 = off
                                   # (parity: no poll, no extra surface)
    elastic_notice_file: str = ""  # with elastic_target_devices: a file
                                   # path polled (retry_io-guarded) at
                                   # each step boundary — `touch <file>`
                                   # is a shrink notice, content "grow"
                                   # the grow-back; consumed notices are
                                   # renamed *.consumed and acked to
                                   # *.ack with the switch record. "" =
                                   # signal/chaos sources only
    pipeline_gd: bool = False      # software-pipelined G/D dispatch
                                   # (ISSUE 7, ParaGAN's separable-stage
                                   # framing): the fused train step is
                                   # dispatched as three stage programs —
                                   # gen_fakes (fill), d_update (consumes
                                   # the fake stack produced during the
                                   # PREVIOUS step, staleness 1), g_update
                                   # (returns the next stack). Per-step
                                   # FLOPs are conservation-equal to the
                                   # fused program (every consumed fake is
                                   # produced once; XLA already CSEs the
                                   # fused step's shared-z G forward) —
                                   # the wins are the largest program's
                                   # peak temp memory (~15% below fused at
                                   # the flagship config: batch headroom)
                                   # and the stage separation itself (the
                                   # substrate for cross-stage placement/
                                   # overlap, DESIGN.md §6f). The stack is
                                   # double-buffered on device and lives
                                   # OUTSIDE the checkpoint pytree (both
                                   # modes save/restore the identical
                                   # state tree); fill/drain at run start,
                                   # checkpoint boundaries, rollback, and
                                   # coordinated stop. Sequential
                                   # update_mode + unconditional models +
                                   # steps_per_call=1 only. False = the
                                   # fused step (reference parity)
    precision: str = ""            # reduced-precision ladder (ISSUE 17,
                                   # ROADMAP item 3). "" = leave the model's
                                   # compute_dtype/param_dtype alone (parity
                                   # with every prior build). "f32": force
                                   # float32 compute+params (the A/B
                                   # reference arm). "bf16": bfloat16 params
                                   # AND compute end-to-end, with f32 master
                                   # Adam first moments (make_optimizer sets
                                   # mu_dtype=float32; nu is a variance —
                                   # bf16's ~3 significant digits suffice —
                                   # and BN running stats follow param dtype
                                   # through batch_norm_init while the
                                   # moment REDUCTIONS are always f32).
                                   # "fp8": the bf16 policy plus simulated
                                   # fp8 quantization of conv GEMM operands
                                   # at >=64px stages (model.quant="fp8" —
                                   # the large progressive phases). The
                                   # policy is applied by normalizing
                                   # model.{compute,param}_dtype/quant in
                                   # __post_init__, so every downstream
                                   # consumer (init, steps, serve, analysis)
                                   # sees ordinary model dtypes
    backend: str = "gspmd"         # "gspmd": jit + sharding annotations, the
                                   # partitioner inserts collectives
                                   # (parallel/api.py) | "shard_map": explicit
                                   # per-device programs with hand-written
                                   # psum/pmean (parallel/shard_map_backend.py;
                                   # DP-only, composes with use_pallas)
    comm_overlap: str = "off"      # collective overlap plane (ISSUE 20,
                                   # DESIGN §6n). "off": the per-leaf ZeRO
                                   # collectives, byte-identical to every
                                   # prior build (parity-pinned). "bucket":
                                   # reduce_grads/gather_updates pack leaves
                                   # into dtype-grouped flat buffers — one
                                   # large collective per bucket instead of
                                   # one per leaf, bit-exact by construction.
                                   # "prefetch" (zero_stage=3 only): bucket's
                                   # plan PLUS gather_params restructured
                                   # into layer-ahead staged gathers so XLA
                                   # overlaps layer i+1's gather with layer
                                   # i's compute
    comm_bucket_mb: int = 4        # bucket size cap in MiB for
                                   # comm_overlap != "off" (per dtype group;
                                   # a single leaf above the cap gets its
                                   # own bucket)

    def __post_init__(self):
        if self.precision not in ("", "f32", "bf16", "fp8"):
            raise ValueError(
                f"precision must be one of '', 'f32', 'bf16', 'fp8', got "
                f"{self.precision!r}")
        if self.precision:
            # Normalize the policy into the model dtypes up front (frozen
            # dataclass: object.__setattr__ is the sanctioned escape hatch,
            # and the rewrite is idempotent so config round-trips through
            # config_from_dict reproduce the same model). precision OVERRIDES
            # any explicit model dtype flags — one knob, one meaning.
            _POLICY = {
                "f32": ("float32", "float32", ""),
                "bf16": ("bfloat16", "bfloat16", ""),
                "fp8": ("bfloat16", "bfloat16", "fp8"),
            }
            cdt, pdt, quant = _POLICY[self.precision]
            if (self.model.compute_dtype, self.model.param_dtype,
                    self.model.quant) != (cdt, pdt, quant):
                object.__setattr__(
                    self, "model",
                    dataclasses.replace(self.model, compute_dtype=cdt,
                                        param_dtype=pdt, quant=quant))
        elif self.model.quant:
            raise ValueError(
                "model.quant is set by the precision policy — use "
                "precision='fp8' rather than setting it directly")
        if self.backend not in ("gspmd", "shard_map"):
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.backend == "shard_map" and (self.mesh.model != 1
                                            or self.mesh.spatial
                                            or self.mesh.shard_opt):
            raise ValueError(
                "backend='shard_map' is data-parallel only (mesh.model must "
                "be 1, spatial/shard_opt False — tensor/spatial/ZeRO-1 "
                f"optimizer-state sharding live in the gspmd backend; "
                f"ZeRO-2/3 is mesh.zero_stage, supported here); got "
                f"mesh={self.mesh}")
        if self.backend == "shard_map" and self.mesh.zero_stage >= 2 \
                and self.grad_clip > 0:
            raise ValueError(
                "zero_stage >= 2 under backend='shard_map' does not compose "
                "with grad_clip: the clip's global norm would be computed "
                "over each replica's gradient SHARD (the explicit reduce-"
                "scatter hands optax local slices) — use the gspmd backend, "
                "where the partitioner computes the true global norm")
        if self.comm_overlap not in ("off", "bucket", "prefetch"):
            raise ValueError(
                f"comm_overlap must be one of 'off', 'bucket', 'prefetch', "
                f"got {self.comm_overlap!r}")
        if self.comm_overlap == "prefetch" and self.mesh.zero_stage != 3:
            raise ValueError(
                "comm_overlap='prefetch' restructures the ZeRO-3 "
                "just-in-time param gathers — it requires "
                f"mesh.zero_stage=3 (got {self.mesh.zero_stage}); use "
                "comm_overlap='bucket' at lower stages")
        if self.comm_bucket_mb <= 0:
            raise ValueError(
                f"comm_bucket_mb must be > 0, got {self.comm_bucket_mb}")
        if self.loss not in ("gan", "wgan-gp", "hinge"):
            raise ValueError(f"unknown loss {self.loss!r}")
        if self.update_mode not in ("sequential", "fused"):
            raise ValueError(f"unknown update_mode {self.update_mode!r}")
        if self.n_critic < 1:
            raise ValueError(f"n_critic must be >= 1, got {self.n_critic}")
        if self.r1_gamma < 0:
            raise ValueError(f"r1_gamma must be >= 0, got {self.r1_gamma}")
        if self.r1_gamma and self.loss == "wgan-gp":
            raise ValueError(
                "r1_gamma composes with the 'gan'/'hinge' families; "
                "'wgan-gp' already carries its own gradient penalty")
        if self.r1_interval < 1:
            raise ValueError(
                f"r1_interval must be >= 1, got {self.r1_interval}")
        if self.r1_interval > 1 and not self.r1_gamma:
            raise ValueError(
                "r1_interval > 1 without r1_gamma is a silent no-op — set "
                "r1_gamma > 0 to enable R1")
        if self.grad_clip < 0:
            raise ValueError(f"grad_clip must be >= 0, got {self.grad_clip}")
        from dcgan_tpu.ops.augment import parse_policy
        parse_policy(self.diffaug)  # raises on unknown policy names
        if not 0.0 <= self.label_smoothing < 0.5:
            raise ValueError(
                f"label_smoothing must be in [0, 0.5), got "
                f"{self.label_smoothing}")
        if self.label_smoothing and self.loss != "gan":
            raise ValueError(
                "label_smoothing targets BCE labels and applies only to "
                f"loss='gan', got loss={self.loss!r}")
        if not 0.0 <= self.g_ema_decay < 1.0:
            raise ValueError(
                f"g_ema_decay must be in [0, 1), got {self.g_ema_decay}")
        if self.fid_every_steps < 0:
            raise ValueError(
                f"fid_every_steps must be >= 0, got {self.fid_every_steps}")
        if self.fid_every_steps and self.fid_num_samples < 64:
            raise ValueError(
                f"fid_num_samples must be >= 64 for a meaningful probe, "
                f"got {self.fid_num_samples}")
        if self.lr_schedule not in ("constant", "linear", "cosine"):
            raise ValueError(f"unknown lr_schedule {self.lr_schedule!r}")
        if self.warmup_steps < 0:
            raise ValueError(f"warmup_steps must be >= 0, got "
                             f"{self.warmup_steps}")
        if self.warmup_steps >= self.max_steps:
            raise ValueError(
                f"warmup_steps ({self.warmup_steps}) must be < max_steps "
                f"({self.max_steps}) — the whole run would be warmup and the "
                "decay schedule would never engage")
        if self.nan_policy not in ("abort", "rollback"):
            raise ValueError(
                f"nan_policy must be 'abort' or 'rollback', got "
                f"{self.nan_policy!r}")
        if self.nan_policy == "rollback" and not self.nan_check_steps:
            raise ValueError(
                "nan_policy='rollback' needs the NaN gate enabled "
                "(nan_check_steps > 0) — with the gate off nothing ever "
                "trips, so the snapshot cost buys no protection")
        if self.rollback_snapshot_steps < 1:
            raise ValueError(
                f"rollback_snapshot_steps must be >= 1, got "
                f"{self.rollback_snapshot_steps}")
        if self.max_rollbacks < 1:
            raise ValueError(
                f"max_rollbacks must be >= 1, got {self.max_rollbacks}")
        if not 0.0 < self.rollback_lr_backoff <= 1.0:
            raise ValueError(
                f"rollback_lr_backoff must be in (0, 1], got "
                f"{self.rollback_lr_backoff}")
        if self.collective_timeout_secs < 0:
            raise ValueError(
                f"collective_timeout_secs must be >= 0, got "
                f"{self.collective_timeout_secs}")
        if self.max_corrupt_records < 0:
            raise ValueError(
                f"max_corrupt_records must be >= 0, got "
                f"{self.max_corrupt_records}")
        if self.flight_recorder_steps < 0:
            raise ValueError(
                f"flight_recorder_steps must be >= 0, got "
                f"{self.flight_recorder_steps}")
        if self.fleet_health_steps < 0:
            raise ValueError(
                f"fleet_health_steps must be >= 0, got "
                f"{self.fleet_health_steps}")
        if self.steps_per_call < 1:
            raise ValueError(
                f"steps_per_call must be >= 1, got {self.steps_per_call}")
        if self.steps_per_call > 1:
            cadences = {
                "log_every_steps": self.log_every_steps,
                "sample_every_steps": self.sample_every_steps,
                "activation_summary_steps": self.activation_summary_steps,
                "nan_check_steps": self.nan_check_steps,
                "save_model_steps": self.save_model_steps,
                "fid_every_steps": self.fid_every_steps,
                # the health gather is a per-cadence COLLECTIVE — a skewed
                # firing subset would deadlock multi-host, same as the
                # activation-summary reasoning
                "fleet_health_steps": self.fleet_health_steps,
            }
            if self.nan_policy == "rollback":
                # the snapshot cadence is inert under the default policy —
                # its (default 100) value must not constrain steps_per_call
                # for runs that never arm rollback
                cadences["rollback_snapshot_steps"] = \
                    self.rollback_snapshot_steps
            # A cadence that is a multiple of K fires exactly on schedule; a
            # cadence that divides K fires at every call boundary (e.g. the
            # default per-step log becomes one line per call, reporting the
            # call's last step). Anything else would fire on a skewed subset
            # of its steps — reject that.
            spc = self.steps_per_call
            bad = {k: v for k, v in cadences.items()
                   if v and v % spc != 0 and spc % v != 0}
            if bad:
                raise ValueError(
                    f"with steps_per_call={spc} every step cadence must be "
                    "0, a multiple of it (fires on schedule), or a divisor "
                    "of it (fires each call boundary); offending: "
                    f"{bad}")
        if self.n_critic > 1 and self.update_mode == "fused":
            raise ValueError(
                "update_mode='fused' (reference-parity single fused step) is "
                "defined only for n_critic=1")
        if self.pipeline_gd:
            if self.update_mode != "sequential":
                raise ValueError(
                    "pipeline_gd dispatches g_update AFTER d_update "
                    "(sequential semantics by construction); "
                    "update_mode='fused' has no pipelined equivalent")
            if self.model.num_classes:
                raise ValueError(
                    "pipeline_gd supports unconditional models only — the "
                    "stage programs do not thread class labels through the "
                    "fake stack")
            if self.steps_per_call != 1:
                raise ValueError(
                    f"pipeline_gd dispatches per-step stage programs; it "
                    f"does not compose with the scanned multi-step path "
                    f"(steps_per_call={self.steps_per_call} — set it to 1)")
        if self.progressive_fade_steps < 0:
            raise ValueError(
                f"progressive_fade_steps must be >= 0, got "
                f"{self.progressive_fade_steps}")
        if self.progressive_fade_steps and not self.progressive:
            raise ValueError(
                "progressive_fade_steps > 0 without --progressive is a "
                "silent no-op — set a --progressive schedule to fade into")
        if self.progressive:
            if self.model.attn_res:
                raise ValueError(
                    "--progressive does not compose with attn_res: the "
                    "attention site is anchored to one feature-map "
                    "resolution, which earlier phases may not contain "
                    "(and carrying attention projections across a stage "
                    "shift is undefined)")
            if self.fid_every_steps:
                raise ValueError(
                    "--progressive does not compose with fid_every_steps: "
                    "the probe's feature extractor and real-side "
                    "statistics are fixed-resolution; score offline per "
                    "phase via the evals CLI instead")
            if self.nan_policy == "rollback" \
                    and self.rollback_lr_backoff < 1.0:
                raise ValueError(
                    "--progressive does not compose with "
                    "rollback_lr_backoff < 1.0: the pre-warmed backoff "
                    "surface is per-phase and a mid-schedule rebuild "
                    "would recompile under the zero-recompile contract; "
                    "use rollback without LR backoff")
            # parse (and thereby validate) the schedule at construction —
            # the trainer re-parses against the live mesh for granule
            # checks; lazy import mirrors the parse_policy pattern above
            from dcgan_tpu.progressive.schedule import parse_schedule
            parse_schedule(self.progressive, model=self.model,
                           batch_size=self.batch_size,
                           max_steps=self.max_steps,
                           steps_per_call=self.steps_per_call,
                           grad_accum=self.grad_accum,
                           fade_steps=self.progressive_fade_steps)
        if self.elastic_target_devices < 0:
            raise ValueError(
                f"elastic_target_devices must be >= 0, got "
                f"{self.elastic_target_devices}")
        if self.elastic_target_devices:
            if self.progressive:
                raise ValueError(
                    "--elastic_target_devices does not compose with "
                    "--progressive: both own the phase-boundary switch "
                    "sequence and the warmed-surface table, and a notice "
                    "landing mid-schedule would have to re-warm every "
                    "remaining phase on the new mesh under the "
                    "zero-recompile contract; run fixed-resolution, or "
                    "take the restart-based elastic path between phases")
            if self.mesh.model > 0 \
                    and self.elastic_target_devices % self.mesh.model:
                raise ValueError(
                    f"elastic_target_devices="
                    f"{self.elastic_target_devices} must be divisible by "
                    f"the model axis (mesh.model={self.mesh.model}) — the "
                    "live switch resizes the data axis only")
        if self.elastic_notice_file and not self.elastic_target_devices:
            raise ValueError(
                "--elastic_notice_file without --elastic_target_devices "
                "is a silent no-op — arm a target topology to switch to")
        if self.prefetch_device_batches < 0:
            raise ValueError(
                f"prefetch_device_batches must be >= 0, got "
                f"{self.prefetch_device_batches}")
        if self.grad_accum < 1:
            raise ValueError(
                f"grad_accum must be >= 1, got {self.grad_accum}")
        if self.batch_size % self.grad_accum:
            raise ValueError(
                f"batch_size ({self.batch_size}) must be a multiple of "
                f"grad_accum ({self.grad_accum}) — microbatches are "
                "batch_size/grad_accum")


# --------------------------------------------------------------------------
# Checkpoint-side config persistence (VERDICT r1 #3).
#
# The reference's Saver stored only variables; restoring required the user to
# re-specify every architecture flag, and a mismatch surfaced as an opaque
# restore error (image_train.py:233-245 had the same hazard). Here the
# trainer writes the full TrainConfig as `config.json` next to the Orbax step
# dirs, and generate/evals/resume read it back — so
# `python -m dcgan_tpu.generate --checkpoint_dir ckpt` needs zero
# architecture flags, and a resume with mismatched architecture fails with a
# clear message instead of an Orbax shape error.
# --------------------------------------------------------------------------

CONFIG_FILENAME = "config.json"


def config_to_dict(cfg: TrainConfig) -> Dict[str, Any]:
    return dataclasses.asdict(cfg)


def _known_fields(cls, d: Dict[str, Any], *, context: str) -> Dict[str, Any]:
    """Filter a dict to cls's fields; warn (don't fail) on unknown keys so a
    checkpoint written by a NEWER framework version still loads."""
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = sorted(set(d) - names)
    if unknown:
        print(f"[dcgan_tpu] ignoring unknown {context} config keys "
              f"{unknown} (checkpoint written by a newer version?)",
              file=sys.stderr)
    return {k: v for k, v in d.items() if k in names}


def config_from_dict(d: Dict[str, Any]) -> TrainConfig:
    d = dict(d)
    model = ModelConfig(**_known_fields(ModelConfig, dict(d.pop("model", {})),
                                        context="model"))
    mesh = MeshConfig(**_known_fields(MeshConfig, dict(d.pop("mesh", {})),
                                      context="mesh"))
    rest = _known_fields(TrainConfig, d, context="train")
    if "sample_grid" in rest:  # JSON round-trips tuples as lists
        rest["sample_grid"] = tuple(rest["sample_grid"])
    return TrainConfig(model=model, mesh=mesh, **rest)


def save_config(cfg: TrainConfig, directory: str) -> str:
    """Write config.json atomically (tmp + rename); returns the path."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, CONFIG_FILENAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(config_to_dict(cfg), f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_config(directory: str) -> Optional[TrainConfig]:
    """The TrainConfig stored next to a checkpoint, or None if absent."""
    path = os.path.join(directory, CONFIG_FILENAME)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return config_from_dict(json.load(f))


# The ModelConfig knobs checkpoint consumers (generate/evals/export CLIs)
# expose as override flags — one list so the parsers cannot drift apart.
MODEL_OVERRIDE_FLAGS = ("arch", "output_size", "c_dim", "z_dim", "gf_dim",
                        "df_dim", "num_classes", "conditional_bn",
                        "attn_res", "attn_heads", "spectral_norm")


def add_model_override_flags(p) -> None:
    """Install the MODEL_OVERRIDE_FLAGS architecture flags on an argparse
    parser — the one shared definition for every checkpoint-consumer CLI
    (generate/evals/export; the trainer's parser wires these knobs with
    live defaults instead of the None='not passed' convention used here).
    Defaults are None so "explicitly passed" is distinguishable from
    "omitted"; precedence is explicit flag > --preset > checkpoint
    config.json > ModelConfig defaults (resolve_model_config).
    """
    import argparse

    p.add_argument("--arch", choices=["dcgan", "resnet", "stylegan"],
                   default=None,
                   help="match the checkpoint's model family")
    p.add_argument("--output_size", type=int, default=None)
    p.add_argument("--c_dim", type=int, default=None)
    p.add_argument("--z_dim", type=int, default=None)
    p.add_argument("--gf_dim", type=int, default=None)
    p.add_argument("--df_dim", type=int, default=None)
    p.add_argument("--num_classes", type=int, default=None)
    p.add_argument("--attn_res", type=int, default=None,
                   help="match the checkpoint's attention config "
                        "(presets supply it; explicit flag overrides)")
    p.add_argument("--attn_heads", type=int, default=None,
                   help="match the checkpoint's attention head count")
    p.add_argument("--spectral_norm", choices=["none", "d", "gd"],
                   default=None,
                   help="match the checkpoint's spectral-norm config")
    p.add_argument("--conditional_bn", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="match the checkpoint's conditional-BN config "
                        "([K, C] per-class BN tables in G)")


def _progressive_checkpoint_resolution(checkpoint_dir: str) -> Optional[int]:
    """The resolution tag of the NEWEST checkpoint sidecar carrying a
    progressive phase tag (ISSUE 15), or None. A run stopped mid-schedule
    saved a SHALLOWER tree than the config.json's final architecture —
    checkpoint consumers must build their restore template at the saved
    phase's resolution, not the schedule's end state."""
    import glob
    import re

    best: Optional[Tuple[int, int]] = None  # (step, resolution)
    for path in glob.glob(os.path.join(checkpoint_dir, "integrity",
                                       "*.sharding.json")):
        m = re.match(r"(\d+)\.sharding\.json$", os.path.basename(path))
        if m is None:
            continue
        try:
            with open(path, encoding="utf-8") as f:
                tag = json.load(f).get("progressive")
            res = int(tag["resolution"])
        except (OSError, ValueError, KeyError, TypeError):
            continue
        step = int(m.group(1))
        if best is None or step > best[0]:
            best = (step, res)
    return None if best is None else best[1]


def resolve_model_config(checkpoint_dir: str, *, preset: Optional[str] = None,
                         overrides: Optional[Dict[str, Any]] = None
                         ) -> ModelConfig:
    """Architecture resolution for checkpoint consumers (generate/evals).

    Precedence: explicit flag overrides > --preset > the checkpoint's own
    config.json > ModelConfig defaults. `overrides` values of None mean
    "not passed" and are dropped.

    Progressive checkpoints (ISSUE 15): the config.json describes the
    schedule's FINAL model, but a mid-schedule checkpoint holds an earlier
    phase's shallower tree — the sidecar's phase tag names which, and the
    resolved output_size adopts it (an explicit --output_size flag still
    wins), so `generate --checkpoint_dir` works zero-flag at any point of
    the schedule instead of failing as an Orbax tree mismatch.
    """
    if preset:
        from dcgan_tpu.presets import get_preset  # lazy: presets imports us

        base = get_preset(preset).model
    else:
        saved = load_config(checkpoint_dir)
        base = saved.model if saved is not None else ModelConfig()
        if saved is not None and saved.progressive:
            res = _progressive_checkpoint_resolution(checkpoint_dir)
            if res is not None and res != base.output_size:
                print(f"[dcgan_tpu] progressive checkpoint: latest step was "
                      f"saved at r{res} (schedule "
                      f"{saved.progressive!r} ends at "
                      f"r{base.output_size}); building the r{res} model",
                      file=sys.stderr)
                base = dataclasses.replace(base, output_size=res)
    given = {k: v for k, v in (overrides or {}).items() if v is not None}
    return dataclasses.replace(base, **given)
