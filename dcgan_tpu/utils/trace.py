"""Shared jax.profiler trace parsing: device-track selection, per-program
rows, and the step-time digest (ISSUE 6 tentpole).

`tools/trace_summary.py` owned the only Chrome-trace parser; promoting it
here lets the trainer digest a capture IN-PROCESS (on the services worker)
the moment a trigger-file capture closes, instead of requiring an offline
tool pass — the `perf/device/*` attribution ROADMAP item 3 needs (where a
step's time actually goes on the device: compute, collectives, and the
idle gaps between consecutive dispatches that an overlapped G/D pipeline
would fill).

Track selection, in preference order:

- pids whose process_name contains "TPU" (e.g. ``/device:TPU:0``) — real
  device timelines. NOT "the busiest pid": on a v5e capture the host pid's
  total X-duration exceeds the device's (host spans nest), so a naive
  busiest-pid rule would pick the host. Within a TPU pid, program-level
  accounting reads the ``XLA Modules`` thread (per-program executions) —
  the ``Steps`` thread's spans cover the whole timeline (they would report
  zero idle) and the ``XLA Ops`` thread is per-op; ops are consulted only
  for collective attribution (collectives are op-named, not module-named).
- else the busiest XLA-executor THREAD track (thread_name matching
  ``XLA``, e.g. ``tf_XLATfrtCpuClient/...``) — where CPU captures put op
  execution. Thread granularity matters: the CPU ``python`` thread carries
  whole-call tracing spans (PjitFunction, profiler frames) that cover the
  timeline and would report zero idle.
- else the busiest non-``python`` thread track of any pid.
- else: no device events (`source == "none"`); callers decide (the CLI
  tool exits nonzero with a usage hint — a silent empty report looked like
  a healthy parse, satellite fix).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Any, Dict, List, Tuple

# substrings marking a device-side collective in XLA program/op names
_COLLECTIVE_MARKERS = ("all-reduce", "all-gather", "reduce-scatter",
                      "all-to-all", "collective-permute", "collective",
                      "allreduce", "allgather", "ragged-all-to-all")


def find_trace(path: str, host: str = "") -> str:
    """Accept a trace file or a --profile_dir root (finds the newest).

    With `host`, hits whose filename belongs to that host win: the
    profiler names each process's file `<hostname>.trace.json.gz` inside
    a shared timestamped session dir, so on a shared-filesystem fleet the
    plain lexicographic tail could be a PEER's timeline. Falls back to
    the newest hit when no filename matches (single-machine multi-process
    captures share one hostname; old layouts may differ)."""
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(
        path, "**", "*.trace.json.gz"), recursive=True))
    if not hits:
        raise FileNotFoundError(f"no *.trace.json.gz under {path}")
    if host:
        mine = [h for h in hits
                if os.path.basename(h).startswith(host + ".")]
        if mine:
            return mine[-1]
    return hits[-1]


def load_events(trace_path: str) -> List[dict]:
    """The raw traceEvents list of one capture (gz or plain json)."""
    opener = gzip.open if trace_path.endswith(".gz") else open
    with opener(trace_path) as f:
        data = json.load(f)
    return data.get("traceEvents", [])


def _meta_names(events: List[dict], kind: str) -> Dict[Any, str]:
    """{pid or (pid, tid): name} from 'process_name'/'thread_name' rows."""
    out: Dict[Any, str] = {}
    for e in events:
        if e.get("ph") != "M" or e.get("name") != kind:
            continue
        name = str(e.get("args", {}).get("name", ""))
        key = e["pid"] if kind == "process_name" \
            else (e["pid"], e.get("tid"))
        out[key] = name
    return out


def select_device_tracks(events: List[dict]
                         ) -> Tuple[List[dict], List[dict], str]:
    """(program events, op events, source) of the device timeline.

    `programs` carries per-program execution spans (busy/idle/step-time
    accounting); `ops` carries the finer per-op spans when the capture has
    them (collective attribution — collectives are op-named). Source is
    "tpu" (TPU-named pid), "xla-thread" / "busiest-thread" (CPU-capture
    fallbacks; programs == ops there), or "none"."""
    xs = [e for e in events if e.get("ph") == "X" and "dur" in e]
    if not xs:
        return [], [], "none"
    pnames = _meta_names(events, "process_name")
    tnames = _meta_names(events, "thread_name")

    def tname(e):
        return tnames.get((e["pid"], e.get("tid")), "")

    tpu_pids = {pid for pid, name in pnames.items() if "TPU" in name}
    if tpu_pids:
        dev = [e for e in xs if e["pid"] in tpu_pids]
        programs = [e for e in dev if "XLA Modules" in tname(e)]
        if not programs:
            # module track absent (older capture layout): everything but
            # the whole-timeline "Steps" spans
            programs = [e for e in dev if "Steps" not in tname(e)] or dev
        ops = [e for e in dev if "XLA Ops" in tname(e)] or programs
        return programs, ops, "tpu"
    by_track: Dict[Tuple[Any, Any], float] = {}
    for e in xs:
        key = (e["pid"], e.get("tid"))
        by_track[key] = by_track.get(key, 0.0) + e["dur"]

    def busiest(keys):
        return max(keys, key=lambda k: by_track[k], default=None)

    xla = busiest([k for k in by_track if "XLA" in tnames.get(k, "")])
    if xla is not None:
        track, source = xla, "xla-thread"
    else:
        track = busiest([k for k in by_track
                         if "python" not in tnames.get(k, "").lower()]) \
            or busiest(by_track)
        source = "busiest-thread"
    picked = [e for e in xs if (e["pid"], e.get("tid")) == track]
    return picked, picked, source


def program_rows(device_events: List[dict]) -> List[dict]:
    """Per-program execution stats, sorted by total time descending —
    the rows tools/trace_summary.py prints."""
    rows: Dict[str, List[float]] = {}
    for e in device_events:
        rows.setdefault(e["name"], []).append(e["dur"] / 1e3)  # us -> ms
    out = []
    for name, durs in sorted(rows.items(), key=lambda kv: -sum(kv[1])):
        ds = sorted(durs)
        out.append({
            "program": name[:80], "n": len(ds),
            "total_ms": round(sum(ds), 3),
            "ms_min": round(ds[0], 4), "ms_max": round(ds[-1], 4),
            "ms_median": round(ds[len(ds) // 2], 4),
        })
    return out


def summarize(trace_path: str) -> Tuple[List[dict], str]:
    """(per-program rows, track source) for one capture."""
    programs, _, source = select_device_tracks(load_events(trace_path))
    return program_rows(programs), source


def _merge_intervals(spans: List[Tuple[float, float]]
                     ) -> List[Tuple[float, float]]:
    merged: List[List[float]] = []
    for lo, hi in sorted(spans):
        if merged and lo <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], hi)
        else:
            merged.append([lo, hi])
    return [(lo, hi) for lo, hi in merged]


def is_collective(name: str) -> bool:
    low = name.lower()
    return any(m in low for m in _COLLECTIVE_MARKERS)


def devstep_ms(path: str, per_exec: int = 1):
    """The device's own per-step ms from a capture (file or profile dir):
    the busiest program's median execution divided by `per_exec` (the
    steps each execution covers — a scanned multi-step program's scan
    width). None when the capture has no usable device events — callers
    (the BENCH rows) publish the field as null rather than fabricating.
    One definition shared by bench.py, tools/bench_trainer_loop.py, and
    the trainer's live perf/device/step_ms so the three can't drift."""
    d = digest(find_trace(path))
    if d["source"] == "none" or d["program_ms_median"] <= 0:
        return None
    return d["program_ms_median"] / max(1, per_exec)


def digest(trace_path: str) -> dict:
    """Step-time attribution over one capture's device timeline.

    Returns (all ms):
      - source:        which track selection applied (see module doc)
      - compute_ms:    union of device busy time (overlapping spans merged,
                       so nested/async events are not double counted)
      - collective_ms: busy time of collective-named events
      - idle_gap_ms:   span minus busy — the time the device sat between
                       consecutive dispatches. THE number ROADMAP item 3
                       (overlapped G/D execution) needs to attribute
                       honestly: a pipelined schedule's win is bounded by
                       this gap.
      - span_ms:       first event start -> last event end
      - program / program_n / program_ms_median: the busiest program (on a
        real device timeline: the train step program; callers divide its
        median by steps_per_call for a per-step devstep_ms)
      - rows:          the full per-program table
    """
    programs, ops, source = select_device_tracks(load_events(trace_path))
    if not programs:
        return {"source": "none", "compute_ms": 0.0, "collective_ms": 0.0,
                "idle_gap_ms": 0.0, "span_ms": 0.0, "program": "",
                "program_n": 0, "program_ms_median": 0.0, "rows": []}
    spans = [(e["ts"], e["ts"] + e["dur"]) for e in programs]
    merged = _merge_intervals(spans)
    busy_us = sum(hi - lo for lo, hi in merged)
    span_us = merged[-1][1] - merged[0][0]
    coll = [(e["ts"], e["ts"] + e["dur"])
            for e in ops if is_collective(e["name"])]
    coll_us = sum(hi - lo for lo, hi in _merge_intervals(coll))
    rows = program_rows(programs)
    top = rows[0]
    return {
        "source": source,
        "compute_ms": round(busy_us / 1e3, 4),
        "collective_ms": round(coll_us / 1e3, 4),
        "idle_gap_ms": round(max(0.0, span_us - busy_us) / 1e3, 4),
        "span_ms": round(span_us / 1e3, 4),
        "program": top["program"],
        "program_n": top["n"],
        "program_ms_median": top["ms_median"],
        "rows": rows,
    }
