"""Shared jax.profiler trace parsing: device-track selection, per-program
rows, and the step-time digest (ISSUE 6 tentpole).

`tools/trace_summary.py` owned the only Chrome-trace parser; promoting it
here lets the trainer digest a capture IN-PROCESS (on the services worker)
the moment a trigger-file capture closes, instead of requiring an offline
tool pass — the `perf/device/*` attribution ROADMAP item 3 needs (where a
step's time actually goes on the device: compute, collectives, and the
idle gaps between consecutive dispatches that an overlapped G/D pipeline
would fill).

Track selection, in preference order:

- pids whose process_name contains "TPU" (e.g. ``/device:TPU:0``) — real
  device timelines. NOT "the busiest pid": on a v5e capture the host pid's
  total X-duration exceeds the device's (host spans nest), so a naive
  busiest-pid rule would pick the host. Within a TPU pid, program-level
  accounting reads the ``XLA Modules`` thread (per-program executions) —
  the ``Steps`` thread's spans cover the whole timeline (they would report
  zero idle) and the ``XLA Ops`` thread is per-op; ops are consulted only
  for collective attribution (collectives are op-named, not module-named).
- else the busiest XLA-executor thread GROUP (thread_name matching
  ``XLA``, grouped by the pool prefix before ``/`` — e.g. all
  ``tf_XLAEigen/<id>`` threads together), spans merged across the
  group's threads and client ``(wait for …)`` spans excluded — where CPU
  captures put op execution. Group granularity matters twice over: the
  CPU ``python`` thread carries whole-call tracing spans (PjitFunction,
  profiler frames) that cover the timeline and would report zero idle,
  and a single-thread pick undercounts captures whose programs spread
  across a pool's threads (the pipelined G/D stage programs do).
- else the busiest non-``python`` thread group of any pid.
- else: no device events (`source == "none"`); callers decide (the CLI
  tool exits nonzero with a usage hint — a silent empty report looked like
  a healthy parse, satellite fix).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Any, Dict, List, Tuple

# substrings marking a device-side collective in XLA program/op names
_COLLECTIVE_MARKERS = ("all-reduce", "all-gather", "reduce-scatter",
                      "all-to-all", "collective-permute", "collective",
                      "allreduce", "allgather", "ragged-all-to-all")


def find_trace(path: str, host: str = "") -> str:
    """Accept a trace file or a --profile_dir root (finds the newest).

    With `host`, hits whose filename belongs to that host win: the
    profiler names each process's file `<hostname>.trace.json.gz` inside
    a shared timestamped session dir, so on a shared-filesystem fleet the
    plain lexicographic tail could be a PEER's timeline. Falls back to
    the newest hit when no filename matches (single-machine multi-process
    captures share one hostname; old layouts may differ)."""
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(
        path, "**", "*.trace.json.gz"), recursive=True))
    if not hits:
        raise FileNotFoundError(f"no *.trace.json.gz under {path}")
    if host:
        mine = [h for h in hits
                if os.path.basename(h).startswith(host + ".")]
        if mine:
            return mine[-1]
    return hits[-1]


def load_events(trace_path: str) -> List[dict]:
    """The raw traceEvents list of one capture (gz or plain json)."""
    opener = gzip.open if trace_path.endswith(".gz") else open
    with opener(trace_path) as f:
        data = json.load(f)
    return data.get("traceEvents", [])


def _meta_names(events: List[dict], kind: str) -> Dict[Any, str]:
    """{pid or (pid, tid): name} from 'process_name'/'thread_name' rows."""
    out: Dict[Any, str] = {}
    for e in events:
        if e.get("ph") != "M" or e.get("name") != kind:
            continue
        name = str(e.get("args", {}).get("name", ""))
        key = e["pid"] if kind == "process_name" \
            else (e["pid"], e.get("tid"))
        out[key] = name
    return out


def select_device_tracks(events: List[dict]
                         ) -> Tuple[List[dict], List[dict], str]:
    """(program events, op events, source) of the device timeline.

    `programs` carries per-program execution spans (busy/idle/step-time
    accounting); `ops` carries the finer per-op spans when the capture has
    them (collective attribution — collectives are op-named). Source is
    "tpu" (TPU-named pid), "xla-thread" / "busiest-thread" (CPU-capture
    fallbacks; programs == ops there), or "none"."""
    xs = [e for e in events if e.get("ph") == "X" and "dur" in e]
    if not xs:
        return [], [], "none"
    pnames = _meta_names(events, "process_name")
    tnames = _meta_names(events, "thread_name")

    def tname(e):
        return tnames.get((e["pid"], e.get("tid")), "")

    tpu_pids = {pid for pid, name in pnames.items() if "TPU" in name}
    if tpu_pids:
        dev = [e for e in xs if e["pid"] in tpu_pids]
        programs = [e for e in dev if "XLA Modules" in tname(e)]
        if not programs:
            # module track absent (older capture layout): everything but
            # the whole-timeline "Steps" spans
            programs = [e for e in dev if "Steps" not in tname(e)] or dev
        ops = [e for e in dev if "XLA Ops" in tname(e)] or programs
        return programs, ops, "tpu"
    # CPU fallbacks select the busiest thread GROUP, not the busiest
    # single thread: executor pools name their threads "<pool>/<id>"
    # (tf_XLAEigen/…, tf_XLATfrtCpuClient/…) and a capture whose programs
    # spread across a pool's threads — the pipelined G/D stage programs
    # do exactly that — would have roughly half its busy time invisible
    # to a single-thread pick, inflating idle_gap_ms as a measurement
    # artifact. Spans are merged across the group's threads (the union
    # accounting below already handles the overlap). Client-side
    # "… (wait for …)" spans are excluded BEFORE selection and
    # accounting: they are the executor *waiting* on work, and counting
    # them as busy would both crown the wait-dominated client group and
    # report near-zero idle on any capture.
    def _group(key):
        # pid stays in the key: two processes may each run a same-named
        # pool (or unnamed threads, prefix ""), and merging across pids
        # would mix unrelated timelines into one pseudo-track
        return (key[0], tnames.get(key, "").split("/")[0])

    by_group: Dict[Tuple[Any, str], float] = {}
    for e in xs:
        if "wait" in e["name"].lower():
            continue
        g = _group((e["pid"], e.get("tid")))
        by_group[g] = by_group.get(g, 0.0) + e["dur"]

    def busiest(groups):
        return max(groups, key=lambda g: by_group[g], default=None)

    xla = busiest([g for g in by_group if "XLA" in g[1]])
    if xla is not None:
        group, source = xla, "xla-thread"
    else:
        group = busiest([g for g in by_group
                         if "python" not in g[1].lower()])
        if group is None:   # explicit None check — an unnamed-thread
            group = busiest(by_group)  # group (pid, "") is a valid pick
        source = "busiest-thread"
    picked = [e for e in xs if _group((e["pid"], e.get("tid"))) == group
              and "wait" not in e["name"].lower()]
    return picked, picked, source


def program_rows(device_events: List[dict]) -> List[dict]:
    """Per-program execution stats, sorted by total time descending —
    the rows tools/trace_summary.py prints."""
    rows: Dict[str, List[float]] = {}
    for e in device_events:
        rows.setdefault(e["name"], []).append(e["dur"] / 1e3)  # us -> ms
    out = []
    for name, durs in sorted(rows.items(), key=lambda kv: -sum(kv[1])):
        ds = sorted(durs)
        out.append({
            "program": name[:80], "n": len(ds),
            "total_ms": round(sum(ds), 3),
            "ms_min": round(ds[0], 4), "ms_max": round(ds[-1], 4),
            "ms_median": round(ds[len(ds) // 2], 4),
        })
    return out


def summarize(trace_path: str) -> Tuple[List[dict], str]:
    """(per-program rows, track source) for one capture."""
    programs, _, source = select_device_tracks(load_events(trace_path))
    return program_rows(programs), source


def _merge_intervals(spans: List[Tuple[float, float]]
                     ) -> List[Tuple[float, float]]:
    merged: List[List[float]] = []
    for lo, hi in sorted(spans):
        if merged and lo <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], hi)
        else:
            merged.append([lo, hi])
    return [(lo, hi) for lo, hi in merged]


def is_collective(name: str) -> bool:
    low = name.lower()
    return any(m in low for m in _COLLECTIVE_MARKERS)


def _intersect_total(a: List[Tuple[float, float]],
                     b: List[Tuple[float, float]]) -> float:
    """Total length of the intersection of two MERGED interval lists
    (both sorted, non-overlapping — `_merge_intervals` output)."""
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def devstep_ms(path: str, per_exec: int = 1):
    """The device's own per-step ms from a capture (file or profile dir):
    the busiest program's median execution divided by `per_exec` (the
    steps each execution covers — a scanned multi-step program's scan
    width). None when the capture has no usable device events — callers
    (the BENCH rows) publish the field as null rather than fabricating.
    One definition shared by bench.py, tools/bench_trainer_loop.py, and
    the trainer's live perf/device/step_ms so the three can't drift."""
    d = digest(find_trace(path))
    if d["source"] == "none" or d["program_ms_median"] <= 0:
        return None
    return d["program_ms_median"] / max(1, per_exec)


def stage_step_ms(d: dict,
                  stages: Tuple[str, ...] = ("d_update", "g_update")
                  ) -> float:
    """Per-step device ms when the step was dispatched as separable stage
    programs (--pipeline_gd, ISSUE 7): the sum of the named stages' median
    executions — the busiest-program median alone would report roughly
    half a step there. 0.0 when the capture's track doesn't name the
    stage programs (the CPU op-level fallback) — callers keep their
    busiest-program estimate. One definition shared by the trainer's
    perf/device/step_ms and bench.py's pipelined A/B arm."""
    return sum(r["ms_median"] for r in d.get("rows", [])
               if any(s in r["program"] for s in stages))


def digest(trace_path: str) -> dict:
    """Step-time attribution over one capture's device timeline.

    Returns (all ms):
      - source:        which track selection applied (see module doc)
      - compute_ms:    union of device busy time (overlapping spans merged,
                       so nested/async events are not double counted)
      - collective_ms: busy time of collective-named events
      - idle_gap_ms:   span minus busy — the time the device sat between
                       consecutive dispatches. THE number ROADMAP item 3
                       (overlapped G/D execution) needs to attribute
                       honestly: a pipelined schedule's win is bounded by
                       this gap.
      - span_ms:       first event start -> last event end
      - program / program_n / program_ms_median: the busiest program (on a
        real device timeline: the train step program; callers divide its
        median by steps_per_call for a per-step devstep_ms)
      - overlap_frac:  fraction of collective busy time that ran
                       CONCURRENTLY with non-collective compute (merged
                       collective intervals intersected with merged
                       non-collective busy intervals, over the collective
                       total; 0.0 when the capture has no collectives).
                       THE attribution the `--comm_overlap` A/B needs
                       (ISSUE 20): bucketing/prefetching claims to hide
                       collective time behind compute, and this is where
                       hidden-vs-exposed shows up on the device timeline
                       — wall-clock alone can't separate "fewer ops" from
                       "overlapped ops".
      - rows:          the full per-program table
    """
    programs, ops, source = select_device_tracks(load_events(trace_path))
    if not programs:
        return {"source": "none", "compute_ms": 0.0, "collective_ms": 0.0,
                "idle_gap_ms": 0.0, "span_ms": 0.0, "program": "",
                "program_n": 0, "program_ms_median": 0.0,
                "overlap_frac": 0.0, "rows": []}
    spans = [(e["ts"], e["ts"] + e["dur"]) for e in programs]
    merged = _merge_intervals(spans)
    busy_us = sum(hi - lo for lo, hi in merged)
    span_us = merged[-1][1] - merged[0][0]
    coll_merged = _merge_intervals(
        [(e["ts"], e["ts"] + e["dur"])
         for e in ops if is_collective(e["name"])])
    coll_us = sum(hi - lo for lo, hi in coll_merged)
    nonc_merged = _merge_intervals(
        [(e["ts"], e["ts"] + e["dur"])
         for e in ops if not is_collective(e["name"])])
    overlap_us = _intersect_total(coll_merged, nonc_merged)
    rows = program_rows(programs)
    top = rows[0]
    return {
        "source": source,
        "compute_ms": round(busy_us / 1e3, 4),
        "collective_ms": round(coll_us / 1e3, 4),
        "idle_gap_ms": round(max(0.0, span_us - busy_us) / 1e3, 4),
        "span_ms": round(span_us / 1e3, 4),
        "program": top["program"],
        "program_n": top["n"],
        "program_ms_median": top["ms_median"],
        "overlap_frac": round(overlap_us / coll_us, 4) if coll_us else 0.0,
        "rows": rows,
    }
