"""Metrics/observability: scalars, histograms, images -> JSONL events + stdout.

The reference's three channels (SURVEY.md §5): per-step stdout loss logging
(image_train.py:160-169), TF summaries — activation/variable histograms and
loss scalars, chief-only, time-throttled to save_summaries_secs=10
(image_train.py:86-115,155-178) — and periodic PNG sample grids. This module
provides the first two natively: an append-only JSONL event stream any tool
can tail, with the same time-throttling contract; grids live in
utils/images.py.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, Mapping, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class CounterSnapshot:
    """One coherent read of the run's recovery/perf counters (ISSUE 6).

    Before this registry the counters lived in four unrelated places —
    `HostServices.dropped`, the process-global quarantine tally, the
    rollback manager's count, and `CompileCacheMonitor` — and each consumer
    (the scalar rows' `_health_extras`, now also the flight recorder and
    the fleet health vector) re-derived its own subset. A snapshot is the
    single read surface; fields a run never wires stay 0.
    """

    services_queue: int = 0        # tasks pending on the services worker
    services_dropped: int = 0      # tasks discarded by backpressure
    rollbacks: int = 0             # NaN-gate rollbacks this run
    corrupt_records: int = 0       # quarantined records this run (delta
                                   # from the trainer's corrupt_base)
    compile_cache_requests: int = 0
    compile_cache_hits: int = 0
    compile_cache_misses: int = 0
    progressive_phase: int = 0     # active progressive-schedule phase
                                   # index (ISSUE 15); 0 in fixed-
                                   # resolution runs — flight-recorder
                                   # dumps and the fleet health vector
                                   # both read it, so a crash dump or a
                                   # straggler row names the phase it
                                   # happened in
    live_topology: int = 0         # live-elasticity topology in effect
                                   # (ISSUE 18): the ACTIVE mesh's device
                                   # count when a notice-driven switch has
                                   # happened, 0 before any switch (and in
                                   # every unarmed run) — a crash dump or
                                   # fleet row from a shrunk run names the
                                   # mesh it ran on
    master_f32_leaves: int = 0     # f32 Adam master-moment leaves under a
                                   # reduced-precision policy (ISSUE 17,
                                   # elastic/rules.py::
                                   # count_master_f32_leaves); 0 when
                                   # precision is unset or f32 — a crash
                                   # dump from a bf16 run that shows 0
                                   # here means the master copy was lost
    # serving plane (ISSUE 9, dcgan_tpu/serve): zero in training runs —
    # the SamplerServer registers these on its own registry instance
    serve_requests: int = 0        # generation requests accepted
    serve_completed: int = 0       # requests fully resolved with images
    serve_dropped: int = 0         # requests shed, total (overload +
                                   # failover — the two fields below)
    serve_dropped_overload: int = 0  # shed by drop-oldest backpressure
    serve_dropped_failover: int = 0  # abandoned during fleet failover
                                     # (no healthy peer could absorb)
    serve_batches: int = 0         # bucketed device dispatches
    serve_queue: int = 0           # requests pending on the serve queue

    def as_dict(self) -> Dict[str, int]:
        # flat getattr walk, not dataclasses.asdict: asdict deep-copies
        # recursively, and this runs once per consumed step on the dispatch
        # thread (the flight recorder is on by default)
        return {name: getattr(self, name) for name in _SNAPSHOT_FIELD_ORDER}


_SNAPSHOT_FIELD_ORDER = tuple(f.name for f in
                              dataclasses.fields(CounterSnapshot))
_SNAPSHOT_FIELDS = frozenset(_SNAPSHOT_FIELD_ORDER)


class CounterRegistry:
    """Named providers -> CounterSnapshot; the trainer registers each
    subsystem's live counter once and every consumer reads `snapshot()`."""

    def __init__(self) -> None:
        self._providers: Dict[str, Callable[[], int]] = {}
        self._groups: list = []

    def provide(self, field: str, fn: Callable[[], int]) -> None:
        if field not in _SNAPSHOT_FIELDS:
            raise ValueError(
                f"unknown counter {field!r}; CounterSnapshot fields: "
                f"{sorted(_SNAPSHOT_FIELDS)}")
        self._providers[field] = fn

    def provide_group(self, fields, fn: Callable[[], Mapping[str, Any]]
                      ) -> None:
        """One provider feeding several fields from a single read — for
        sources whose counters come as one dict (CompileCacheMonitor):
        snapshot() calls `fn` once, not once per field. `fn` may return
        extra keys; only `fields` are consumed."""
        for field in fields:
            if field not in _SNAPSHOT_FIELDS:
                raise ValueError(
                    f"unknown counter {field!r}; CounterSnapshot fields: "
                    f"{sorted(_SNAPSHOT_FIELDS)}")
        self._groups.append((tuple(fields), fn))

    def snapshot(self) -> CounterSnapshot:
        vals = {name: int(fn()) for name, fn in self._providers.items()}
        for fields, fn in self._groups:
            got = fn()
            for field in fields:
                vals[field] = int(got[field])
        return CounterSnapshot(**vals)


def histogram_summary(values, bins: int = 30) -> Dict[str, Any]:
    """Compact histogram record (the replacement for tf.histogram_summary,
    distriubted_model.py:79): moments + sparsity + binned counts.

    Non-finite-safe: a tensor carrying NaN/Inf (a diverging run mid-flight)
    bins its FINITE values and reports a `nonfinite_count` key instead of
    crashing the writer — telemetry degrades, the numerical-health gate
    (not the histogram channel) owns killing the run. The extra key appears
    ONLY when non-finite values exist, so healthy runs' event records are
    byte-identical to before."""
    arr = np.asarray(values, dtype=np.float32).ravel()
    finite = arr[np.isfinite(arr)] if arr.size else arr
    if finite.size:
        counts, edges = np.histogram(finite, bins=bins)
    else:
        counts, edges = np.histogram([], bins=bins, range=(0.0, 1.0))
    out = {
        "count": int(arr.size),
        "min": float(finite.min()) if finite.size else 0.0,
        "max": float(finite.max()) if finite.size else 0.0,
        "mean": float(finite.mean()) if finite.size else 0.0,
        "std": float(finite.std()) if finite.size else 0.0,
        # zero_fraction: the reference's per-layer sparsity scalar
        # (distriubted_model.py:80)
        "zero_fraction": float(np.mean(arr == 0.0)) if arr.size else 0.0,
        "bin_edges": [float(e) for e in edges],
        "bin_counts": [int(c) for c in counts],
    }
    if finite.size != arr.size:
        out["nonfinite_count"] = int(arr.size - finite.size)
    return out


def activation_stats(acts: Mapping[str, Any], bins: int = 30,
                     axis_name: Optional[str] = None
                     ) -> Dict[str, Dict[str, Any]]:
    """Device-side histogram + sparsity per activation tensor.

    The reference ships every activation tensor to the summary writer
    (distriubted_model.py:79-80); here the reduction happens on device inside
    the jitted summary program — only ~2*bins scalars per layer cross to the
    host. Returns {name: {min,max,mean,std,zero_fraction,bin_counts,bin_edges}}
    of jnp values; MetricWriter.write_activations converts to JSON.

    With `axis_name` (explicit-collective execution, e.g. the shard_map
    backend) the stats are *global*: min/max are pmax'd first so every shard
    bins against the same edges, then the counts psum — the result is the
    exact histogram of the full cross-shard batch, identical on every shard.
    """
    import jax.numpy as jnp
    from jax import lax

    out: Dict[str, Dict[str, Any]] = {}
    for name, x in acts.items():
        v = x.astype(jnp.float32).ravel()
        lo, hi = jnp.min(v), jnp.max(v)
        mean = jnp.mean(v)
        zero_frac = jnp.mean(v == 0.0)
        count = v.size
        if axis_name is not None:
            lo = lax.pmin(lo, axis_name)
            hi = lax.pmax(hi, axis_name)
            mean = lax.pmean(mean, axis_name)
            zero_frac = lax.pmean(zero_frac, axis_name)
            count = count * lax.psum(1, axis_name)
        # two-pass variance around the (global) mean — E[x^2]-E[x]^2 would
        # cancel catastrophically in f32 for low-relative-variance layers
        var = jnp.mean(jnp.square(v - mean))
        if axis_name is not None:
            var = lax.pmean(var, axis_name)
        counts, edges = jnp.histogram(v, bins=bins, range=(lo, hi))
        if axis_name is not None:
            counts = lax.psum(counts, axis_name)
        out[name] = {
            "count": count,
            "min": lo,
            "max": hi,
            "mean": mean,
            "std": jnp.sqrt(var),
            # the reference's per-layer sparsity scalar
            # (tf.nn.zero_fraction, distriubted_model.py:80)
            "zero_fraction": zero_frac,
            "bin_counts": counts,
            "bin_edges": edges,
        }
    return out


class MetricWriter:
    """Chief-only, time-throttled event writer.

    write_scalars / write_histograms append JSONL events; `every_secs`
    mirrors the reference's save_summaries_secs gate (image_train.py:37,
    155-178): ready() flips true at most once per interval.

    With tensorboard=True (default) every event is mirrored into a
    TensorBoard-native events.out.tfevents.* file (utils/tb_events.py) —
    scalars, per-layer histograms + sparsity scalars, and sample-grid images
    render in the same dashboards the reference's TF summaries did
    (image_train.py:86-118).

    Threading contract: NOT thread-safe — the JSONL append and the TB
    writer's internal buffer both assume one caller at a time. The trainer
    honors this by routing every write through one thread: the services
    executor's single worker in async mode (train/services.py), the
    dispatch thread itself with --async_services=false. `ready()` stays on
    the dispatch thread in both modes (it only reads the clock).
    """

    def __init__(self, logdir: str, *, every_secs: float = 10.0,
                 enabled: bool = True, filename: str = "events.jsonl",
                 tensorboard: bool = True):
        self.logdir = logdir
        self.every_secs = every_secs
        self.enabled = enabled
        self._next_time = 0.0  # first call always fires, like the reference
        self._path = os.path.join(logdir, filename)
        self._tb = None
        if enabled:
            from dcgan_tpu.utils.retry import retry_io

            # retried (DCG006): one-shot at construction, and a transient
            # mkdir failure would kill the run before its first step
            retry_io(lambda: os.makedirs(logdir, exist_ok=True),
                     tag="metrics-mkdir")
            if tensorboard:
                from dcgan_tpu.utils.tb_events import TBEventWriter

                self._tb = TBEventWriter(logdir)

    def ready(self, now: Optional[float] = None) -> bool:
        if not self.enabled:
            return False
        now = time.time() if now is None else now
        if now >= self._next_time:
            # advance from *now*, not by accumulation — a slow step shouldn't
            # cause a burst of catch-up summaries
            self._next_time = now + self.every_secs
            return True
        return False

    def _emit(self, kind: str, step: int, payload: Mapping[str, Any]) -> None:
        if not self.enabled:
            return
        event = {"kind": kind, "step": int(step), "time": time.time(),
                 **payload}
        with open(self._path, "a") as f:
            f.write(json.dumps(event) + "\n")

    def write_scalars(self, step: int, scalars: Mapping[str, Any]) -> None:
        vals = {k: float(v) for k, v in scalars.items()}
        self._emit("scalars", step, {"values": vals})
        if self._tb:
            for k, v in vals.items():
                self._tb.add_scalar(k, v, step)
            self._tb.flush()

    def write_histograms(self, step: int, tensors: Mapping[str, Any],
                         bins: int = 30) -> None:
        # one reduction pass per tensor; the TB mirror reuses the bins
        summaries = {k: histogram_summary(v, bins) for k, v in tensors.items()}
        self._emit("histograms", step, {"values": summaries})
        if self._tb:
            for k, s in summaries.items():
                self._tb.add_histogram_bins(
                    k, step, bin_edges=s["bin_edges"],
                    bin_counts=s["bin_counts"], minimum=s["min"],
                    maximum=s["max"], num=float(s["count"]), mean=s["mean"],
                    std=s["std"])
            self._tb.flush()

    def write_activations(self, step: int,
                          stats: Mapping[str, Mapping[str, Any]]) -> None:
        """Emit precomputed per-layer activation stats (activation_stats
        output, already reduced on device)."""
        def conv(rec):
            out = {}
            for k, v in rec.items():
                a = np.asarray(v)
                if a.ndim:  # bin_counts stay ints, matching histogram_summary
                    cast = int if k == "bin_counts" else float
                    out[k] = [cast(x) for x in a.ravel()]
                else:
                    out[k] = int(a) if k == "count" else float(a)
            return out
        converted = {k: conv(rec) for k, rec in stats.items()}
        self._emit("activations", step, {"values": converted})
        if self._tb:
            # the reference's two per-layer channels: activation histogram +
            # sparsity scalar (distriubted_model.py:79-80)
            for k, rec in converted.items():
                self._tb.add_histogram_bins(
                    k + "/activations", step,
                    bin_edges=rec["bin_edges"], bin_counts=rec["bin_counts"],
                    minimum=rec["min"], maximum=rec["max"],
                    num=float(rec["count"]), mean=rec["mean"],
                    std=rec["std"])
                self._tb.add_scalar(k + "/sparsity", rec["zero_fraction"],
                                    step)
            self._tb.flush()

    def write_image_event(self, step: int, name: str, path: str) -> None:
        """Record that an image artifact was written (the grid PNG itself is
        saved by utils.images)."""
        self._emit("image", step, {"name": name, "path": path})
        if self._tb and os.path.exists(path):
            with open(path, "rb") as f:
                self._tb.add_image_png(name, f.read(), step)
            self._tb.flush()

    def flush(self) -> None:
        """Force buffered TB state to disk (JSONL writes are already
        per-event durable); the services drain barrier's final task."""
        if self._tb:
            self._tb.flush()

    def close(self) -> None:
        if self._tb:
            self._tb.close()
            self._tb = None


def param_histograms(params, prefix: str = "") -> Dict[str, np.ndarray]:
    """Flatten a param pytree into {dotted/path: leaf} for histogram events —
    the reference histograms every trainable variable (image_train.py:114-115).
    """
    import jax

    out: Dict[str, np.ndarray] = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        name = prefix + "/".join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        out[name] = np.asarray(leaf)
    return out
