"""Bounded retry with jittered exponential backoff for transient host IO.

The fail-operational layer's policy for IO that is *retryable by nature*
(filesystem writes of derived artifacts: checkpoint integrity manifests,
metric/event files): a transient `OSError` gets a few spaced attempts before
it becomes a real failure, instead of killing a multi-hour run over one NFS
hiccup. Deliberately NOT used around Orbax array writes themselves — a
half-finished collective save is not safely re-enterable from this layer;
Orbax's tmp+rename protocol plus the integrity manifest fallback in
utils/checkpoint.py own that failure mode.

Jitter is deterministic (seeded from the site tag + attempt number): two
processes retrying the same site still decorrelate, and a chaos drill run
is exactly reproducible.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Tuple, Type, TypeVar

from dcgan_tpu.testing import chaos

T = TypeVar("T")

DEFAULT_ATTEMPTS = 3
DEFAULT_BASE_DELAY_S = 0.05
DEFAULT_MAX_DELAY_S = 2.0


def retry_io(fn: Callable[[], T], *, tag: str,
             attempts: int = DEFAULT_ATTEMPTS,
             base_delay_s: float = DEFAULT_BASE_DELAY_S,
             max_delay_s: float = DEFAULT_MAX_DELAY_S,
             retry_on: Tuple[Type[BaseException], ...] = (OSError,),
             sleep: Callable[[float], None] = time.sleep) -> T:
    """Run `fn` with up to `attempts` tries; `retry_on` failures back off
    (base * 2^i plus deterministic jitter, capped) between tries, and the
    last failure propagates unchanged. `tag` names the site in logs and is
    the chaos hook's selector (testing/chaos.py io_error_once)."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    for attempt in range(attempts):
        try:
            chaos.maybe_io_error(tag)
            return fn()
        except retry_on as e:
            if attempt == attempts - 1:
                raise
            delay = min(max_delay_s, base_delay_s * (2 ** attempt))
            delay *= 0.5 + random.Random(f"{tag}:{attempt}").random()
            print(f"[dcgan_tpu] transient IO error at {tag!r} "
                  f"(attempt {attempt + 1}/{attempts}): {e} — "
                  f"retrying in {delay * 1e3:.0f} ms", flush=True)
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
