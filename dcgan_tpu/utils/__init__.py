"""Aux subsystems: checkpoint/resume, metrics, sample grids, profiling."""

from dcgan_tpu.utils.checkpoint import Checkpointer  # noqa: F401
from dcgan_tpu.utils.images import image_grid, inverse_transform, save_png  # noqa: F401
from dcgan_tpu.utils.metrics import MetricWriter, histogram_summary  # noqa: F401
