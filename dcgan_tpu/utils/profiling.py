"""Tracing / profiling: per-step timing stats + jax.profiler trace capture.

The reference has no tracing or profiling subsystem (SURVEY.md §5); its nearest
artifacts are the elapsed-time stamp in the per-step log (image_train.py:148,162)
and the dead `log_device_placement` flag (image_train.py:36). SURVEY.md names
the TPU-native equivalent explicitly — "jax.profiler trace capture + per-step
timing" — and this module is it:

- `StepTimer`: rolling per-step wall-time statistics (mean/p50/p90/max,
  steps/sec, images/sec) over a sliding window, emitted through the
  MetricWriter alongside the loss scalars.
- `TraceCapture`: captures a jax.profiler trace (XLA device + host timelines,
  viewable in TensorBoard/Perfetto) for a configured window of steps, e.g.
  steps [10, 15) once compilation has settled — or ON DEMAND (ISSUE 6):
  with a trigger path configured, touching that file starts a capture of
  the next `num_steps` steps mid-run, no restart or pre-chosen
  --profile_start_step needed; each completed capture fires `on_capture`
  so the trainer can digest it in-process (utils/trace.py).

Timing caveat: step dispatch is async; host-side wall time per step is only
meaningful when something syncs the host to the device each iteration. The
trainer's per-step metric logging (float() on the loss scalars) provides that
sync, so the timer measures true steady-state step latency including data-feed
time — which is the point: a rising step time with constant device time is the
input-bound signature (the reference's own pathology, SURVEY.md §2.4 #10).
With async_services (the default) the sync is lag-by-one — step N's metrics
materialize while step N+1 runs — so each tick still follows exactly one
device-progress point per step; steady-state rates are unchanged, only the
attribution of an individual slow step can shift by one tick.

`note_host` feeds the dispatch-thread occupancy channel: the trainer stamps
the wall time its dispatch thread spends executing host-side service work
(metric materialization, submissions, inline writers) per loop iteration, and
summary() reports it as perf/host_ms_mean plus perf/dispatch_occupancy (the
fraction of step time the dispatch thread is busy with non-dispatch work —
the number the async services layer exists to drive toward zero;
tools/bench_trainer_loop.py's occupancy mode records it on/off).
"""

from __future__ import annotations

import collections
import contextlib
import os
import time
from typing import Callable, Dict, Optional


class StepTimer:
    """Sliding-window wall-time stats for the training hot loop."""

    def __init__(self, *, window: int = 50,
                 images_per_step: Optional[int] = None):
        self.window = window
        self.images_per_step = images_per_step
        self._durations: collections.deque = collections.deque(maxlen=window)
        self._host: collections.deque = collections.deque(maxlen=window)
        self._host_pending = 0.0
        self._last: Optional[float] = None

    def tick(self, now: Optional[float] = None, steps: int = 1) -> None:
        """Mark the end of `steps` training steps (a multi-step dispatch
        counts each scanned step); the first call only arms the timer."""
        now = time.perf_counter() if now is None else now
        if self._last is not None:
            per_step = (now - self._last) / max(1, steps)
            host_per_step = self._host_pending / max(1, steps)
            for _ in range(max(1, steps)):
                self._durations.append(per_step)
                self._host.append(host_per_step)
        self._host_pending = 0.0
        self._last = now

    def note_host(self, seconds: float) -> None:
        """Accumulate dispatch-thread host-work time attributed to the
        steps of the NEXT tick (call any number of times per iteration)."""
        self._host_pending += seconds

    @property
    def last_step_ms(self):
        """Most recent per-step wall ms (None before the second tick) —
        the flight recorder's per-record step time."""
        return 1e3 * self._durations[-1] if self._durations else None

    @property
    def last_host_ms(self):
        """Most recent per-step dispatch-thread host-work ms."""
        return 1e3 * self._host[-1] if self._host else None

    def __len__(self) -> int:
        return len(self._durations)

    def summary(self, prefix: str = "perf/") -> Dict[str, float]:
        """Stats over the current window; empty dict until 2+ ticks."""
        if not self._durations:
            return {}
        ds = sorted(self._durations)
        n = len(ds)
        mean = sum(ds) / n
        out = {
            f"{prefix}step_ms_mean": 1e3 * mean,
            f"{prefix}step_ms_p50": 1e3 * ds[n // 2],
            f"{prefix}step_ms_p90": 1e3 * ds[min(n - 1, (9 * n) // 10)],
            f"{prefix}step_ms_max": 1e3 * ds[-1],
            f"{prefix}steps_per_sec": 1.0 / mean if mean > 0 else 0.0,
        }
        if self.images_per_step and mean > 0:
            out[f"{prefix}images_per_sec"] = self.images_per_step / mean
        if self._host:
            host_mean = sum(self._host) / len(self._host)
            out[f"{prefix}host_ms_mean"] = 1e3 * host_mean
            out[f"{prefix}dispatch_occupancy"] = \
                host_mean / mean if mean > 0 else 0.0
        return out


class StartupProfile:
    """Named-phase wall-clock breakdown of time-to-first-step (ISSUE 5).

    The trainer brackets each startup phase (`init`, `restore`, `data`,
    `warmup`) with `phase()` and stamps `first_step()` at the first proven
    device-progress point; `summary()` is the breakdown the warm-start
    bench (tools/bench_startup.py) A/Bs cold-vs-warm. Phases are additive
    and disjoint; `total_ms` runs from construction to the first-step
    stamp, so untracked gaps (imports inside phases, loader thread spin-up)
    are visible as total minus the named parts rather than hidden.
    """

    def __init__(self) -> None:
        self._t0 = time.perf_counter()
        self._phases: Dict[str, float] = {}
        self._first_step_ms: Optional[float] = None

    def phase(self, name: str):
        """Context manager accumulating wall time under `name`."""
        @contextlib.contextmanager
        def _cm():
            t0 = time.perf_counter()
            try:
                yield self
            finally:
                self._phases[name] = self._phases.get(name, 0.0) \
                    + (time.perf_counter() - t0) * 1e3
        return _cm()

    def first_step(self) -> None:
        """Stamp the first completed training step (idempotent — the first
        call wins; later materializations are steady state)."""
        if self._first_step_ms is None:
            self._first_step_ms = (time.perf_counter() - self._t0) * 1e3

    @property
    def done(self) -> bool:
        return self._first_step_ms is not None

    def summary(self, prefix: str = "perf/startup/") -> Dict[str, float]:
        out = {f"{prefix}{k}_ms": v for k, v in self._phases.items()}
        if self._first_step_ms is not None:
            out[f"{prefix}total_ms"] = self._first_step_ms
        return out


class TraceCapture:
    """jax.profiler capture windows: one scheduled, any number triggered.

    Call maybe_start(step) before dispatching the step and maybe_stop(step)
    after it; each capture brackets exactly `num_steps` steps. Two ways a
    window opens (ISSUE 6):

    - scheduled (the PR-1 behavior): with `schedule=True` and a logdir, one
      one-shot capture starts at the first boundary >= start_step;
    - triggered: with `trigger_path` set, touching that file starts a
      capture at the next boundary (one touch, one capture; touch again
      for another). The poll is one os.stat per boundary, and only when a
      trigger path is configured, so default runs pay nothing.

    Trigger consumption is mtime-keyed, not remove-keyed: each process
    captures when it sees a NEW mtime and remembers it, and only the
    `consume` process (the trainer passes the chief) deletes the file —
    at the END of its capture, not the start. Multi-process jobs sharing
    a filesystem would otherwise race: an at-start remove wins on
    whichever boundary stats first, and every later-polling peer
    (possibly the chief, the only process that digests) silently did
    nothing. Deferring removal to capture-end leaves the file visible for
    the full num_steps window — SPMD hosts run boundaries in near-
    lockstep, so every peer's poll lands inside it. One mtime serves one
    capture per process (a touch DURING a capture is absorbed by the
    removal at its end), and an undeletable file degrades to
    once-per-touch instead of a capture loop.

    `on_capture(stop_step)` fires after each capture closes — the trainer
    hands the trace to the services worker for in-process digestion.
    Inactive (and free) when logdir is empty.
    """

    def __init__(self, logdir: str, *, start_step: int = 10,
                 num_steps: int = 5, schedule: bool = True,
                 trigger_path: str = "", consume: bool = True,
                 on_capture: Optional[Callable[[int], None]] = None):
        self.logdir = logdir
        self.start_step = start_step
        self.num_steps = num_steps
        self.trigger_path = trigger_path if logdir else ""
        self.consume = consume
        self.on_capture = on_capture
        self._active = False
        self._scheduled_done = not (schedule and logdir and num_steps > 0)
        self._stop_at = 0
        self._served_mtime: Optional[int] = None
        self._consume_pending = False
        self.captures = 0

    @property
    def active(self) -> bool:
        return self._active

    def _begin(self, step: int) -> None:
        import jax

        jax.profiler.start_trace(self.logdir)
        self._active = True
        self._stop_at = step + self.num_steps

    def maybe_start(self, step: int) -> None:
        if self._active:
            return
        if not self._scheduled_done and step >= self.start_step:
            self._scheduled_done = True
            self._begin(step)
            return
        if self.trigger_path and self.num_steps > 0:
            try:
                mtime = os.stat(self.trigger_path).st_mtime_ns
            except OSError:
                return  # absent (or unreadable): nothing to serve
            if mtime == self._served_mtime:
                return  # this touch already got its capture
            self._served_mtime = mtime
            self._consume_pending = self.consume
            self._begin(step)

    def _consume_trigger(self) -> None:
        if not self._consume_pending:
            return
        self._consume_pending = False
        try:
            os.remove(self.trigger_path)
        except OSError:
            pass  # mtime guard prevents a re-trigger loop

    def maybe_stop(self, step: int, sync=None) -> None:
        """`step` is the number of steps completed so far; pass the step's
        outputs as `sync` so the trace contains the device execution, not just
        its dispatch (the train step is pure, so only blocking on its results
        guarantees completion)."""
        if not self._active or step < self._stop_at:
            return
        import jax

        if sync is not None:
            jax.block_until_ready(sync)
        jax.profiler.stop_trace()
        self._active = False
        self.captures += 1
        self._consume_trigger()
        if self.on_capture is not None:
            self.on_capture(step)

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self._consume_trigger()
