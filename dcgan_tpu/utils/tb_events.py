"""TensorBoard-compatible event files, written natively (no TF dependency).

The reference's summary channel is TF event files — scalars, histograms, and
images merged and written by the chief's Supervisor (image_train.py:86-118,
164-178; distriubted_model.py:75-80) — which TensorBoard then renders. The
JSONL stream (utils/metrics.py) is this framework's native channel; this
module restores the *file-format* parity so the same dashboards work: it
hand-encodes the three proto messages TensorBoard reads —

    Event          { double wall_time=1; int64 step=2;
                     oneof { string file_version=3; Summary summary=5; } }
    Summary        { repeated Value value=1; }
    Summary.Value  { string tag=1; oneof { float simple_value=2;
                     Image image=4; HistogramProto histo=5; } }
    Summary.Image  { int32 height=1; int32 width=2; int32 colorspace=3;
                     bytes encoded_image_string=4; }
    HistogramProto { double min=1; max=2; num=3; sum=4; sum_squares=5;
                     repeated double bucket_limit=6, bucket=7 [packed]; }

— and frames each serialized Event as a TFRecord (length + masked CRC32C,
data/tfrecord.py, the same container the input pipeline speaks). File naming
follows the `events.out.tfevents.<time>.<host>` convention TensorBoard globs
for, and the first record is the `brain.Event:2` version header.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional, Sequence

import numpy as np

from dcgan_tpu.data.example_proto import _len_delimited, _write_varint
from dcgan_tpu.data.tfrecord import masked_crc32c

_WT_VARINT = 0
_WT_I64 = 1
_WT_I32 = 5


def _write_tag(out: bytearray, field: int, wire_type: int) -> None:
    _write_varint(out, (field << 3) | wire_type)


def _write_double(out: bytearray, field: int, value: float) -> None:
    _write_tag(out, field, _WT_I64)
    out.extend(struct.pack("<d", float(value)))


def _write_float(out: bytearray, field: int, value: float) -> None:
    _write_tag(out, field, _WT_I32)
    out.extend(struct.pack("<f", float(value)))


def _write_int(out: bytearray, field: int, value: int) -> None:
    _write_tag(out, field, _WT_VARINT)
    _write_varint(out, int(value) & ((1 << 64) - 1))


def _packed_doubles(out: bytearray, field: int,
                    values: Sequence[float]) -> None:
    payload = struct.pack(f"<{len(values)}d", *[float(v) for v in values])
    _len_delimited(out, field, payload)


def encode_scalar_event(tag: str, value: float, step: int,
                        wall_time: Optional[float] = None) -> bytes:
    sv = bytearray()
    _len_delimited(sv, 1, tag.encode("utf-8"))       # Value.tag
    _write_float(sv, 2, value)                       # Value.simple_value
    return _encode_event_with_summary(bytes(sv), step, wall_time)


def encode_histogram_event(tag: str, step: int, *,
                           bin_edges: Sequence[float],
                           bin_counts: Sequence[int],
                           minimum: float, maximum: float,
                           num: float, total: float, total_squares: float,
                           wall_time: Optional[float] = None) -> bytes:
    """Histogram from precomputed bins — exactly what activation_stats /
    histogram_summary produce (utils/metrics.py): len(bin_edges) ==
    len(bin_counts) + 1; bucket_limit[i] is bucket i's right edge."""
    if len(bin_edges) != len(bin_counts) + 1:
        raise ValueError(
            f"need len(bin_edges) == len(bin_counts)+1, got "
            f"{len(bin_edges)} edges / {len(bin_counts)} counts")
    histo = bytearray()
    _write_double(histo, 1, minimum)
    _write_double(histo, 2, maximum)
    _write_double(histo, 3, num)
    _write_double(histo, 4, total)
    _write_double(histo, 5, total_squares)
    _packed_doubles(histo, 6, list(bin_edges[1:]))   # right edges
    _packed_doubles(histo, 7, list(bin_counts))
    sv = bytearray()
    _len_delimited(sv, 1, tag.encode("utf-8"))
    _len_delimited(sv, 5, bytes(histo))              # Value.histo
    return _encode_event_with_summary(bytes(sv), step, wall_time)


def encode_image_event(tag: str, png_bytes: bytes, step: int, *,
                       height: int, width: int, colorspace: int = 3,
                       wall_time: Optional[float] = None) -> bytes:
    img = bytearray()
    _write_int(img, 1, height)
    _write_int(img, 2, width)
    _write_int(img, 3, colorspace)                   # 3 = RGB
    _len_delimited(img, 4, png_bytes)
    sv = bytearray()
    _len_delimited(sv, 1, tag.encode("utf-8"))
    _len_delimited(sv, 4, bytes(img))                # Value.image
    return _encode_event_with_summary(bytes(sv), step, wall_time)


def _encode_event_with_summary(value_msg: bytes, step: int,
                               wall_time: Optional[float]) -> bytes:
    summary = bytearray()
    _len_delimited(summary, 1, value_msg)            # Summary.value
    ev = bytearray()
    _write_double(ev, 1, time.time() if wall_time is None else wall_time)
    _write_int(ev, 2, step)                          # Event.step
    _len_delimited(ev, 5, bytes(summary))            # Event.summary
    return bytes(ev)


def encode_version_event(wall_time: Optional[float] = None) -> bytes:
    ev = bytearray()
    _write_double(ev, 1, time.time() if wall_time is None else wall_time)
    _len_delimited(ev, 3, b"brain.Event:2")          # Event.file_version
    return bytes(ev)


def png_dimensions(png_bytes: bytes) -> tuple:
    """(height, width) from a PNG IHDR header."""
    if png_bytes[:8] != b"\x89PNG\r\n\x1a\n" or png_bytes[12:16] != b"IHDR":
        raise ValueError("not a PNG")
    width, height = struct.unpack(">II", png_bytes[16:24])
    return height, width


class TBEventWriter:
    """Append TFRecord-framed Event protos to an events.out.tfevents.* file.

    The write path the reference delegated to Supervisor.summary_computed
    (image_train.py:174) — here a plain file the chief appends to, flushed per
    event batch so a running TensorBoard tails it live.
    """

    def __init__(self, logdir: str, *, filename_suffix: str = ""):
        os.makedirs(logdir, exist_ok=True)
        name = (f"events.out.tfevents.{int(time.time())}."
                f"{socket.gethostname()}{filename_suffix}")
        self.path = os.path.join(logdir, name)
        self._f = open(self.path, "ab")
        self._write_record(encode_version_event())
        self.flush()

    def _write_record(self, event_bytes: bytes) -> None:
        length = struct.pack("<Q", len(event_bytes))
        self._f.write(length)
        self._f.write(struct.pack("<I", masked_crc32c(length)))
        self._f.write(event_bytes)
        self._f.write(struct.pack("<I", masked_crc32c(event_bytes)))

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self._write_record(encode_scalar_event(tag, value, step))

    def add_histogram_bins(self, tag: str, step: int, *,
                           bin_edges: Sequence[float],
                           bin_counts: Sequence[int],
                           minimum: float, maximum: float, num: float,
                           mean: float, std: float) -> None:
        """From reduced stats (activation_stats / histogram_summary schema):
        sum and sum_squares are reconstructed as num*mean and
        num*(std^2 + mean^2)."""
        self._write_record(encode_histogram_event(
            tag, step, bin_edges=bin_edges, bin_counts=bin_counts,
            minimum=minimum, maximum=maximum, num=num, total=num * mean,
            total_squares=num * (std * std + mean * mean)))

    def add_histogram_values(self, tag: str, values, step: int,
                             bins: int = 30) -> None:
        arr = np.asarray(values, dtype=np.float64).ravel()
        counts, edges = np.histogram(arr, bins=bins)
        self._write_record(encode_histogram_event(
            tag, step, bin_edges=edges, bin_counts=counts,
            minimum=float(arr.min()) if arr.size else 0.0,
            maximum=float(arr.max()) if arr.size else 0.0,
            num=float(arr.size), total=float(arr.sum()),
            total_squares=float(np.square(arr).sum())))

    def add_image_png(self, tag: str, png_bytes: bytes, step: int) -> None:
        h, w = png_dimensions(png_bytes)
        self._write_record(encode_image_event(tag, png_bytes, step,
                                              height=h, width=w))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.flush()
        self._f.close()
