"""Sample-grid rendering: tanh-range batches -> tiled PNG.

The reference's save_images/merge/inverse_transform helpers
(image_train.py:197-219) tiled 64 generator samples into an 8x8 canvas via
scipy.misc.imsave. Same capability, numpy + PIL, any grid shape.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np


def inverse_transform(images: np.ndarray) -> np.ndarray:
    """tanh range [-1,1] -> [0,1] (image_train.py:218-219)."""
    return (np.asarray(images, dtype=np.float32) + 1.0) / 2.0


def image_grid(images: np.ndarray, grid: Tuple[int, int]) -> np.ndarray:
    """Tile [N,H,W,C] into [rows*H, cols*W, C]; N must fill the grid."""
    rows, cols = grid
    images = np.asarray(images)
    n, h, w, c = images.shape
    if n < rows * cols:
        raise ValueError(f"grid {rows}x{cols} needs {rows*cols} images, "
                         f"got {n}")
    canvas = np.zeros((rows * h, cols * w, c), dtype=images.dtype)
    for idx in range(rows * cols):
        r, col = divmod(idx, cols)
        canvas[r * h:(r + 1) * h, col * w:(col + 1) * w] = images[idx]
    return canvas


def save_png(path: str, image01: np.ndarray) -> None:
    """Save a [H,W,C] float image in [0,1] as PNG."""
    from PIL import Image

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arr = np.clip(np.asarray(image01) * 255.0, 0, 255).astype(np.uint8)
    if arr.shape[-1] == 1:
        arr = arr[..., 0]
    Image.fromarray(arr).save(path)


def save_sample_grid(path: str, images: np.ndarray,
                     grid: Tuple[int, int] = (8, 8)) -> None:
    """tanh-range samples -> tiled PNG on disk (the reference's
    `save_images(images, [8,8], './samples/train_{e}_{s}.png')`,
    image_train.py:188-190)."""
    save_png(path, image_grid(inverse_transform(images), grid))
