"""The BENCH_* env knobs -> config, shared by every measurement entrypoint.

bench.py (the driver's bench contract) and tools/step_profile.py (the
roofline profiler) must build IDENTICAL configs from the same env — a
profile row is only meaningful as the decomposition of a captured bench
row. Round 4 kept two hand-copies of the parsing and they drifted
(step_profile missed BENCH_ATTN_RES); this module is the single copy.

Knobs handled here (model-shape only — batch/steps/scan/backends stay with
their owners, they don't change WHAT is measured, only how long):

  BENCH_PRESET     named preset (presets.py) instead of the flagship
  BENCH_SIZE       output resolution (default 64)
  BENCH_ATTN=1     self-attention at 32x32 (the sagan64-attn shape)
  BENCH_SN=1       spectral norm on both nets
  BENCH_PALLAS=1   use_pallas (flash attention; BN too unless split below)
  BENCH_BN_PALLAS=0  keep BN on XLA while BENCH_PALLAS routes attention
                   through the flash kernels — the measured-best split
                   (DESIGN.md §8b)
  BENCH_ATTN_RES=R attention at feature-map resolution R on top of
                   whatever config the knobs above built (the long-context
                   knob: R=128 at BENCH_SIZE=256 is S=16384)
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple

from dcgan_tpu.config import ModelConfig, TrainConfig


def bench_model_config(env=None) -> Tuple[ModelConfig, str]:
    """(ModelConfig, label) from the non-preset BENCH_* model knobs."""
    env = os.environ if env is None else env
    mcfg = ModelConfig(
        output_size=int(env.get("BENCH_SIZE", 64)),
        use_pallas=env.get("BENCH_PALLAS", "") == "1",
        bn_pallas=(False if env.get("BENCH_BN_PALLAS") == "0" else None),
        attn_res=32 if env.get("BENCH_ATTN", "") == "1" else 0,
        spectral_norm="gd" if env.get("BENCH_SN", "") == "1" else "none")
    # the label must be injective over the knobs above — capture renders
    # group by it, and two configs sharing a label would merge into one
    # published row (the never-mix-configs contract)
    size = mcfg.output_size
    if mcfg.attn_res:
        label = f"sagan{size}-attn"
    else:
        label = "headline" if size == 64 else f"dcgan{size}"
    # BENCH_ATTN_RES is applied to the CONFIG later (apply_attn_res_override
    # runs on the full TrainConfig), but the label must reflect it NOW
    # (ADVICE r5 #2): the flash/pallas suffix below keys off whether
    # attention actually runs, and computing it pre-override mislabeled
    # e.g. BENCH_ATTN_RES=128 + BENCH_PALLAS=1 + BENCH_BN_PALLAS=0 as
    # '-pallas-xlabn' (declared "no Pallas kernel runs") though it runs
    # flash attention. The bench matrix's long-context rows name these
    # '<family>-attn<R>-{flash,dense}' (tools/capture_all.py) — match that.
    attn_res_knob = int(env.get("BENCH_ATTN_RES", "0") or 0)
    if attn_res_knob:
        label += f"-attn{attn_res_knob}"
    effective_attn = mcfg.attn_res or attn_res_knob
    if mcfg.use_pallas:
        # "-flash" = flash attention with BN split back to XLA (the
        # measured-best form); "-pallas" = both kernel families engaged;
        # "-pallas-xlabn" = the degenerate no-attention + BN-split combo
        # (no Pallas kernel actually runs — kept distinct so it can never
        # merge with the fused-BN row)
        if effective_attn and mcfg.bn_pallas is False:
            label += "-flash"
        elif mcfg.bn_pallas is False:
            label += "-pallas-xlabn"
        else:
            label += "-pallas"
    elif attn_res_knob:
        label += "-dense"  # the bench matrix's explicit dense rows
    if mcfg.spectral_norm != "none":
        label += "-sn"
    return mcfg, label


def apply_attn_res_override(cfg: TrainConfig, env=None) -> TrainConfig:
    """BENCH_ATTN_RES on top of ANY built config (preset or default).

    Only overrides use_pallas when BENCH_PALLAS is explicitly set — a
    preset's own use_pallas must survive an attn_res-only override.
    """
    env = os.environ if env is None else env
    if not env.get("BENCH_ATTN_RES"):
        return cfg
    model_kw = {"attn_res": int(env["BENCH_ATTN_RES"])}
    if "BENCH_PALLAS" in env:
        model_kw["use_pallas"] = env["BENCH_PALLAS"] == "1"
    return dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, **model_kw))
