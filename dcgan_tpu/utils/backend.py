"""Robust JAX backend acquisition for flaky tunneled-TPU transports.

Round 1's driver bench capture failed because ONE transient
``UNAVAILABLE: TPU backend setup/compile error`` from the tunneled TPU
plugin crashed bench.py at ``jax.devices()`` (BENCH_r01.json rc=1).  A
failed plugin init is frequently transient on this transport — the same
probe succeeds seconds later — but JAX leaves partially-initialized
module state behind (``xla_bridge._backends`` / the ``get_backend``
cache), so a bare second ``jax.devices()`` call can re-raise a stale
error instead of re-dialing the plugin.

``acquire_devices`` makes backend acquisition a bounded retry loop:
each failed attempt clears JAX's backend caches, sleeps with exponential
backoff, and re-dials.  Final failure raises with a structured one-line
JSON payload so the caller (bench.py, __graft_entry__) can surface a
machine-readable error instead of a bare traceback.
"""

from __future__ import annotations

import json
import os
import sys
import time


def shard_map(f, *, mesh, in_specs, out_specs, check: bool = True):
    """`jax.shard_map` across the API graduation: the modern form takes
    `check_vma`, the `jax.experimental.shard_map` form this container's
    jaxlib ships takes `check_rep` (same meaning). Every shard_map call
    site in the codebase routes through here — without the fallback the
    whole explicit-collective layer (shard_map backend, ring attention,
    per-shard Pallas BN) failed at first use on jax 0.4.37."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check)


def _reset_backend_state() -> None:
    """Clear JAX's cached (possibly poisoned) backend state."""
    try:
        from jax._src import xla_bridge

        xla_bridge._clear_backends()
        xla_bridge.get_backend.cache_clear()
    except Exception:  # pragma: no cover - best-effort across jax versions
        pass


def _platforms_config() -> str | None:
    """The effective jax_platforms setting ('' / None = auto-select)."""
    try:
        from jax._src import config as jax_config

        return jax_config.jax_platforms.value
    except Exception:  # pragma: no cover - jax internals moved
        return None


def _probe_in_subprocess(timeout: float) -> bool:
    """Dial the backend in a throwaway child first.

    Against a dead tunnel ``jax.devices()`` can HANG rather than raise
    (observed 2026-07-30: a bare devices() probe ran >90 s before being
    killed).  A hang inside a child converts to a timeout here; in-process
    it is fatal to the caller (e.g. the driver's compile check).  Returns
    True if the child dialed successfully; False if it raised (the caller's
    own in-process attempt will surface the real error).  Raises on hang.
    """
    import subprocess

    platforms = _platforms_config()
    env = dict(os.environ)
    if platforms:
        env["JAX_PLATFORMS"] = platforms  # mirror in-process config
    try:
        res = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            env=env, timeout=timeout, capture_output=True)
        return res.returncode == 0
    except subprocess.TimeoutExpired:
        raise RuntimeError(json.dumps({
            "error": "backend_hang",
            "probe_timeout_s": timeout,
        }))


def acquire_devices(attempts: int = 5, base_delay: float = 2.0,
                    max_delay: float = 30.0,
                    hang_timeout: float | None = None):
    """``jax.devices()`` with bounded retry/backoff on backend-init failure.

    Returns the device list on success.  With ``hang_timeout`` set, each
    attempt first dials the backend in a throwaway subprocess so a HUNG
    tunnel (which an in-process call cannot recover from) becomes a
    retryable failure instead of blocking the caller forever.  CPU-only
    configs skip the probe (local CPU init cannot hang).  On final failure
    raises RuntimeError whose message is a single JSON line
    ``{"error": "backend_unavailable", "attempts": N, "last_error": ...}``.
    """
    import jax

    probe = hang_timeout is not None and _platforms_config() != "cpu"
    last: Exception | None = None
    for i in range(attempts):
        try:
            if probe:
                _probe_in_subprocess(hang_timeout)
            return jax.devices()
        except Exception as e:  # UNAVAILABLE, plugin dial errors, hang probe
            last = e
            _reset_backend_state()
            if i + 1 < attempts:
                delay = min(base_delay * (2 ** i), max_delay)
                first_line = (str(e).splitlines() or [""])[0][:200]
                print(
                    f"backend init attempt {i + 1}/{attempts} failed "
                    f"({type(e).__name__}: {first_line}); "
                    f"retrying in {delay:.0f}s",
                    file=sys.stderr)
                time.sleep(delay)
    raise RuntimeError(json.dumps({
        "error": "backend_unavailable",
        "attempts": attempts,
        "last_error": str(last)[:500],
    }))
