"""Checkpoint / resume (Orbax-backed) with integrity verification.

The reference's story (SURVEY.md §3.3, §5): a tf.train.Saver over all
variables (image_train.py:103), Supervisor-driven periodic save every 600 s on
the chief only (image_train.py:123-129), and restore-latest on startup
(image_train.py:141-146,233-245). Same contract here over the train-state
pytree — params, BN running stats, both Adam states, step — with Orbax doing
sharded, async-capable array IO (each host writes its shards; no PS process
holds "the" copy).

Integrity layer (ISSUE 3): Orbax's tmp+rename protocol guarantees a step
directory is COMPLETE, not that its bytes stay GOOD — a post-rename partial
flush on power loss, a filesystem that silently truncates, or plain bit rot
all leave an integer-named dir whose restore dies mid-run with an opaque
array error, and the seed had no fallback. Here every finalized step gets a
checksum manifest (`<dir>/integrity/<step>.json`, size + crc32 per file,
written atomically via tmp+rename, chief-only); `restore_latest` verifies
the newest step against its manifest first, renames a failing step to
`<step>.corrupt` (kept for forensics, invisible to the step scanner), and
falls back to the next-newest intact checkpoint. Steps without a manifest
(legacy dirs, or a crash before the manifest landed) are trusted as before —
verification only ever ADDS protection. Manifest IO runs under
utils/retry.retry_io, so one transient host-IO error does not fail a save.

Single-pass verified restore (ISSUE 5): the seed's restore read every
checkpoint byte TWICE — a sequential checksum pass over the whole step,
then Orbax's leaf payload read of the same files. Restarts are this
trainer's normal fault response (PRs 3-4), so that double full read sat on
the critical path of every recovery. Now `restore_latest` fuses the two:
a size pre-check from stat metadata (zero payload bytes; catches
truncation, the dominant real-world corruption, before anything is
dispatched), a pre-parse checksum of the SMALL structural files (so the
native parser never consumes unverified metadata), then the checksum pass
over the bulk array chunks runs THREAD-POOLED in manifest (tree) order on
background threads while the calling thread runs the Orbax leaf
payload read right behind it — the verifier streams each file into the
page cache and the payload read is served from memory, so the step's bytes
come off storage once and restore wall-clock is max(verify, restore)
instead of their sum. The verification CONTRACT is unchanged: the restore
result is returned only after a clean checksum verdict; a failing verdict
discards it, quarantines the step, and falls back (a restore exception on
a step whose checksums FAIL is corruption evidence; on a step whose
checksums pass it propagates as before). A per-process fingerprint cache
(path, size, mtime_ns -> crc32) shares save-time manifest hashes with
restore-time verification, so a file the process itself just checksummed
is never read again.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax

Pytree = Any

INTEGRITY_DIRNAME = "integrity"

# fingerprint -> crc32 cache shared by the manifest writer and the restore
# verifier: (abspath, size, mtime_ns) identifies a file's bytes for the
# atomic-rename files Orbax and the manifest writer produce, so a file this
# process already checksummed (at save time, or an earlier verify) is not
# read again. Process-local, bounded; a changed file changes its
# fingerprint, so stale entries can never match.
_CRC_CACHE: Dict[Tuple[str, int, int], int] = {}
_CRC_CACHE_MAX = 8192

# Files at or under this size are CRC-verified BEFORE the Orbax restore is
# dispatched; only larger files fuse their verification with the payload
# read. The small files are the format's structural metadata (OCDBT
# manifests, _METADATA, sharding records) — feeding corrupt structure to
# the native parser concurrently would trade the old verify-first ordering
# for wall-clock on bytes that are cheap to verify anyway; the array chunk
# files that dominate restore IO stay fused.
_PREPARSE_VERIFY_MAX_BYTES = 1 << 20


def _file_checksum(path: str, chunk: int = 1 << 20) -> Tuple[int, int]:
    """(size, crc32) of one file, streamed."""
    size = 0
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            size += len(block)
            crc = zlib.crc32(block, crc)
    return size, crc & 0xFFFFFFFF


def _file_checksum_cached(path: str) -> Tuple[int, int, bool]:
    """(size, crc32, served_from_cache) — one disk read per file per
    fingerprint per process."""
    apath = os.path.abspath(path)
    st = os.stat(apath)
    key = (apath, st.st_size, st.st_mtime_ns)
    crc = _CRC_CACHE.get(key)
    if crc is not None:
        return st.st_size, crc, True
    size, crc = _file_checksum(apath)
    if len(_CRC_CACHE) >= _CRC_CACHE_MAX:
        _CRC_CACHE.clear()
    # fingerprint with the POST-read stat only if unchanged mid-read
    st2 = os.stat(apath)
    if (st2.st_size, st2.st_mtime_ns) == (st.st_size, st.st_mtime_ns):
        _CRC_CACHE[key] = crc
    return size, crc, False


def _dir_checksums(step_dir: str) -> Dict[str, Dict[str, int]]:
    """{relative path: {size, crc32}} over every regular file under
    `step_dir` (hashes land in the fingerprint cache, so a same-process
    restore verifies them without re-reading)."""
    out: Dict[str, Dict[str, int]] = {}
    for root, _, files in os.walk(step_dir):
        for name in sorted(files):
            path = os.path.join(root, name)
            rel = os.path.relpath(path, step_dir)
            size, crc, _ = _file_checksum_cached(path)
            out[rel] = {"size": size, "crc32": crc}
    return out


_IDENTITY_COPY = None


def _rebase_onto_xla_buffers(tree: Pytree) -> Pytree:
    """Rebase a just-restored tree onto fresh XLA-owned buffers via one
    non-donating jitted identity pass (the rollback.device_copy idiom).

    Workaround for a jaxlib 0.4.37 CPU interaction the warm-start work
    surfaced: DONATING a tensorstore-backed buffer (what Orbax restore
    returns) into an executable DESERIALIZED from the persistent
    compilation cache corrupts the heap (malloc_consolidate/SIGSEGV a few
    dispatches later). Reading such buffers is fine — only donation is
    broken — so one identity copy whose outputs are ordinary XLA
    allocations makes the restored state safe to feed the trainer's
    donated step programs. Applied only when the persistent cache is
    configured (the only regime that deserializes executables); costs one
    device-side copy of the state, value- and sharding-preserving, and is
    a mesh-consistent per-shard program under multi-host (every process
    dispatches it at the same point, like the rollback snapshot copy)."""
    global _IDENTITY_COPY
    if _IDENTITY_COPY is None:
        _IDENTITY_COPY = jax.jit(
            lambda t: jax.tree_util.tree_map(lambda a: a + 0, t))
    return _IDENTITY_COPY(tree)


def persistent_cache_active() -> bool:
    """Whether JAX's persistent compilation cache is configured — the only
    regime that runs DESERIALIZED executables, where donated non-XLA-owned
    buffers are unsafe (see _rebase_onto_xla_buffers; train/rollback.py
    applies the same rebase to its host-snapshot restore path)."""
    try:
        return bool(jax.config.jax_compilation_cache_dir)
    except AttributeError:  # future jax: config knob renamed/removed
        return False


def owned_host_copy(tree: Pytree) -> Pytree:
    """`jax.device_get` whose result is safe to hold across donated
    dispatches when the persistent cache is active.

    On CPU, device_get returns zero-copy numpy VIEWS of the XLA buffers.
    Executables DESERIALIZED from the persistent compilation cache donate
    those buffers in place even while a view is alive (jaxlib 0.4.37 —
    fresh-compiled executables copy instead when external references
    exist), so a host "snapshot" would silently track the live state. One
    owned copy per leaf breaks the aliasing; skipped when the cache is off
    (no deserialized executables, the views behave). The ONE site holding
    this workaround's knowledge — the rollback snapshot and the trainer's
    multi-process histogram capture both call it."""
    host = jax.device_get(tree)
    if not persistent_cache_active():
        return host
    import numpy as np

    return jax.tree_util.tree_map(lambda x: np.array(x, copy=True), host)


def has_restorable_checkpoint(directory: str) -> bool:
    """True iff `directory` holds at least one completed Orbax step dir.

    Cheap filesystem check — no CheckpointManager construction (which
    would spin up async machinery and create the directory as a side
    effect). Completed Orbax steps are integer-named subdirectories;
    in-flight temp dirs carry an `.orbax-checkpoint-tmp` suffix and fail
    the digit test. Gates config.json adoption in the CLI: a stale config
    from a run that died before its first save must not claim the
    directory (mirror of the trainer's `latest_step() is not None` gate
    on the arch-mismatch check).
    """
    import os

    try:
        entries = os.listdir(directory)
    except OSError:
        return False
    return any(name.isdigit() and os.path.isdir(os.path.join(directory, name))
               for name in entries)


class Checkpointer:
    """save / maybe_save (time-throttled) / restore_latest over a state pytree.

    Only the chief process drives the save cadence (is_chief gating lives in
    the trainer, matching the reference's chief-only Supervisor saver), but
    all processes must enter save() together for multi-host array gather.
    """

    def __init__(self, directory: str, *, save_interval_secs: float = 600.0,
                 save_interval_steps: int = 1000, max_to_keep: int = 5,
                 async_save: bool = True):
        import os

        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self._mgr_options = dict(max_to_keep=max_to_keep,
                                 enable_async_checkpointing=async_save)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(**self._mgr_options))
        self.save_interval_secs = save_interval_secs
        self.save_interval_steps = save_interval_steps
        self._next_save = time.time() + save_interval_secs
        # reshard accounting of the last restore (ISSUE 12): None when the
        # same-topology path ran (the default; sidecar present but not
        # needed), else {"reshard_ms", "host_stage", "saved_processes",
        # "saved_devices", "leaves"} — the trainer's elastic/* row and
        # tools/bench_startup.py's cross-topology arm read it
        self.last_reshard: Optional[Dict[str, float]] = None
        # sharding sidecars captured at save() time, written (chief-only)
        # once their step finalizes — see _stash_sidecar
        self._pending_sidecars: Dict[int, Dict] = {}
        # progressive-schedule phase tag (ISSUE 15): the trainer sets this
        # dict ({"phase": i, "resolution": r}) at start and on every phase
        # switch; saves fold it into the sharding sidecar so a resume can
        # cross-check which phase's tree a checkpoint carries. None (the
        # default) leaves the sidecar schema untouched — parity.
        self.progressive_tag: Optional[Dict[str, int]] = None
        # checksum-pass parallelism for the fused verified restore; the
        # env override exists for hosts whose storage saturates earlier
        self.verify_threads = max(1, int(os.environ.get(
            "DCGAN_CKPT_VERIFY_THREADS", "4")))
        # {"files","bytes_read","bytes_cached","verify_ms","restore_ms"}
        # of the last successful VERIFIED restore (None when the restore
        # was unverified or never happened) — the trainer's startup report
        # and tools/bench_startup.py read it
        self.last_restore_stats: Optional[Dict[str, float]] = None

    def save(self, step: int, state: Pytree, *, force: bool = False) -> None:
        self._mgr.save(int(step),
                       args=self._ocp.args.StandardSave(state),
                       force=force)
        # the sharding sidecar (ISSUE 12) is derived from the live tree's
        # NamedShardings NOW (the arrays may be donated away by the next
        # step program) and written once the step FINALIZES, beside its
        # integrity manifest — an in-flight async step has no dir yet and
        # the stale-pruner must keep treating dirless files as garbage
        self._stash_sidecar(step, state)
        # manifest any step finalized by now (with async saves that is the
        # PREVIOUS save — this step's manifest lands on the next call/wait)
        self._write_pending_manifests()

    # -- sharding sidecar (ISSUE 12) -----------------------------------------

    def _stash_sidecar(self, step: int, state: Pytree) -> None:
        """Capture the saving topology for `step`: logical per-leaf specs
        + mesh axis names/sizes + process count (elastic/sidecar.py
        schema). Chief-only like the manifests; host/np trees (no
        NamedShardings) simply get none — absence restores exactly as
        before, same-topology."""
        if jax.process_index() != 0:
            return
        from dcgan_tpu.elastic import sidecar as _sidecar

        payload = _sidecar.build_payload(state)
        if payload is not None:
            tag = getattr(self, "progressive_tag", None)
            if tag:
                # which progressive phase's tree this step carries
                # (ISSUE 15); key absent in fixed-resolution runs
                payload["progressive"] = dict(tag)
            self._pending_sidecars[int(step)] = payload

    def _write_sidecar(self, step: int, payload: Dict) -> None:
        from dcgan_tpu.elastic import sidecar as _sidecar
        from dcgan_tpu.utils.retry import retry_io

        path = _sidecar.sidecar_path(self.directory, step)

        def _write():
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
            os.replace(tmp, path)

        retry_io(_write, tag="ckpt-sidecar")

    # -- integrity manifests -------------------------------------------------

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, INTEGRITY_DIRNAME,
                            f"{int(step)}.json")

    def _finalized_steps(self) -> list:
        """Integer-named step dirs on disk, newest first. Orbax's tmp+rename
        finalize means an integer-named dir is complete; in-flight temp dirs
        carry a suffix and fail the digit test (same contract as
        has_restorable_checkpoint)."""
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return []
        return sorted(
            (int(n) for n in entries if n.isdigit()
             and os.path.isdir(os.path.join(self.directory, n))),
            reverse=True)

    def _write_pending_manifests(self) -> None:
        """Write the checksum manifest for every finalized step that lacks
        one. Chief-only (one writer per shared filesystem); manifest IO —
        not the Orbax array writes — retries transient OSErrors with
        jittered backoff (utils/retry)."""
        if jax.process_index() != 0:
            return
        from dcgan_tpu.utils.retry import retry_io

        # prune manifests AND sharding sidecars whose step Orbax retention
        # already deleted (keep both beside a .corrupt dir — forensics)
        int_dir = os.path.join(self.directory, INTEGRITY_DIRNAME)

        def _stem(name: str) -> str:
            if name.endswith(".sharding.json"):
                return name[:-len(".sharding.json")]
            return name[:-5] if name.endswith(".json") else ""

        try:
            stale = [n for n in os.listdir(int_dir)
                     if _stem(n).isdigit()
                     and not os.path.exists(
                         os.path.join(self.directory, _stem(n)))
                     and not os.path.exists(
                         os.path.join(self.directory,
                                      _stem(n) + ".corrupt"))]
        except OSError:
            stale = []
        for name in stale:
            try:
                os.remove(os.path.join(int_dir, name))
            except OSError:
                pass

        for step in self._finalized_steps():
            # the step's stashed sharding sidecar lands with (before) its
            # manifest — both describe a now-durable step
            payload = self._pending_sidecars.pop(step, None)
            if payload is not None:
                self._write_sidecar(step, payload)
            path = self._manifest_path(step)
            if os.path.exists(path):
                continue
            step_dir = os.path.join(self.directory, str(step))

            def _write(step=step, path=path, step_dir=step_dir):
                manifest = {"step": step, "files": _dir_checksums(step_dir)}
                os.makedirs(os.path.dirname(path), exist_ok=True)
                tmp = f"{path}.{os.getpid()}.tmp"
                with open(tmp, "w") as f:
                    json.dump(manifest, f, indent=1, sort_keys=True)
                os.replace(tmp, path)

            retry_io(_write, tag="ckpt-manifest")

    def _manifest_files(self, step: int
                        ) -> Tuple[Optional[Dict[str, Dict[str, int]]], str]:
        """The step's manifest file table, or (None, why) when the step
        restores UNVERIFIED (no manifest: legacy dirs and crash-before-
        manifest saves keep the seed's restore semantics; an unreadable
        manifest is a manifest-side problem, not evidence against the
        arrays). Manifest IO runs under retry_io — a verification failure
        permanently condemns a step, so transient blips get their bounded
        retries before any verdict."""
        from dcgan_tpu.utils.retry import retry_io

        path = self._manifest_path(step)
        if not os.path.exists(path):
            return None, "no integrity manifest (unverified)"

        def _read_manifest():
            with open(path) as f:
                return json.load(f)

        try:
            return retry_io(_read_manifest, tag="ckpt-verify")["files"], \
                "manifest"
        except (OSError, ValueError, KeyError) as e:
            return None, f"unreadable integrity manifest ({e})"

    def _stat_precheck(self, step: int,
                       files: Dict[str, Dict[str, int]]) -> Optional[str]:
        """Metadata-only screen, tree order: a manifest-listed file that is
        missing or the wrong SIZE is deterministic corruption (truncation /
        deletion — the dominant real-world classes), caught from stat calls
        before a single payload byte is read or any restore collective is
        dispatched. Returns the failure reason or None.

        Retry semantics mirror PR 4's verify fix: a missing file condemns
        immediately (deterministic), but any other stat OSError — an NFS
        hiccup, a momentary EIO — gets retry_io's bounded retries before
        the verdict, because a failing screen permanently quarantines the
        step."""
        from dcgan_tpu.utils.retry import retry_io

        step_dir = os.path.join(self.directory, str(step))
        for rel, rec in files.items():
            fpath = os.path.join(step_dir, rel)
            try:
                size = os.stat(fpath).st_size
            except FileNotFoundError:
                return f"missing file {rel!r}"
            except OSError:
                try:
                    size = retry_io(lambda p=fpath: os.stat(p).st_size,
                                    tag="ckpt-verify")
                except OSError as e:
                    return f"unreadable file {rel!r} ({e})"
            if size != rec["size"]:
                return (f"size mismatch on {rel!r} "
                        f"({size} != {rec['size']})")
        return None

    def _crc_pass(self, step: int, files: Dict[str, Dict[str, int]]
                  ) -> Tuple[bool, str, Dict[str, float]]:
        """Thread-pooled checksum pass over the manifest's files in tree
        (sorted-path) order: (ok, why, stats). Reads stream through the
        fingerprint cache, so bytes this process already hashed (the save-
        time manifest write, an earlier verify) are not re-read; fresh
        reads run under retry_io so only an error that survives the bounded
        retries counts as evidence against the bytes. The verdict reports
        the FIRST failing file in tree order — deterministic across the
        pool's scheduling."""
        from concurrent.futures import ThreadPoolExecutor

        from dcgan_tpu.utils.retry import retry_io

        step_dir = os.path.join(self.directory, str(step))
        t0 = time.perf_counter()

        def _one(item):
            rel, rec = item
            fpath = os.path.join(step_dir, rel)
            try:
                size, crc, cached = retry_io(
                    lambda p=fpath: _file_checksum_cached(p),
                    tag="ckpt-verify")
            except FileNotFoundError:
                return f"missing file {rel!r}", 0, 0
            except OSError as e:
                return f"unreadable file {rel!r} ({e})", 0, 0
            if size != rec["size"]:
                return (f"size mismatch on {rel!r} "
                        f"({size} != {rec['size']})"), 0, 0
            if crc != rec["crc32"]:
                return f"crc32 mismatch on {rel!r}", 0, 0
            return None, (0 if cached else size), (size if cached else 0)

        items = list(files.items())
        n = min(self.verify_threads, max(1, len(items)))
        if n > 1:
            with ThreadPoolExecutor(max_workers=n,
                                    thread_name_prefix="ckpt-crc") as pool:
                results = list(pool.map(_one, items))
        else:
            results = [_one(i) for i in items]
        stats = {
            "files": float(len(items)),
            "bytes_read": float(sum(r[1] for r in results)),
            "bytes_cached": float(sum(r[2] for r in results)),
            "verify_ms": (time.perf_counter() - t0) * 1e3,
        }
        for why, _, _ in results:
            if why is not None:
                return False, why, stats
        return True, "verified", stats

    def _verify_step(self, step: int) -> Tuple[bool, str]:
        """Check a finalized step dir against its manifest: metadata screen
        first (missing/truncated files condemn with zero payload reads),
        then the thread-pooled checksum pass. No manifest = trusted —
        verification only ever adds protection."""
        files, why = self._manifest_files(step)
        if files is None:
            return True, why
        bad = self._stat_precheck(step, files)
        if bad is not None:
            return False, bad
        ok, why, _ = self._crc_pass(step, files)
        return ok, why

    def _mark_corrupt(self, step: int, why: str) -> None:
        """Rename a failing step dir to `<step>.corrupt` (chief-only): the
        step scanner and Orbax both ignore non-integer names, the bytes stay
        on disk for forensics, and the manifest stays beside it."""
        from dcgan_tpu.utils.retry import retry_io

        src = os.path.join(self.directory, str(step))
        dst = f"{src}.corrupt"
        print(f"[dcgan_tpu] checkpoint step {step} failed integrity check "
              f"({why}) — marking {dst} and falling back to the newest "
              f"intact checkpoint", flush=True)
        if jax.process_index() == 0 and os.path.isdir(src):
            # retried (DCG006): a transient rename failure here would
            # abort the very fallback that exists to survive bad bytes
            retry_io(lambda: os.replace(src, dst), tag="ckpt-corrupt-mark")
        try:
            self._mgr.reload()  # drop the manager's cached step metadata
        except Exception:  # older orbax without reload(): rebuild instead
            self._mgr.close()
            self._mgr = self._ocp.CheckpointManager(
                self.directory,
                options=self._ocp.CheckpointManagerOptions(
                    **self._mgr_options))

    def maybe_save(self, step: int, state: Pytree) -> bool:
        """Throttled save — the Supervisor's save_model_secs=600 cadence
        (image_train.py:129).

        Single-process: wall-clock throttle. Multi-host: save() is a
        collective, so the decision must be identical on every process —
        per-process clocks are not, so the cadence switches to the
        deterministic step interval.
        """
        if jax.process_count() > 1:
            if step % self.save_interval_steps != 0:
                return False
        else:
            now = time.time()
            if now < self._next_save:
                return False
            self._next_save = now + self.save_interval_secs
        self.save(step, state)
        return True

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def delete_steps_after(self, step: int) -> list:
        """Remove checkpoints NEWER than `step`; returns the steps dropped.

        Rollback support (train/rollback.py): a save taken between the
        last-good snapshot and the gate trip may embed the divergence the
        gate only caught later (the gate runs every nan_check_steps, not
        every step), and a replayed save at the same step number would
        collide with the stale dir.

        Multi-host (ISSUE 4): every process calls this at the same
        consensus-agreed rollback, but only the chief touches the shared
        filesystem (one deleter, like the manifest writer); the others
        wait at a named barrier so no process can dispatch a replayed save
        into a directory the chief is still deleting, then every manager
        drops its cached step metadata. The in-flight-save wait runs FIRST
        and the barrier is unconditional: the disk listing below is only
        symmetric across processes after every process has finished (and
        Orbax has committed) its async save work — a `dropped`-gated
        barrier could be entered by the process that listed after the
        commit rename and skipped by the one that listed before it."""
        multi = jax.process_count() > 1
        self._mgr.wait_until_finished()  # never race an in-flight save
        dropped = [s for s in self._finalized_steps() if s > step]
        delete_err = None
        if dropped and jax.process_index() == 0:
            import shutil

            from dcgan_tpu.utils.retry import retry_io

            for s in dropped:
                if multi:
                    # raw removal: CheckpointManager.delete is not a
                    # collective contract across orbax versions, and the
                    # reload below resyncs every manager anyway. A FAILED
                    # removal must be loud (matching mgr.delete's raise on
                    # the single-process path): a surviving poisoned-window
                    # dir is exactly the stale-collision / unverified-
                    # restore hazard this method exists to prevent. The
                    # failure is RECORDED, not raised here — the chief must
                    # still reach the verdict allgather below, or the
                    # non-chief processes deadlock in it.
                    try:
                        retry_io(lambda p=os.path.join(
                            self.directory, str(s)): shutil.rmtree(p),
                            tag="ckpt-delete")
                    except OSError as e:
                        delete_err = e
                        break
                else:
                    self._mgr.delete(s)
                # the manifest must die with the step: a REPLAYED save at
                # this step number writes different bytes, and verifying
                # them against the stale manifest would falsely mark the
                # good checkpoint .corrupt at the next restore (the
                # sharding sidecar likewise — a replayed save re-records
                # its topology fresh)
                from dcgan_tpu.elastic import sidecar as _sidecar

                for stale_path in (self._manifest_path(s),
                                   _sidecar.sidecar_path(self.directory,
                                                         s)):
                    try:
                        os.remove(stale_path)
                    except OSError:
                        pass
        if multi:
            import numpy as np
            from jax.experimental import multihost_utils

            # one allgather doubles as the barrier (no process passes this
            # point until all have entered) AND carries the chief's
            # deletion verdict, so success/failure is decided identically
            # on every process — an asymmetric raise above a collective is
            # a deadlock generator
            failed = np.asarray(multihost_utils.process_allgather(
                np.asarray(1 if delete_err is not None else 0,
                           np.int32))).reshape(-1)
            try:
                self._mgr.reload()
            except Exception:  # older orbax: rebuild instead
                self._mgr.close()
                self._mgr = self._ocp.CheckpointManager(
                    self.directory,
                    options=self._ocp.CheckpointManagerOptions(
                        **self._mgr_options))
            if failed.any():
                raise RuntimeError(
                    f"rollback checkpoint cleanup failed on the chief "
                    f"(steps {dropped}): aborting on every process rather "
                    f"than replaying into a stale step dir"
                ) from delete_err
        return dropped

    def restore_latest(self, target_state: Pytree) -> Optional[Pytree]:
        """Restore the newest INTACT checkpoint into the shape/sharding of
        `target_state` (pass the freshly-initialized state); None if no
        checkpoint exists — the reference's load() boolean contract
        (image_train.py:233-245).

        Candidates are tried newest-first: a step whose integrity manifest
        disagrees with the bytes on disk is renamed `<step>.corrupt` and the
        next-newest step is tried — a truncated latest checkpoint costs the
        run its most recent save interval, not the whole run. Steps without
        a manifest restore exactly as before (unverified), and restore-time
        exceptions still propagate — only MANIFEST-proven corruption
        quarantines a step, so a tree/shape mismatch can never silently
        retire good checkpoints.

        SINGLE-PASS (ISSUE 5): bulk verification is fused with the restore
        instead of preceding it. The stat pre-check screens out truncation
        with zero payload reads; small files (the format's structural
        metadata) CRC-verify before the native parser sees them; then the
        thread-pooled checksum pass over the bulk array chunks runs on
        background threads while THIS thread (the one that must own the
        multi-host restore collective) runs Orbax's leaf payload read of
        the same files — bytes come off storage once (the verifier's read
        warms the page cache the payload read is served from) and restore
        wall-clock is max(verify, restore) instead of their sum. The
        restored tree is RETURNED only after a clean checksum verdict; a
        failing verdict discards it and falls back, and a restore
        exception is re-raised only when the checksums PASSED (on a step
        whose checksums fail, the exception is just corruption showing up
        twice). Verdicts stay deterministic across processes — every
        process hashes the same shared-filesystem bytes — so the
        quarantine/fallback branch is taken symmetrically, like before.

        ELASTIC (ISSUE 12): each candidate step's sharding sidecar
        (written at save time beside the integrity manifest) names the
        SAVING topology; when it differs from the target tree's — a
        preempted 32-chip job resuming as 16, a 2-process save resumed by
        1 — the restore RESHARDS instead of failing deep inside the array
        reader. Same process count: the read itself is directed at the
        current NamedShardings (each process pulls exactly its new
        shards). Different process count: the arrays restore host-side
        (numpy, full arrays, no device staging copy) and
        `make_array_from_callback` uploads each device's shard
        (elastic/reshard.py). Verification, quarantine fallback, and the
        donation-safety rebase are IDENTICAL on both paths; a missing or
        unreadable sidecar — or a matching topology — takes the exact
        pre-elastic path, so same-topology restores are byte-identical in
        behavior (the parity contract). `last_reshard` records the event.
        """
        from dcgan_tpu.elastic import reshard as _reshard
        from dcgan_tpu.elastic import sidecar as _sidecar

        self.last_reshard = None
        abstract = _reshard.device_abstract(target_state)
        for step in self._finalized_steps():
            # topology decision first: zero payload bytes move before the
            # reshard-vs-direct choice is made. The choice itself is
            # elastic/sidecar.restore_decision — shared with the protocol
            # simulator (ISSUE 14), which replays it under a virtual
            # process census and lockstep-audits the branch
            payload = _sidecar.read(self.directory, step)
            path_kind, mismatch = _sidecar.restore_decision(payload,
                                                            target_state)
            step_abstract, assemble, reshard_info = abstract, None, None
            if mismatch is not None:
                saved_procs = int(payload.get("process_count", 1))
                saved_devices = 1
                for s in payload["mesh"]["sizes"]:
                    saved_devices *= int(s)
                host_stage = path_kind == "host"
                if host_stage:
                    step_abstract = _reshard.host_abstract(target_state)
                    assemble = lambda t: _reshard.put_host_tree(
                        t, target_state)
                print(f"[dcgan_tpu] cross-topology restore of step {step}: "
                      f"{mismatch} — resharding via the sharding sidecar "
                      f"({'host-staged' if host_stage else 'device-read'} "
                      f"path)", flush=True)
                reshard_info = {
                    "host_stage": 1.0 if host_stage else 0.0,
                    "saved_processes": float(saved_procs),
                    "saved_devices": float(saved_devices),
                    "leaves": float(len(jax.tree_util.tree_leaves(
                        target_state))),
                }
            files, why = self._manifest_files(step)
            if files is None:
                # unverified restore (legacy/unreadable-manifest step):
                # exactly the seed's semantics, exceptions propagate
                t0 = time.perf_counter()
                restored = self._mgr.restore(
                    step,
                    args=self._ocp.args.StandardRestore(step_abstract))
                if assemble is not None:
                    restored = assemble(restored)
                if reshard_info is not None:
                    reshard_info["reshard_ms"] = \
                        (time.perf_counter() - t0) * 1e3
                    self.last_reshard = reshard_info
                return _rebase_onto_xla_buffers(restored) \
                    if persistent_cache_active() else restored
            bad = self._stat_precheck(step, files)
            if bad is not None:
                self._mark_corrupt(step, bad)
                continue
            # structural metadata (small files: OCDBT manifests, _METADATA,
            # sharding records) verifies BEFORE the native parser ever sees
            # it — only the bulk array chunks, which dominate restore IO,
            # fuse their verification with the payload read
            small = {r: rec for r, rec in files.items()
                     if rec["size"] <= _PREPARSE_VERIFY_MAX_BYTES}
            large = {r: rec for r, rec in files.items()
                     if rec["size"] > _PREPARSE_VERIFY_MAX_BYTES}
            ok, vwhy, stats = self._crc_pass(step, small)
            if not ok:
                self._mark_corrupt(step, vwhy)
                continue
            verdict: List = []
            verifier = None
            if large:
                verifier = threading.Thread(
                    target=lambda: verdict.extend(
                        self._crc_pass(step, large)),
                    name="ckpt-verify", daemon=True)
            t0 = time.perf_counter()
            if verifier is not None:
                verifier.start()
            restored, restore_err = None, None
            try:
                restored = self._mgr.restore(
                    step,
                    args=self._ocp.args.StandardRestore(step_abstract))
                if assemble is not None:
                    # host-staged reshard: upload each device's shard of
                    # the target sharding from the numpy staging tree —
                    # part of the restore wall-clock it replaces
                    restored = assemble(restored)
            except Exception as e:  # verdict decides if this is corruption
                restore_err = e
            restore_ms = (time.perf_counter() - t0) * 1e3
            if verifier is not None:
                verifier.join()
                if not verdict:  # verifier died before producing a verdict
                    if restore_err is not None:
                        raise restore_err
                    raise RuntimeError(
                        f"checkpoint verifier died without a verdict on "
                        f"step {step}")
                ok, vwhy, big_stats = verdict
                for k in ("files", "bytes_read", "bytes_cached",
                          "verify_ms"):
                    stats[k] += big_stats[k]
                if not ok:
                    restored = None  # corrupt bytes — never hand them out
                    self._mark_corrupt(step, vwhy)
                    continue
            if restore_err is not None:
                raise restore_err
            stats["restore_ms"] = restore_ms
            self.last_restore_stats = stats
            if reshard_info is not None:
                reshard_info["reshard_ms"] = restore_ms
                self.last_reshard = reshard_info
            return _rebase_onto_xla_buffers(restored) \
                if persistent_cache_active() else restored
        return None

    def wait(self) -> None:
        """Block until async saves are durable (and manifest them)."""
        self._mgr.wait_until_finished()
        self._write_pending_manifests()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._write_pending_manifests()
        self._mgr.close()
