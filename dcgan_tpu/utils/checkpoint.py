"""Checkpoint / resume (Orbax-backed) with integrity verification.

The reference's story (SURVEY.md §3.3, §5): a tf.train.Saver over all
variables (image_train.py:103), Supervisor-driven periodic save every 600 s on
the chief only (image_train.py:123-129), and restore-latest on startup
(image_train.py:141-146,233-245). Same contract here over the train-state
pytree — params, BN running stats, both Adam states, step — with Orbax doing
sharded, async-capable array IO (each host writes its shards; no PS process
holds "the" copy).

Integrity layer (ISSUE 3): Orbax's tmp+rename protocol guarantees a step
directory is COMPLETE, not that its bytes stay GOOD — a post-rename partial
flush on power loss, a filesystem that silently truncates, or plain bit rot
all leave an integer-named dir whose restore dies mid-run with an opaque
array error, and the seed had no fallback. Here every finalized step gets a
checksum manifest (`<dir>/integrity/<step>.json`, size + crc32 per file,
written atomically via tmp+rename, chief-only); `restore_latest` verifies
the newest step against its manifest first, renames a failing step to
`<step>.corrupt` (kept for forensics, invisible to the step scanner), and
falls back to the next-newest intact checkpoint. Steps without a manifest
(legacy dirs, or a crash before the manifest landed) are trusted as before —
verification only ever ADDS protection. Manifest IO runs under
utils/retry.retry_io, so one transient host-IO error does not fail a save.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Any, Dict, Optional, Tuple

import jax

Pytree = Any

INTEGRITY_DIRNAME = "integrity"


def _file_checksum(path: str, chunk: int = 1 << 20) -> Tuple[int, int]:
    """(size, crc32) of one file, streamed."""
    size = 0
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            size += len(block)
            crc = zlib.crc32(block, crc)
    return size, crc & 0xFFFFFFFF


def _dir_checksums(step_dir: str) -> Dict[str, Dict[str, int]]:
    """{relative path: {size, crc32}} over every regular file under
    `step_dir`."""
    out: Dict[str, Dict[str, int]] = {}
    for root, _, files in os.walk(step_dir):
        for name in sorted(files):
            path = os.path.join(root, name)
            rel = os.path.relpath(path, step_dir)
            size, crc = _file_checksum(path)
            out[rel] = {"size": size, "crc32": crc}
    return out


def has_restorable_checkpoint(directory: str) -> bool:
    """True iff `directory` holds at least one completed Orbax step dir.

    Cheap filesystem check — no CheckpointManager construction (which
    would spin up async machinery and create the directory as a side
    effect). Completed Orbax steps are integer-named subdirectories;
    in-flight temp dirs carry an `.orbax-checkpoint-tmp` suffix and fail
    the digit test. Gates config.json adoption in the CLI: a stale config
    from a run that died before its first save must not claim the
    directory (mirror of the trainer's `latest_step() is not None` gate
    on the arch-mismatch check).
    """
    import os

    try:
        entries = os.listdir(directory)
    except OSError:
        return False
    return any(name.isdigit() and os.path.isdir(os.path.join(directory, name))
               for name in entries)


class Checkpointer:
    """save / maybe_save (time-throttled) / restore_latest over a state pytree.

    Only the chief process drives the save cadence (is_chief gating lives in
    the trainer, matching the reference's chief-only Supervisor saver), but
    all processes must enter save() together for multi-host array gather.
    """

    def __init__(self, directory: str, *, save_interval_secs: float = 600.0,
                 save_interval_steps: int = 1000, max_to_keep: int = 5,
                 async_save: bool = True):
        import os

        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self._mgr_options = dict(max_to_keep=max_to_keep,
                                 enable_async_checkpointing=async_save)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(**self._mgr_options))
        self.save_interval_secs = save_interval_secs
        self.save_interval_steps = save_interval_steps
        self._next_save = time.time() + save_interval_secs

    def save(self, step: int, state: Pytree, *, force: bool = False) -> None:
        self._mgr.save(int(step),
                       args=self._ocp.args.StandardSave(state),
                       force=force)
        # manifest any step finalized by now (with async saves that is the
        # PREVIOUS save — this step's manifest lands on the next call/wait)
        self._write_pending_manifests()

    # -- integrity manifests -------------------------------------------------

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, INTEGRITY_DIRNAME,
                            f"{int(step)}.json")

    def _finalized_steps(self) -> list:
        """Integer-named step dirs on disk, newest first. Orbax's tmp+rename
        finalize means an integer-named dir is complete; in-flight temp dirs
        carry a suffix and fail the digit test (same contract as
        has_restorable_checkpoint)."""
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return []
        return sorted(
            (int(n) for n in entries if n.isdigit()
             and os.path.isdir(os.path.join(self.directory, n))),
            reverse=True)

    def _write_pending_manifests(self) -> None:
        """Write the checksum manifest for every finalized step that lacks
        one. Chief-only (one writer per shared filesystem); manifest IO —
        not the Orbax array writes — retries transient OSErrors with
        jittered backoff (utils/retry)."""
        if jax.process_index() != 0:
            return
        from dcgan_tpu.utils.retry import retry_io

        # prune manifests whose step Orbax retention already deleted (keep
        # the manifest beside a .corrupt dir — forensics)
        int_dir = os.path.join(self.directory, INTEGRITY_DIRNAME)
        try:
            stale = [n for n in os.listdir(int_dir)
                     if n.endswith(".json") and n[:-5].isdigit()
                     and not os.path.exists(
                         os.path.join(self.directory, n[:-5]))
                     and not os.path.exists(
                         os.path.join(self.directory, n[:-5] + ".corrupt"))]
        except OSError:
            stale = []
        for name in stale:
            try:
                os.remove(os.path.join(int_dir, name))
            except OSError:
                pass

        for step in self._finalized_steps():
            path = self._manifest_path(step)
            if os.path.exists(path):
                continue
            step_dir = os.path.join(self.directory, str(step))

            def _write(step=step, path=path, step_dir=step_dir):
                manifest = {"step": step, "files": _dir_checksums(step_dir)}
                os.makedirs(os.path.dirname(path), exist_ok=True)
                tmp = f"{path}.{os.getpid()}.tmp"
                with open(tmp, "w") as f:
                    json.dump(manifest, f, indent=1, sort_keys=True)
                os.replace(tmp, path)

            retry_io(_write, tag="ckpt-manifest")

    def _verify_step(self, step: int) -> Tuple[bool, str]:
        """Check a finalized step dir against its manifest. No manifest =
        trusted (legacy dirs and crash-before-manifest saves keep the seed's
        restore semantics — verification only ever adds protection).

        Every read here runs under utils/retry.retry_io: a verification
        FAILURE permanently condemns the step (`.corrupt` rename), so a
        transient IO blip — an NFS hiccup mid-checksum, a momentarily
        unreadable manifest — must get its bounded retries before the
        verdict. Only an error that SURVIVES the retries counts as
        evidence against the bytes."""
        from dcgan_tpu.utils.retry import retry_io

        path = self._manifest_path(step)
        if not os.path.exists(path):
            return True, "no integrity manifest (unverified)"

        def _read_manifest():
            with open(path) as f:
                return json.load(f)

        try:
            manifest = retry_io(_read_manifest, tag="ckpt-verify")
            files = manifest["files"]
        except (OSError, ValueError, KeyError) as e:
            # an unreadable manifest is a manifest-side problem, not
            # evidence against the arrays — trust the step, say so
            return True, f"unreadable integrity manifest ({e})"
        step_dir = os.path.join(self.directory, str(step))
        for rel, rec in files.items():
            fpath = os.path.join(step_dir, rel)
            if not os.path.exists(fpath):
                # a manifest-listed file that is GONE is deterministic
                # corruption (truncation/deletion) — condemn immediately
                # rather than retry-with-backoff a FileNotFoundError and
                # mislog it as transient
                return False, f"missing file {rel!r}"
            try:
                size, crc = retry_io(
                    lambda p=fpath: _file_checksum(p), tag="ckpt-verify")
            except FileNotFoundError:
                return False, f"missing file {rel!r}"
            except OSError as e:
                return False, f"unreadable file {rel!r} ({e})"
            if size != rec["size"]:
                return False, (f"size mismatch on {rel!r} "
                               f"({size} != {rec['size']})")
            if crc != rec["crc32"]:
                return False, f"crc32 mismatch on {rel!r}"
        return True, "verified"

    def _mark_corrupt(self, step: int, why: str) -> None:
        """Rename a failing step dir to `<step>.corrupt` (chief-only): the
        step scanner and Orbax both ignore non-integer names, the bytes stay
        on disk for forensics, and the manifest stays beside it."""
        src = os.path.join(self.directory, str(step))
        dst = f"{src}.corrupt"
        print(f"[dcgan_tpu] checkpoint step {step} failed integrity check "
              f"({why}) — marking {dst} and falling back to the newest "
              f"intact checkpoint", flush=True)
        if jax.process_index() == 0 and os.path.isdir(src):
            os.replace(src, dst)
        try:
            self._mgr.reload()  # drop the manager's cached step metadata
        except Exception:  # older orbax without reload(): rebuild instead
            self._mgr.close()
            self._mgr = self._ocp.CheckpointManager(
                self.directory,
                options=self._ocp.CheckpointManagerOptions(
                    **self._mgr_options))

    def maybe_save(self, step: int, state: Pytree) -> bool:
        """Throttled save — the Supervisor's save_model_secs=600 cadence
        (image_train.py:129).

        Single-process: wall-clock throttle. Multi-host: save() is a
        collective, so the decision must be identical on every process —
        per-process clocks are not, so the cadence switches to the
        deterministic step interval.
        """
        if jax.process_count() > 1:
            if step % self.save_interval_steps != 0:
                return False
        else:
            now = time.time()
            if now < self._next_save:
                return False
            self._next_save = now + self.save_interval_secs
        self.save(step, state)
        return True

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def delete_steps_after(self, step: int) -> list:
        """Remove checkpoints NEWER than `step`; returns the steps dropped.

        Rollback support (train/rollback.py): a save taken between the
        last-good snapshot and the gate trip may embed the divergence the
        gate only caught later (the gate runs every nan_check_steps, not
        every step), and a replayed save at the same step number would
        collide with the stale dir.

        Multi-host (ISSUE 4): every process calls this at the same
        consensus-agreed rollback, but only the chief touches the shared
        filesystem (one deleter, like the manifest writer); the others
        wait at a named barrier so no process can dispatch a replayed save
        into a directory the chief is still deleting, then every manager
        drops its cached step metadata. The in-flight-save wait runs FIRST
        and the barrier is unconditional: the disk listing below is only
        symmetric across processes after every process has finished (and
        Orbax has committed) its async save work — a `dropped`-gated
        barrier could be entered by the process that listed after the
        commit rename and skipped by the one that listed before it."""
        multi = jax.process_count() > 1
        self._mgr.wait_until_finished()  # never race an in-flight save
        dropped = [s for s in self._finalized_steps() if s > step]
        delete_err = None
        if dropped and jax.process_index() == 0:
            import shutil

            from dcgan_tpu.utils.retry import retry_io

            for s in dropped:
                if multi:
                    # raw removal: CheckpointManager.delete is not a
                    # collective contract across orbax versions, and the
                    # reload below resyncs every manager anyway. A FAILED
                    # removal must be loud (matching mgr.delete's raise on
                    # the single-process path): a surviving poisoned-window
                    # dir is exactly the stale-collision / unverified-
                    # restore hazard this method exists to prevent. The
                    # failure is RECORDED, not raised here — the chief must
                    # still reach the verdict allgather below, or the
                    # non-chief processes deadlock in it.
                    try:
                        retry_io(lambda p=os.path.join(
                            self.directory, str(s)): shutil.rmtree(p),
                            tag="ckpt-delete")
                    except OSError as e:
                        delete_err = e
                        break
                else:
                    self._mgr.delete(s)
                # the manifest must die with the step: a REPLAYED save at
                # this step number writes different bytes, and verifying
                # them against the stale manifest would falsely mark the
                # good checkpoint .corrupt at the next restore
                try:
                    os.remove(self._manifest_path(s))
                except OSError:
                    pass
        if multi:
            import numpy as np
            from jax.experimental import multihost_utils

            # one allgather doubles as the barrier (no process passes this
            # point until all have entered) AND carries the chief's
            # deletion verdict, so success/failure is decided identically
            # on every process — an asymmetric raise above a collective is
            # a deadlock generator
            failed = np.asarray(multihost_utils.process_allgather(
                np.asarray(1 if delete_err is not None else 0,
                           np.int32))).reshape(-1)
            try:
                self._mgr.reload()
            except Exception:  # older orbax: rebuild instead
                self._mgr.close()
                self._mgr = self._ocp.CheckpointManager(
                    self.directory,
                    options=self._ocp.CheckpointManagerOptions(
                        **self._mgr_options))
            if failed.any():
                raise RuntimeError(
                    f"rollback checkpoint cleanup failed on the chief "
                    f"(steps {dropped}): aborting on every process rather "
                    f"than replaying into a stale step dir"
                ) from delete_err
        return dropped

    def restore_latest(self, target_state: Pytree) -> Optional[Pytree]:
        """Restore the newest INTACT checkpoint into the shape/sharding of
        `target_state` (pass the freshly-initialized state); None if no
        checkpoint exists — the reference's load() boolean contract
        (image_train.py:233-245).

        Candidates are tried newest-first: a step whose integrity manifest
        disagrees with the bytes on disk is renamed `<step>.corrupt` and the
        next-newest step is tried — a truncated latest checkpoint costs the
        run its most recent save interval, not the whole run. Steps without
        a manifest restore exactly as before (unverified), and restore-time
        exceptions still propagate — only MANIFEST-proven corruption
        quarantines a step, so a tree/shape mismatch can never silently
        retire good checkpoints."""
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding",
                                                            None))
            if hasattr(x, "shape") else x,
            target_state)
        for step in self._finalized_steps():
            ok, why = self._verify_step(step)
            if not ok:
                self._mark_corrupt(step, why)
                continue
            return self._mgr.restore(
                step, args=self._ocp.args.StandardRestore(abstract))
        return None

    def wait(self) -> None:
        """Block until async saves are durable (and manifest them)."""
        self._mgr.wait_until_finished()
        self._write_pending_manifests()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._write_pending_manifests()
        self._mgr.close()
