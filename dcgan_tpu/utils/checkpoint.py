"""Checkpoint / resume (Orbax-backed).

The reference's story (SURVEY.md §3.3, §5): a tf.train.Saver over all
variables (image_train.py:103), Supervisor-driven periodic save every 600 s on
the chief only (image_train.py:123-129), and restore-latest on startup
(image_train.py:141-146,233-245). Same contract here over the train-state
pytree — params, BN running stats, both Adam states, step — with Orbax doing
sharded, async-capable array IO (each host writes its shards; no PS process
holds "the" copy).
"""

from __future__ import annotations

import time
from typing import Any, Optional

import jax

Pytree = Any


def has_restorable_checkpoint(directory: str) -> bool:
    """True iff `directory` holds at least one completed Orbax step dir.

    Cheap filesystem check — no CheckpointManager construction (which
    would spin up async machinery and create the directory as a side
    effect). Completed Orbax steps are integer-named subdirectories;
    in-flight temp dirs carry an `.orbax-checkpoint-tmp` suffix and fail
    the digit test. Gates config.json adoption in the CLI: a stale config
    from a run that died before its first save must not claim the
    directory (mirror of the trainer's `latest_step() is not None` gate
    on the arch-mismatch check).
    """
    import os

    try:
        entries = os.listdir(directory)
    except OSError:
        return False
    return any(name.isdigit() and os.path.isdir(os.path.join(directory, name))
               for name in entries)


class Checkpointer:
    """save / maybe_save (time-throttled) / restore_latest over a state pytree.

    Only the chief process drives the save cadence (is_chief gating lives in
    the trainer, matching the reference's chief-only Supervisor saver), but
    all processes must enter save() together for multi-host array gather.
    """

    def __init__(self, directory: str, *, save_interval_secs: float = 600.0,
                 save_interval_steps: int = 1000, max_to_keep: int = 5,
                 async_save: bool = True):
        import os

        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save))
        self.save_interval_secs = save_interval_secs
        self.save_interval_steps = save_interval_steps
        self._next_save = time.time() + save_interval_secs

    def save(self, step: int, state: Pytree, *, force: bool = False) -> None:
        self._mgr.save(int(step),
                       args=self._ocp.args.StandardSave(state),
                       force=force)

    def maybe_save(self, step: int, state: Pytree) -> bool:
        """Throttled save — the Supervisor's save_model_secs=600 cadence
        (image_train.py:129).

        Single-process: wall-clock throttle. Multi-host: save() is a
        collective, so the decision must be identical on every process —
        per-process clocks are not, so the cadence switches to the
        deterministic step interval.
        """
        if jax.process_count() > 1:
            if step % self.save_interval_steps != 0:
                return False
        else:
            now = time.time()
            if now < self._next_save:
                return False
            self._next_save = now + self.save_interval_secs
        self.save(step, state)
        return True

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore_latest(self, target_state: Pytree) -> Optional[Pytree]:
        """Restore the newest checkpoint into the shape/sharding of
        `target_state` (pass the freshly-initialized state); None if no
        checkpoint exists — the reference's load() boolean contract
        (image_train.py:233-245)."""
        step = self._mgr.latest_step()
        if step is None:
            return None
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding",
                                                            None))
            if hasattr(x, "shape") else x,
            target_state)
        return self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(abstract))

    def wait(self) -> None:
        """Block until async saves are durable."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
