"""Weight sources for the sampler server: checkpoint or exported artifact.

Both present the same surface to the worker thread:

- `prepare()` — the cold-start heavy lifting (restore / deserialize +
  model build), called ON the dispatch thread so every collective the
  restore issues stays where the collective-thread rule wants it;
  returns the source's metadata dict.
- `bucket_plan(ladder)` — the `(name, fn, args)` AOT rows for
  `buckets.compile_buckets`.
- `bind(compiled)` — hand back the per-bucket executables.
- `sample(bucket, z[, labels])` — one device dispatch through the bound
  executable, materialized to a host array.

CheckpointSource is the full-fidelity path: it builds the same
ParallelTrain surface the trainer uses and restores device-resident
weights ONCE through the single-pass verified restore
(`utils/checkpoint.py` — stat screen, CRC fused with the payload read,
quarantine + newest-intact fallback), then serves EMA or live weights per
the flag. ArtifactSource is the light path: a `.jaxexport` StableHLO blob
plus its JSON sidecar is enough to cold-start — no checkpoint directory,
no framework state; the sidecar's serving block (ISSUE 9 satellite:
z_dim, num_classes, weight source, bucket-ladder hint) supplies the
calling convention.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, Optional

import numpy as np

from dcgan_tpu.serve.buckets import BucketLadder, sampler_plan
from dcgan_tpu.utils.retry import retry_io


def latest_finalized_step(checkpoint_dir: str) -> Optional[int]:
    """Newest FINALIZED checkpoint step under `checkpoint_dir`, or None.
    Integer-named directory == finalized: Orbax writes under a tmp name
    and renames on completion, so a digit-named dir is complete by
    contract (same screen `Checkpointer._finalized_steps` applies). IO
    errors read as "nothing new" — the promotion watcher polls this and
    must never crash a replica on a filesystem blip."""
    try:
        steps = [int(d) for d in os.listdir(checkpoint_dir)
                 if d.isdigit()]
    except OSError:
        return None
    return max(steps) if steps else None


class CheckpointSource:
    """Serve from a trained checkpoint through the framework sampler."""

    def __init__(self, checkpoint_dir: str, *, use_ema: bool = False,
                 preset: Optional[str] = None,
                 overrides: Optional[dict] = None,
                 max_batch: int = 64, quantize: str = ""):
        if quantize not in ("", "int8"):
            raise ValueError(
                f"quantize must be '' or 'int8', got {quantize!r}")
        self.checkpoint_dir = checkpoint_dir
        self.use_ema = use_ema
        self.preset = preset
        self.overrides = overrides
        self.max_batch = max_batch
        self.quantize = quantize
        self.z_dim = 0
        self.num_classes = 0
        self.granule = 1
        self._state = None
        self._pt = None
        self._ckpt = None
        self._compiled: Dict[int, Callable] = {}

    def prepare(self) -> dict:
        import jax

        from dcgan_tpu.config import TrainConfig, resolve_model_config
        from dcgan_tpu.parallel import make_mesh, make_parallel_train
        from dcgan_tpu.utils.checkpoint import Checkpointer

        mcfg = resolve_model_config(self.checkpoint_dir, preset=self.preset,
                                    overrides=self.overrides)
        mesh = make_mesh(TrainConfig(model=mcfg).mesh)
        self.granule = mesh.shape["data"]
        batch = -(-self.max_batch // self.granule) * self.granule
        cfg = TrainConfig(model=mcfg, batch_size=batch,
                          checkpoint_dir=self.checkpoint_dir,
                          # any value > 0 makes sample() read
                          # state["ema_gen"] (the generate.py convention)
                          g_ema_decay=0.999 if self.use_ema else 0.0)
        self._pt = make_parallel_train(cfg, mesh)
        state = self._pt.init(jax.random.key(0))
        ckpt = Checkpointer(self.checkpoint_dir)
        self._ckpt = ckpt
        # transient stat/read blips during the restore retry with backoff
        # (the PR 4 ckpt-verify contract); a persistently broken
        # checkpoint still fails the cold start loudly after the bounded
        # attempts
        restored = retry_io(lambda: ckpt.restore_latest(state),
                            tag="serve-restore")
        if restored is None:
            raise FileNotFoundError(
                f"no checkpoint under {self.checkpoint_dir}")
        restored, quant_report = self._maybe_quantize(restored)
        self._state = restored
        self.z_dim = mcfg.z_dim
        self.num_classes = mcfg.num_classes
        # elastic cold start (ISSUE 12): a checkpoint saved on a different
        # topology restores through the sharding sidecar's reshard path —
        # the serving mesh is whatever THIS host has, not whatever the
        # training fleet had. Surfaced in the metadata (and the server's
        # warm banner) so an operator can see a cross-topology cold start
        # happened and what it cost.
        meta = {"source": "checkpoint",
                "step": int(jax.device_get(restored["step"])),
                "weights": "ema" if self.use_ema else "live"}
        if quant_report is not None:
            meta["quantize"] = quant_report
        if ckpt.last_reshard is not None:
            meta["resharded"] = {
                "saved_processes": int(
                    ckpt.last_reshard["saved_processes"]),
                "saved_devices": int(ckpt.last_reshard["saved_devices"]),
                "reshard_ms": round(ckpt.last_reshard["reshard_ms"], 1),
            }
        return meta

    def _maybe_quantize(self, restored):
        """Apply the int8 serving rung (ISSUE 17) when armed: round-trip
        BOTH weight copies through int8 — sample() serves whichever the
        ema flag picks, and the two must not silently diverge in
        fidelity. Returns (state, quant_report-or-None)."""
        if self.quantize != "int8":
            return restored, None
        from dcgan_tpu.serve.quantize import quantize_dequantize_int8

        gen_q, quant_report = quantize_dequantize_int8(
            restored["params"]["gen"])
        ema_q, _ = quantize_dequantize_int8(restored["ema_gen"])
        restored = dict(restored)
        restored["params"] = dict(restored["params"], gen=gen_q)
        restored["ema_gen"] = ema_q
        return restored, quant_report

    def reload(self) -> dict:
        """Re-restore the newest finalized step into the EXISTING state
        template — same avals and shardings, so the swapped weights ride
        the already-compiled bucket executables with zero new programs
        (the promotion contract, ISSUE 19). Called ON the dispatch
        thread by the promotion control op; `self._state` is only
        replaced on success, so a failed reload leaves the replica
        serving its old weights. Returns the refreshed metadata."""
        import jax

        restored = retry_io(
            lambda: self._ckpt.restore_latest(self._state),
            tag="serve-restore")
        if restored is None:
            raise FileNotFoundError(
                f"no checkpoint under {self.checkpoint_dir}")
        restored, quant_report = self._maybe_quantize(restored)
        self._state = restored
        meta = {"source": "checkpoint",
                "step": int(jax.device_get(restored["step"])),
                "weights": "ema" if self.use_ema else "live"}
        if quant_report is not None:
            meta["quantize"] = quant_report
        return meta

    def latest_step_on_disk(self) -> Optional[int]:
        """Promotion-watcher probe: newest finalized step, or None."""
        return latest_finalized_step(self.checkpoint_dir)

    def bucket_plan(self, ladder: BucketLadder):
        return sampler_plan(self._pt.sample, ladder, self.z_dim,
                            state=self._state,
                            num_classes=self.num_classes)

    def bind(self, compiled: Dict[int, Callable]) -> None:
        self._compiled = compiled

    def compiled_buckets(self):
        """Ascending bound bucket rungs (the promotion re-prime list)."""
        return tuple(sorted(self._compiled))

    def sample(self, bucket: int, z: np.ndarray,
               labels: Optional[np.ndarray] = None) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        args: tuple = (self._state, jnp.asarray(z, jnp.float32))
        if self.num_classes:
            lbl = labels if labels is not None \
                else np.zeros((bucket,), np.int32)
            args = args + (jnp.asarray(lbl, jnp.int32),)
        return np.asarray(jax.device_get(self._compiled[bucket](*args)))


class ArtifactSource:
    """Serve from an `export.py` `.jaxexport` artifact + JSON sidecar —
    no checkpoint, no framework state: the weights are baked into the
    StableHLO bytes and the sidecar carries the calling convention."""

    def __init__(self, path: str):
        self.path = path
        sidecar_path = path + ".json"
        if not os.path.exists(sidecar_path):
            raise FileNotFoundError(
                f"artifact sidecar {sidecar_path} not found — export.py "
                "writes it next to the artifact; the server needs its "
                "calling convention (z_dim / num_classes / ladder hint)")
        with open(sidecar_path) as f:
            self.sidecar = json.load(f)
        self.z_dim = int(self.sidecar["z_dim"])
        self.num_classes = int(self.sidecar.get("num_classes", 0) or 0)
        self.granule = 1  # replicated artifact: any batch size tiles
        self._jit_call = None
        self._compiled: Dict[int, Callable] = {}

    def ladder_hint(self) -> Optional[list]:
        """The exporter's suggested bucket ladder (sidecar serving block),
        or None for artifacts written before ISSUE 9."""
        return (self.sidecar.get("serving") or {}).get("bucket_ladder")

    def prepare(self) -> dict:
        import jax
        from jax import export as jexport

        with open(self.path, "rb") as f:
            exported = jexport.deserialize(f.read())
        # jit the artifact's call so each ladder rung AOT-lowers like any
        # other program (an un-jitted Exported.call retraces per call)
        self._jit_call = jax.jit(exported.call)
        serving = self.sidecar.get("serving") or {}
        return {"source": "artifact",
                "step": self.sidecar.get("step"),
                "weights": serving.get("source",
                                       self.sidecar.get("weights", "live"))}

    def bucket_plan(self, ladder: BucketLadder):
        return sampler_plan(self._jit_call, ladder, self.z_dim,
                            num_classes=self.num_classes)

    def bind(self, compiled: Dict[int, Callable]) -> None:
        self._compiled = compiled

    def compiled_buckets(self):
        return tuple(sorted(self._compiled))

    # no reload(): an artifact's weights are baked into the StableHLO
    # bytes — promotion needs a checkpoint source; the worker fails the
    # ticket (without poisoning the replica) when reload is absent

    def sample(self, bucket: int, z: np.ndarray,
               labels: Optional[np.ndarray] = None) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        args: tuple = (jnp.asarray(z, jnp.float32),)
        if self.num_classes:
            lbl = labels if labels is not None \
                else np.zeros((bucket,), np.int32)
            args = args + (jnp.asarray(lbl, jnp.int32),)
        return np.asarray(jax.device_get(self._compiled[bucket](*args)))
