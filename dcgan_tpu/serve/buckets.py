"""Bucket ladder: the only batch shapes the serving plane may dispatch.

Continuous batching over XLA has one constraint the GPU-serving literature
can gloss over: every distinct batch shape is its own compiled executable.
A server that dispatches whatever batch the queue happens to hold retraces
on nearly every flush — seconds of compile on the latency path of
millisecond requests. The Gemma-on-TPU serving comparison (PAPERS.md,
arXiv 2605.25645) makes the same move made here: pick a small ladder of
batch buckets, AOT-compile the sampler at every rung up front (the PR 5
`train/warmup.py` discipline pointed at the sampler instead of the train
programs), and snap every dynamic batch UP to the nearest rung, padding
with throwaway latent rows. The zero-recompile guarantee follows by
construction: the worker only ever calls the per-bucket compiled
executables built during warmup, so no live dispatch can trigger a trace
(`tests/test_serve.py` pins this through `CompileCacheMonitor` — zero
compile requests after warmup under a live persistent cache).

`sampler_plan` emits the same `(name, fn, example_args)` rows
`train/warmup.py::aot_compile` consumes; `compile_buckets` is the
serve-side variant that KEEPS the compiled executables (warmup can throw
its copies away because the trainer's live dispatch goes through the jit
wrappers; the server dispatches the AOT executables directly — no
first-call deserialize, no jit-cache lookup on the latency path).

The rungs are part of the committed program manifest (ISSUE 11): the
semantic analyzer lowers `sampler_plan` over the default doubling ladder
and records each rung's jaxpr fingerprint + donation map in
`analysis/programs.lock.jsonl` (serve::sampler@b<N> rows — samplers must
never donate; an accidental `donate_argnums` here is a DCG007 finding).
Changing the ladder shape or the sampler program regenerates the
manifest (`python -m dcgan_tpu.analysis --semantic --write-manifest`).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class BucketLadder:
    """Ascending, granule-aligned batch sizes the server may dispatch.

    `granule` is the device-tiling unit (the mesh's data-axis size for a
    sharded sampler, 1 for an exported artifact): every bucket must divide
    over it or the sharded sample program cannot accept the batch.
    """

    buckets: Tuple[int, ...]
    granule: int = 1

    def __post_init__(self):
        if not self.buckets:
            raise ValueError("bucket ladder must not be empty")
        if self.granule < 1:
            raise ValueError(f"granule must be >= 1, got {self.granule}")
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(
                f"buckets must be strictly ascending, got {self.buckets}")
        bad = [b for b in self.buckets if b < 1 or b % self.granule]
        if bad:
            raise ValueError(
                f"buckets {bad} are not positive multiples of the device "
                f"granule {self.granule}")

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def snap(self, n: int) -> int:
        """The smallest bucket >= n — the shape a batch of n requests is
        padded to. n past the top rung returns max_bucket (the caller
        chunks oversized work across dispatches)."""
        if n < 1:
            raise ValueError(f"batch size must be >= 1, got {n}")
        for b in self.buckets:
            if b >= n:
                return b
        return self.max_bucket


def build_ladder(max_batch: int, granule: int = 1) -> BucketLadder:
    """The default doubling ladder: granule, 2*granule, 4*granule, ...
    capped by (and always including) `max_batch` rounded up to the
    granule. Doubling keeps the rung count logarithmic — the AOT warmup
    compiles one sampler per rung — while bounding padding waste at <2x
    on any fill level."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if granule < 1:
        raise ValueError(f"granule must be >= 1, got {granule}")
    top = -(-max_batch // granule) * granule
    rungs = []
    b = granule
    while b < top:
        rungs.append(b)
        b *= 2
    rungs.append(top)
    return BucketLadder(buckets=tuple(rungs), granule=granule)


def parse_buckets(spec: str, granule: int = 1) -> BucketLadder:
    """'8,16,32' -> BucketLadder — the CLI's explicit-ladder form."""
    try:
        rungs = tuple(sorted({int(tok) for tok in spec.split(",") if tok}))
    except ValueError:
        raise ValueError(
            f"--buckets must be comma-separated ints, got {spec!r}"
        ) from None
    return BucketLadder(buckets=rungs, granule=granule)


def sampler_plan(sample_fn: Callable, ladder: BucketLadder, z_dim: int, *,
                 state: Any = None, num_classes: int = 0
                 ) -> List[Tuple[str, Callable, tuple]]:
    """(name, jitted fn, example args) for the sampler at every ladder
    rung — the same row shape `train/warmup.py::build_warmup_plan`
    produces and `aot_compile` consumes. `state` is the live train-state
    pytree for a framework sampler (pt.sample(state, z[, labels])); None
    for an artifact sampler whose weights are baked in (fn(z[, labels]))."""
    import jax
    import jax.numpy as jnp

    plan: List[Tuple[str, Callable, tuple]] = []
    for b in ladder.buckets:
        z = jax.ShapeDtypeStruct((b, z_dim), jnp.float32)
        args: tuple = (z,) if state is None else (state, z)
        if num_classes:
            args = args + (jax.ShapeDtypeStruct((b,), jnp.int32),)
        plan.append((f"sampler@b{b}", sample_fn, args))
    return plan


def compile_buckets(plan: Sequence[Tuple[str, Callable, tuple]]
                    ) -> Tuple[Dict[int, Callable], Dict[str, float]]:
    """AOT-compile every planned rung; ({bucket: compiled executable},
    {name: compile_ms}). With a persistent compile cache configured each
    rung's compile primes (or deserializes from) the cache exactly like
    the trainer's warmup — a warm serve restart pays bounded IO, not
    compile — and the returned executables are what the dispatch thread
    calls, so post-warmup serving can never trace."""
    compiled: Dict[int, Callable] = {}
    timings: Dict[str, float] = {}
    for name, fn, args in plan:
        t0 = time.perf_counter()
        compiled[_bucket_of(name)] = fn.lower(*args).compile()
        timings[name] = (time.perf_counter() - t0) * 1e3
    return compiled, timings


def _bucket_of(name: str) -> int:
    return int(name.rsplit("@b", 1)[1])
