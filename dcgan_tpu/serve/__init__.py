"""Serving plane (ISSUE 9): generation as a service.

The reference's only generation surface is a sampler node inside the train
graph (SURVEY.md §3.4); `generate.py` made it a CLI, `export.py` made it a
portable artifact — this package makes it a *service*: a thread-safe
request queue, a continuous batcher that snaps dynamic batches onto a
small ladder of AOT-precompiled batch buckets (the PR 5 warmup discipline
pointed at the sampler), a single dispatch thread owning every device
program (the collective-thread rule, DESIGN.md §6b), and a lifecycle of
cold start -> warm serving -> graceful drain on SIGTERM.

Layers:
- buckets.py  — the bucket ladder and the AOT sampler compile plan
- sources.py  — where weights come from: a checkpoint (single-pass
                verified restore) or a `.jaxexport` artifact + sidecar
- server.py   — queue, batcher, backpressure, latency accounting
- worker.py   — the dispatch thread (cold start + batch loop + drain +
                weight promotion)
- router.py   — fleet routing: least-queue-depth dispatch, heartbeat
                health, hedge-once failover (ISSUE 19)
- fleet.py    — N replicas + router + live checkpoint promotion
- __main__.py — `python -m dcgan_tpu.serve` entry point (`--fleet N`)
"""

from dcgan_tpu.serve.buckets import (  # noqa: F401
    BucketLadder,
    build_ladder,
    compile_buckets,
    parse_buckets,
    sampler_plan,
)
from dcgan_tpu.serve.fleet import (  # noqa: F401
    PROMOTION_SEQUENCE,
    ServeFleet,
)
from dcgan_tpu.serve.router import (  # noqa: F401
    Router,
    RouterError,
    promotion_targets,
)
from dcgan_tpu.serve.server import (  # noqa: F401
    PromotionTicket,
    Response,
    SamplerServer,
    ServeError,
    ServeOverloadError,
)
from dcgan_tpu.serve.sources import (  # noqa: F401
    ArtifactSource,
    CheckpointSource,
    latest_finalized_step,
)
