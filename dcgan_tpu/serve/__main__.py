"""`python -m dcgan_tpu.serve`: the generation-as-a-service entry point.

The first non-training entry point with its own lifecycle:

  cold start   restore the checkpoint ONCE through the single-pass
               verified restore (or deserialize a `.jaxexport` artifact +
               sidecar — no checkpoint needed), AOT-compile the sampler
               at every bucket rung (persistent compile cache honored:
               warm restarts deserialize instead of compiling);
  warm serving replay a recorded arrival trace (`--trace`) or generate a
               deterministic Poisson demo load (`--demo_requests` /
               `--demo_rps`), requests flowing through the continuous
               batcher onto the precompiled buckets;
  drain        SIGTERM/SIGINT stops intake, in-flight and queued
               requests complete in FIFO order, the report/events land,
               and the process exits 0 — a preemption notice becomes a
               clean handoff, not dropped requests.

Usage:
    python -m dcgan_tpu.serve --checkpoint_dir ckpt --demo_requests 64
    python -m dcgan_tpu.serve --artifact sampler.jaxexport \
        --trace trace.json --report report.json --platform cpu

`--report` writes one JSON object (the serve/* metric row + request
accounting) and `--events_dir` mirrors the same row through MetricWriter
into an events.jsonl any existing tooling can tail.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dcgan_tpu.serve",
        description="continuous-batching sampler server with AOT bucket "
                    "plans")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--checkpoint_dir",
                     help="serve a trained checkpoint (verified restore)")
    src.add_argument("--artifact",
                     help="serve a .jaxexport artifact (+ .json sidecar); "
                          "no checkpoint directory needed")
    p.add_argument("--use_ema", action="store_true",
                   help="checkpoint source: serve the EMA generator")
    p.add_argument("--quantize", default="", choices=["", "int8"],
                   help="checkpoint source: post-training quantize the "
                        "served generator weights (int8 symmetric "
                        "per-channel quantize-dequantize at load; the "
                        "report rides the warm banner)")
    p.add_argument("--preset", default=None,
                   help="named config supplying the architecture instead "
                        "of the checkpoint's config.json")
    from dcgan_tpu.config import add_model_override_flags

    add_model_override_flags(p)
    p.add_argument("--buckets", default=None,
                   help="explicit bucket ladder, e.g. 8,16,32 (default: "
                        "the artifact sidecar's hint, else a doubling "
                        "ladder under --max_batch)")
    p.add_argument("--max_batch", type=int, default=64,
                   help="top bucket of the default ladder")
    p.add_argument("--max_queue", type=int, default=256,
                   help="request-queue bound (drop-oldest past it)")
    p.add_argument("--max_wait_ms", type=float, default=10.0,
                   help="deadline flush: max time the oldest request "
                        "waits for batchmates")
    p.add_argument("--compile_cache_dir", default="",
                   help="persistent compile cache (warm restarts "
                        "deserialize the bucket programs)")
    p.add_argument("--fleet", type=int, default=0,
                   help="run N health-checked replicas behind the "
                        "failover router (0 = single bare server)")
    p.add_argument("--heartbeat_secs", type=float, default=0.25,
                   help="fleet health-poll cadence")
    p.add_argument("--miss_beats", type=int, default=4,
                   help="consecutive silent health polls before a "
                        "replica is drained from rotation")
    p.add_argument("--watch_promotions", action="store_true",
                   help="fleet mode: watch the checkpoint dir for newly "
                        "finalized steps and hot-swap weights live "
                        "(zero recompiles, zero dropped requests)")
    p.add_argument("--watch_interval_secs", type=float, default=0.5,
                   help="promotion-watcher poll interval")
    p.add_argument("--trace", default=None,
                   help="JSON arrival trace to replay: {\"arrivals\": "
                        "[{\"t_ms\": ..., \"num_images\": ...}, ...]}")
    p.add_argument("--demo_requests", type=int, default=0,
                   help="generate this many Poisson-arrival demo "
                        "requests instead of a trace")
    p.add_argument("--demo_rps", type=float, default=20.0,
                   help="demo load mean arrival rate (requests/sec)")
    p.add_argument("--demo_max_images", type=int, default=8,
                   help="demo load per-request image count is uniform "
                        "in [1, this]")
    p.add_argument("--report", default=None,
                   help="write the final JSON report row here")
    p.add_argument("--events_dir", default=None,
                   help="mirror the serve/* row into events.jsonl here "
                        "(MetricWriter)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None)
    return p


def _load_arrivals(args) -> List[dict]:
    """[{t_ms, num_images}, ...] from --trace or the demo generator."""
    if args.trace:
        with open(args.trace) as f:
            arrivals = json.load(f)["arrivals"]
        return sorted(arrivals, key=lambda a: a["t_ms"])
    if args.demo_requests <= 0:
        return []
    import numpy as np

    rng = np.random.default_rng(args.seed)
    t = 0.0
    out = []
    for _ in range(args.demo_requests):
        t += float(rng.exponential(1e3 / args.demo_rps))
        out.append({"t_ms": t,
                    "num_images": int(rng.integers(
                        1, args.demo_max_images + 1))})
    return out


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    from dcgan_tpu.analysis import tripwire

    tripwire.maybe_install()  # DCGAN_THREAD_CHECKS=1 honors the drill env
    from dcgan_tpu.config import MODEL_OVERRIDE_FLAGS
    from dcgan_tpu.serve.buckets import parse_buckets
    from dcgan_tpu.serve.fleet import ServeFleet
    from dcgan_tpu.serve.server import SamplerServer
    from dcgan_tpu.serve.sources import ArtifactSource, CheckpointSource

    def _make_source():
        if args.artifact:
            return ArtifactSource(args.artifact)
        return CheckpointSource(
            args.checkpoint_dir, use_ema=args.use_ema, preset=args.preset,
            overrides={n: getattr(args, n) for n in MODEL_OVERRIDE_FLAGS},
            max_batch=args.max_batch, quantize=args.quantize)

    ladder = parse_buckets(args.buckets) if args.buckets else None
    fleet_n = max(0, args.fleet)
    fleet = None
    if fleet_n:
        fleet = ServeFleet(
            [_make_source() for _ in range(fleet_n)],
            buckets=(ladder.buckets if ladder is not None else None),
            max_batch=args.max_batch, max_queue=args.max_queue,
            max_wait_ms=args.max_wait_ms,
            cache_dir=args.compile_cache_dir, seed=args.seed,
            heartbeat_secs=args.heartbeat_secs,
            miss_beats=args.miss_beats,
            watch_promotions=args.watch_promotions,
            watch_interval_secs=args.watch_interval_secs)
        server = fleet.servers[0]   # banner/cold-start reporting
    else:
        server = SamplerServer(_make_source(), ladder=ladder,
                               max_batch=args.max_batch,
                               max_queue=args.max_queue,
                               max_wait_ms=args.max_wait_ms,
                               cache_dir=args.compile_cache_dir,
                               seed=args.seed)

    # graceful drain on SIGTERM/SIGINT: the handler only flips a flag —
    # the main thread breaks out of the load loop and runs the drain
    stop_event = threading.Event()

    def _on_signal(signum, frame):
        print(f"[dcgan_tpu.serve] received signal {signum}: stopping "
              "intake, draining in-flight requests", flush=True)
        stop_event.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    t0 = time.perf_counter()
    if fleet is not None:
        metas = fleet.start()
        meta = metas[0]
    else:
        meta = server.start()
    cold = server.cold_ms
    cache_note = ""
    if server._monitor is not None:
        c = server._monitor.counters()
        cache_note = (f", cache {int(c['hits'])} hit(s) / "
                      f"{int(c['misses'])} miss(es)")
    print(f"[dcgan_tpu.serve] cold start in "
          f"{cold.get('cold_start_ms', 0.0):.0f} ms "
          f"(restore {cold.get('restore_ms', 0.0):.0f} ms, "
          f"{len(server.ladder.buckets)} bucket(s) "
          f"{list(server.ladder.buckets)} warm in "
          f"{cold.get('warmup_ms', 0.0):.0f} ms{cache_note}) — "
          f"{meta.get('source')} step {meta.get('step')} "
          f"{meta.get('weights')} weights", flush=True)
    if meta.get("resharded"):
        # elastic cold start (ISSUE 12): the checkpoint was saved on a
        # different topology and restored through the sidecar reshard
        rs = meta["resharded"]
        print(f"[dcgan_tpu.serve] cross-topology cold start: checkpoint "
              f"saved on {rs['saved_processes']} process(es) x "
              f"{rs['saved_devices']} device(s), resharded onto this "
              f"host's mesh in {rs['reshard_ms']:.0f} ms", flush=True)
    if fleet is not None:
        print(f"[dcgan_tpu.serve] fleet: {fleet_n} replica(s) warm, "
              f"heartbeat {args.heartbeat_secs:.2f}s x "
              f"{args.miss_beats} miss(es)"
              + (", promotion watcher on" if args.watch_promotions
                 else ""), flush=True)
    print("[dcgan_tpu.serve] warm: serving", flush=True)

    arrivals = _load_arrivals(args)
    intake = fleet if fleet is not None else server
    responses = []
    submitted = 0
    t_load = time.monotonic()
    for arrival in arrivals:
        wait = arrival["t_ms"] / 1e3 - (time.monotonic() - t_load)
        if wait > 0 and stop_event.wait(wait):
            break
        if stop_event.is_set():
            break
        if fleet is not None:
            responses.append(fleet.submit(
                arrival["num_images"],
                client_id=arrival.get("client")))
        else:
            responses.append(server.submit(arrival["num_images"]))
        submitted += 1
    if not arrivals:
        # no load source: idle-serve until a signal arrives
        stop_event.wait()

    interrupted = stop_event.is_set()
    if fleet is not None:
        fleet.stop(drain=True)
    else:
        server.stop(drain=True)
    completed = sum(1 for r in responses if r.done() and r.error is None)
    failed = sum(1 for r in responses if r.done() and r.error is not None)
    report = intake.report()
    row = {
        "label": "serve-report",
        "buckets": list(server.ladder.buckets),
        "meta": meta,
        "devices": _device_count(),
        "submitted": submitted,
        "unsubmitted": len(arrivals) - submitted,
        "completed": completed,
        "failed": failed,
        "interrupted": interrupted,
        "wall_s": round(time.perf_counter() - t0, 3),
        **{k: (round(v, 3) if isinstance(v, float) else v)
           for k, v in report.items()},
    }
    if fleet is not None:
        row["fleet"] = {
            "replicas": fleet_n,
            "unhealthy": [[i, reason] for i, reason
                          in fleet.router.unhealthy_events],
            "failovers": fleet.router.failovers,
            "stop_errors": fleet.stop_errors,
            "promotions": fleet.promotion_results,
            "per_replica": [
                {k: (round(v, 3) if isinstance(v, float) else v)
                 for k, v in r.items()}
                for r in fleet.per_replica_reports()],
        }
    if args.report:
        with open(args.report, "w") as f:
            json.dump(row, f)
            f.write("\n")
    if args.events_dir:
        from dcgan_tpu.utils.metrics import MetricWriter

        writer = MetricWriter(args.events_dir, every_secs=0.0,
                              tensorboard=False)
        writer.write_scalars(int(meta.get("step") or 0), report)
        writer.close()
    print(f"[dcgan_tpu.serve] drain: {int(report['serve/completed'])} "
          f"request(s) completed, {int(report['serve/dropped'])} dropped, "
          "queue empty, clean exit", flush=True)
    return 0


def _device_count() -> int:
    import jax

    return jax.device_count()


if __name__ == "__main__":
    sys.exit(main())
