"""Post-training int8 quantization for the serving path (ISSUE 17).

The serving rung of the reduced-precision ladder: samplers tolerate far
more quantization than training (no gradient pathways to poison, one
forward per request), so the EXPORTED/served generator weights get int8
while training stays on the f32/bf16 ladder — the Gemma-on-TPU serving-
economics framing (arXiv:2605.25645), scoped serve-only on purpose:

- int8 training would perturb the G/D equilibrium this repo's parity
  gates pin (BN statistics and Adam moments react to weight noise);
- serving quality is gated here by a committed max relative-error bound
  per leaf instead (tests/test_precision.py), and the quantization
  REPORT rides the server banner / artifact sidecar so an operator can
  see a quantized fleet is quantized.

Mechanics: symmetric per-output-channel affine (scale = amax/127 over
each kernel's last axis — output channels for conv/deconv HWIO kernels
and linear [in, out] weights), quantize-DEquantize at load time. The
served pytree keeps its original dtypes/shapes, so every downstream
surface (bucket ladder AOT rows, export, sharding rules) is untouched:
the rung is a weight TRANSFORM, not a new execution path. True int8
storage/dispatch would be a lowering follow-up; the quality/economics
decision is what this rung commits.
"""

from __future__ import annotations

from typing import Any, Tuple

Pytree = Any

#: leaves quantized: 2-d+ weight matrices/kernels ("w"). Biases, BN
#: affines/stats, and SN vectors stay exact — sub-percent of the bytes,
#: disproportionate quality cost.
_QUANT_LEAF = "w"


def quantize_dequantize_int8(tree: Pytree) -> Tuple[Pytree, dict]:
    """Returns (tree', report): every eligible weight leaf round-tripped
    through symmetric per-output-channel int8; report carries the census
    + worst-case relative error for the banner/sidecar and the committed
    test bound."""
    import jax
    import jax.numpy as jnp

    from dcgan_tpu.elastic.rules import path_str

    flat = jax.tree_util.tree_flatten_with_path(tree)
    leaves, treedef = flat
    out = []
    quantized = 0
    worst_rel = 0.0
    worst_path = ""
    total_bytes = 0
    quant_bytes = 0
    for path, leaf in leaves:
        p = path_str(path)
        total_bytes += leaf.size * leaf.dtype.itemsize
        if not (p.endswith("/" + _QUANT_LEAF) or p == _QUANT_LEAF) \
                or leaf.ndim < 2:
            out.append(leaf)
            continue
        xf = leaf.astype(jnp.float32)
        # per-output-channel: the last axis of HWIO kernels and [in, out]
        # linears is the output dim; each channel gets its own amax scale
        amax = jnp.max(jnp.abs(xf), axis=tuple(range(leaf.ndim - 1)),
                       keepdims=True)
        scale = jnp.maximum(amax, 1e-12) / 127.0
        q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
        deq = (q.astype(jnp.float32) * scale).astype(leaf.dtype)
        denom = max(float(jnp.max(jnp.abs(xf))), 1e-12)
        rel = float(jnp.max(jnp.abs(deq.astype(jnp.float32) - xf))) / denom
        if rel > worst_rel:
            worst_rel, worst_path = rel, p
        quantized += 1
        quant_bytes += leaf.size  # 1 byte/elem if stored as int8
        out.append(deq)
    tree_out = jax.tree_util.tree_unflatten(treedef, out)
    report = {
        "scheme": "int8-sym-per-channel",
        "quantized_leaves": quantized,
        "max_rel_error": round(worst_rel, 6),
        "worst_leaf": worst_path,
        "int8_bytes": int(quant_bytes),
        "orig_bytes": int(total_bytes),
    }
    return tree_out, report
