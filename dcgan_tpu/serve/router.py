"""Failover router: health-checked dispatch over N sampler replicas.

The fleet's routing brain (ISSUE 19 tentpole). The router owns NO device
state and never touches a device API — it sees replicas purely through
their thread-safe surface (`submit`, `queue_depth`, `beats`, `poisoned`,
`evict_pending`, `record_failover_drop`), so it can run on any thread
without entering the collective-thread rule's jurisdiction (DESIGN.md
§6b/§6m: one dispatch thread PER replica; the router is a client of all
of them and a peer of none).

Routing policy:
- least-queue-depth among healthy replicas, lowest index breaking ties
  (deterministic, so tests can pin the choice);
- sticky per-client routing: a `client_id`'s requests ride ONE replica,
  which preserves the server's per-client FIFO ordering guarantee across
  the fleet — re-picked only if the sticky replica leaves rotation;
- hedge-once failover: when a replica fails a request for a replica-side
  reason (worker death, stop, eviction — NOT overload, NOT a bad
  request), the router resubmits it to a healthy peer at most once; a
  second failure (or no healthy peer) fails the client request and is
  counted as a failover drop on the replica that failed it.

Health model:
- every replica's dispatch thread bumps a `beats` counter on each batcher
  iteration and after each dispatch; the monitor thread polls every
  `heartbeat_secs` and counts polls with NO progress — `miss_beats`
  consecutive silent polls drain the replica from rotation;
- a poisoned replica (dispatch thread died) is unhealthy immediately and
  permanently; a beat-silent replica that resumes beating is re-admitted
  (the slow-heartbeat false-positive path, exercised by chaos
  `replica_slow_beat_at_dispatch`);
- on the healthy->unhealthy transition the router rescues the replica's
  parked queue (`evict_pending`): each evicted request's failover
  callback resubmits it to a healthy peer, so a wedged replica sheds its
  backlog instead of holding clients hostage.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dcgan_tpu.serve.server import (Response, ServeError,
                                    ServeOverloadError)

#: consecutive silent health polls before a replica leaves rotation
DEFAULT_MISS_BEATS = 4
#: health-poll cadence; beats bump at least every ~0.1s on a live worker
DEFAULT_HEARTBEAT_SECS = 0.25
#: hedge-once: a client request is submitted at most this many times
MAX_ATTEMPTS = 2


def promotion_targets(health: Dict[int, bool]) -> Tuple[int, ...]:
    """The replicas a weight promotion should target: exactly the
    healthy ones, ascending. Pure function — the protocol tier's virtual
    fleet (analysis/simulate.py) drives THIS decision logic, so the
    drain-lattice deadlock proof covers the code that picks the drain
    set, not a lookalike: a regression that includes a dead replica in
    the target set surfaces as a structural deadlock finding."""
    return tuple(sorted(i for i, ok in health.items() if ok))


class RouterError(ServeError):
    """No healthy replica could take the request."""


class Router:
    """Least-queue-depth dispatch with heartbeat health and hedge-once
    failover over a fixed replica list. Thread-safe; replicas are
    addressed by list index."""

    def __init__(self, replicas, *,
                 heartbeat_secs: float = DEFAULT_HEARTBEAT_SECS,
                 miss_beats: int = DEFAULT_MISS_BEATS):
        if not replicas:
            raise ValueError("router needs at least one replica")
        if miss_beats < 1:
            raise ValueError(f"miss_beats must be >= 1, got {miss_beats}")
        self._replicas = list(replicas)
        self.heartbeat_secs = heartbeat_secs
        self.miss_beats = miss_beats
        self._lock = threading.Lock()
        self._healthy = {i: True for i in range(len(self._replicas))}
        self._last_beats = {i: -1 for i in range(len(self._replicas))}
        self._misses = {i: 0 for i in range(len(self._replicas))}
        self._sticky: Dict[Any, int] = {}
        self.failovers = 0          # requests rescued onto a peer
        self.failover_drops = 0     # requests no peer could absorb
        self.unhealthy_events: List[Tuple[int, str]] = []
        self._monitor_thread: Optional[threading.Thread] = None
        self._monitor_stop = threading.Event()

    # -- health -------------------------------------------------------------

    def health(self) -> Dict[int, bool]:
        """Index -> healthy, the promotion_targets input."""
        with self._lock:
            return {i: (ok and not self._replicas[i].poisoned())
                    for i, ok in self._healthy.items()}

    def healthy_indices(self) -> Tuple[int, ...]:
        return tuple(i for i, ok in self.health().items() if ok)

    def mark_unhealthy(self, idx: int, reason: str) -> None:
        """Drain replica `idx` from rotation and rescue its parked
        queue. Idempotent per transition; also the monitor's edge
        action."""
        with self._lock:
            if not self._healthy.get(idx, False):
                return
            self._healthy[idx] = False
            self.unhealthy_events.append((idx, reason))
        print(f"[dcgan_tpu] serve fleet: replica {idx} UNHEALTHY "
              f"({reason}) — drained from rotation", flush=True)
        # outside the lock: evictions fire failover callbacks that
        # resubmit through pick()
        self._replicas[idx].evict_pending()

    def mark_healthy(self, idx: int) -> None:
        """Re-admit a replica whose heartbeat resumed (never a poisoned
        one — that is permanent)."""
        if self._replicas[idx].poisoned():
            return
        with self._lock:
            if self._healthy.get(idx, True):
                return
            self._healthy[idx] = True
            self._misses[idx] = 0
        print(f"[dcgan_tpu] serve fleet: replica {idx} re-admitted "
              f"(heartbeat resumed)", flush=True)

    def poll_health(self) -> Dict[int, bool]:
        """One monitor tick: advance beat bookkeeping, apply unhealthy /
        re-admission transitions, return the post-tick health map.
        Callable directly from tests — the monitor thread just loops
        this."""
        for i, r in enumerate(self._replicas):
            if r.poisoned():
                self.mark_unhealthy(i, "poisoned")
                continue
            beats = r.beats
            with self._lock:
                progressed = beats != self._last_beats[i]
                self._last_beats[i] = beats
                if progressed:
                    self._misses[i] = 0
                else:
                    self._misses[i] += 1
                misses = self._misses[i]
            if progressed:
                self.mark_healthy(i)
            elif misses >= self.miss_beats:
                self.mark_unhealthy(
                    i, f"missed {misses} heartbeats")
        return self.health()

    def start_monitor(self) -> None:
        """Spawn the health-poll thread (daemon; touches no device)."""
        if self._monitor_thread is not None:
            return
        def _loop():
            while not self._monitor_stop.wait(self.heartbeat_secs):
                self.poll_health()
        self._monitor_thread = threading.Thread(
            target=_loop, name="dcgan-serve-health", daemon=True)
        self._monitor_thread.start()

    def stop_monitor(self) -> None:
        self._monitor_stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(5.0)
            self._monitor_thread = None

    # -- routing ------------------------------------------------------------

    def pick(self, client_id=None) -> int:
        """The replica for the next request: sticky client mapping while
        its replica is in rotation, else least queue depth among healthy
        replicas with lowest index breaking ties."""
        health = self.health()
        with self._lock:
            healthy = [i for i, ok in health.items() if ok]
            if not healthy:
                raise RouterError("no healthy replicas in rotation")
            if client_id is not None:
                stick = self._sticky.get(client_id)
                if stick in healthy:
                    return stick
            choice = min(healthy,
                         key=lambda i: (self._replicas[i].queue_depth(),
                                        i))
            if client_id is not None:
                self._sticky[client_id] = choice
            return choice

    def submit(self, num_images: int = 1, *,
               z: Optional[np.ndarray] = None,
               labels: Optional[np.ndarray] = None,
               seed: Optional[int] = None,
               client_id=None) -> Response:
        """Route one request; returns a client-facing Response that
        survives a replica death mid-flight (hedge-once). Raises
        RouterError only when NO replica is healthy at submit time."""
        client_resp = Response()
        req = {"attempts": 0, "settled": False,
               "lock": threading.Lock(),
               "kwargs": dict(z=z, labels=labels, seed=seed),
               "num_images": num_images, "client_id": client_id}
        idx = self.pick(client_id)
        self._submit_to(idx, client_resp, req)
        return client_resp

    def _submit_to(self, idx: int, client_resp: Response, req) -> None:
        req["attempts"] += 1
        resp = self._replicas[idx].submit(req["num_images"],
                                          **req["kwargs"])
        resp.add_done_callback(
            lambda r, i=idx: self._on_done(i, r, client_resp, req))

    @staticmethod
    def _retryable(err: BaseException) -> bool:
        """Replica-side failures are retryable; deliberate shedding
        (overload) and bad requests (ValueError) are the client's to
        see."""
        return not isinstance(err, (ServeOverloadError, ValueError))

    def _on_done(self, idx: int, resp: Response,
                 client_resp: Response, req) -> None:
        """Failover callback, run on the resolving thread. Settles the
        client response exactly once; a retryable replica failure with
        budget left resubmits to a healthy peer instead."""
        with req["lock"]:
            if req["settled"]:
                return
            err = resp.error
            if err is None:
                req["settled"] = True
                client_resp._resolve(resp.images, resp.meta)
                return
            retry = self._retryable(err) and req["attempts"] < MAX_ATTEMPTS
            if not retry:
                req["settled"] = True
        if req["settled"]:
            if err is not None:
                if self._retryable(err):
                    with self._lock:
                        self.failover_drops += 1
                    self._replicas[idx].record_failover_drop()
                client_resp._fail(err)
            return
        # hedge-once: the failed replica is excluded by its health (a
        # dead replica is poisoned or about to be marked), but exclude
        # it explicitly too in case the monitor has not ticked yet
        try:
            health = self.health()
            healthy = [i for i, ok in health.items()
                       if ok and i != idx]
            if not healthy:
                raise RouterError(
                    f"no healthy peer to absorb failover from replica "
                    f"{idx}")
            with self._lock:
                self.failovers += 1
                peer = min(healthy,
                           key=lambda i: (self._replicas[i].queue_depth(),
                                          i))
                if req["client_id"] is not None:
                    self._sticky[req["client_id"]] = peer
            self._submit_to(peer, client_resp, req)
        except BaseException:  # noqa: BLE001 — no peer: fail the client
            with req["lock"]:
                req["settled"] = True
            with self._lock:
                self.failover_drops += 1
            self._replicas[idx].record_failover_drop()
            client_resp._fail(err)
