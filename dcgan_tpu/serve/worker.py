"""The serving plane's dispatch thread: cold start, batch loop, drain.

The collective-thread rule (DESIGN.md §6b) says every device program and
every collective stays on ONE thread per process. In the trainer that
thread is the one that entered `train()`; in the serving plane it is this
worker: the checkpoint restore (an Orbax collective on multi-host
topologies), the AOT bucket compiles, and every sampler dispatch all run
here, while callers only touch the thread-safe queue. The thread is a
DECLARED dispatch-thread owner — `analysis/core.py`'s
`Config.dispatch_thread_targets` names `ServeWorker._run`, so DCG001
does not flag the collectives reachable from this thread target (they
are exactly where the rule wants them), and at runtime the worker enters
`tripwire.dispatch_scope()` so under DCGAN_THREAD_CHECKS=1 any OTHER
thread touching a wrapped collective entry point trips loudly.

Lifecycle owned here:
- cold start: (optional) persistent-compile-cache wiring + monitor, the
  source's restore/deserialize, ladder resolution, AOT compile of every
  bucket rung — timed into the server's cold_ms/compile_ms breakdown;
- warm serving: `server._next_batch()` -> assemble z/labels -> bucketed
  dispatch -> split images back per request, resolving Responses with
  latency accounting;
- weight promotion (ISSUE 19): a PromotionTicket control op popped from
  the batcher IS the drain barrier — the loop is sequential, so the
  in-flight dispatch has fully resolved before the swap. `_promote`
  reloads the newest finalized step into the existing state template
  (same avals/shardings — no new programs), re-primes every rung with a
  throwaway dispatch (the PR 14 prime() trick re-links the swapped
  weights through every cached executable), and resumes; the compile
  cache monitor's request delta proves zero recompiles across the swap.
- drain: once the server stops intake, the loop keeps flushing until the
  queue is empty (FIFO, same batching rules), then exits cleanly.

A failure anywhere fails the in-flight requests and poisons the server —
never a silent half-service. (Exception: a reload that fails BEFORE the
state swap fails only its ticket — the old weights are intact, so the
replica keeps serving them; the fleet surfaces the error.)

Chaos hooks (testing/chaos.py, fleet drills): the per-dispatch counter
feeds `should_kill_replica` / `maybe_replica_hang` /
`maybe_replica_slow_beat`, so a FaultPlan can crash, wedge, or
heartbeat-mute exactly one replica at its n-th dispatch.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from dcgan_tpu.serve.server import PromotionTicket, ServeError
from dcgan_tpu.testing import chaos


class ServeWorker:
    """Single dispatch thread bound to one SamplerServer."""

    def __init__(self, server):
        self._server = server
        self._dispatch_index = 0   # 1-based, bumped per request batch
        name = "dcgan-serve-dispatch" if server.replica_index == 0 \
            else f"dcgan-serve-dispatch-{server.replica_index}"
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    # -- the dispatch thread ------------------------------------------------

    def _run(self) -> None:
        # declared dispatch-thread owner (DCG001 allowlist): collectives
        # REACHED FROM here are on the right thread by definition
        from dcgan_tpu.analysis import tripwire

        s = self._server
        with tripwire.dispatch_scope():
            try:
                self._cold_start()
            except BaseException as e:  # noqa: BLE001 — reported to callers
                s._fail_all(e)
                s._ready.set()
                return
            s._t_warm = time.monotonic()
            s._ready.set()
            while True:
                batch = s._next_batch()
                if batch is None:
                    return
                if isinstance(batch, PromotionTicket):
                    try:
                        self._promote(batch)
                    except BaseException as e:  # noqa: BLE001
                        batch._fail(e)
                        s._fail_all(e)
                        return
                    continue
                spans, total = batch
                self._dispatch_index += 1
                idx = self._dispatch_index
                try:
                    mute = chaos.maybe_replica_slow_beat(
                        s.replica_index, idx)
                    if mute:
                        s._mute_beats(mute)
                    chaos.maybe_replica_hang(s.replica_index, idx)
                    if chaos.should_kill_replica(s.replica_index, idx):
                        raise ServeError(
                            f"chaos: replica {s.replica_index} killed "
                            f"before dispatch {idx}")
                    self._dispatch(spans, total)
                    s._bump_beat()
                except BaseException as e:  # noqa: BLE001
                    for p, _ in spans:
                        p.resp._fail(e)
                    s._fail_all(e)
                    return

    def _cold_start(self) -> None:
        s = self._server
        t0 = time.perf_counter()
        if s.cache_dir:
            from dcgan_tpu.train import warmup

            warmup.configure_compile_cache(s.cache_dir)
            s._monitor = warmup.CompileCacheMonitor()
        s.meta = s.source.prepare()
        t_restore = time.perf_counter()
        s.ladder = s._resolve_ladder()
        from dcgan_tpu.serve.buckets import compile_buckets

        compiled, timings = compile_buckets(s.source.bucket_plan(s.ladder))
        s.source.bind(compiled)
        s.compile_ms = timings
        # prime every COMPILED rung with one throwaway end-to-end
        # dispatch: the FIRST execution of a compiled sharded program
        # also compiles the input-resharding transfer for host-built args
        # (one tiny program per bucket shape) — paying it here keeps the
        # zero-recompile guarantee literal for live traffic, and a broken
        # rung fails the cold start loudly instead of the first request.
        # (Sources with an empty bucket plan — test fakes — have no
        # executables to prime.)
        for b in sorted(compiled):
            z0 = np.zeros((b, s.source.z_dim), np.float32)
            lbl0 = np.zeros((b,), np.int32) \
                if s.source.num_classes else None
            s.source.sample(b, z0, lbl0)
        t_warm = time.perf_counter()
        s.cold_ms = {
            "restore_ms": (t_restore - t0) * 1e3,
            "warmup_ms": (t_warm - t_restore) * 1e3,
            "cold_start_ms": (t_warm - t0) * 1e3,
        }
        if s._monitor is not None:
            s._cache_post_warmup = s._monitor.counters()

    def _promote(self, ticket: PromotionTicket) -> None:
        """Hot-swap weights to the newest finalized checkpoint step.
        Runs ON the dispatch thread, after the in-flight batch resolved
        (the drain barrier). A reload failure BEFORE the swap fails only
        the ticket — old weights intact, the replica keeps serving; a
        re-prime failure raises (caller poisons the server: the swapped
        state could not dispatch)."""
        s = self._server
        reload_fn = getattr(s.source, "reload", None)
        if reload_fn is None:
            ticket._fail(ServeError(
                f"{type(s.source).__name__} does not support weight "
                "promotion (no reload())"))
            return
        base = s._monitor.counters()["requests"] \
            if s._monitor is not None else None
        t0 = time.perf_counter()
        try:
            meta = reload_fn()
        except BaseException as e:  # noqa: BLE001 — replica survives
            ticket._fail(e)
            return
        # re-prime every rung: the first execution of a cached program
        # with the swapped host-built args re-links the input-resharding
        # transfer — a throwaway dispatch per bucket keeps the
        # zero-recompile guarantee literal for the first real request
        # after the swap
        rungs = getattr(s.source, "compiled_buckets", tuple)()
        for b in rungs:
            z0 = np.zeros((b, s.source.z_dim), np.float32)
            lbl0 = np.zeros((b,), np.int32) \
                if s.source.num_classes else None
            s.source.sample(b, z0, lbl0)
        swap_ms = (time.perf_counter() - t0) * 1e3
        delta = (s._monitor.counters()["requests"] - base) \
            if base is not None else None
        s.meta.update(meta)
        s.promotions += 1
        s.promote_swap_ms = swap_ms
        s._bump_beat()
        ticket._resolve({"replica": s.replica_index,
                         "step": meta.get("step"),
                         "swap_ms": swap_ms,
                         "compile_requests_delta": delta})

    def _dispatch(self, spans: List[Tuple], total: int) -> None:
        s = self._server
        # re-check caller-provided latent widths against the now-resolved
        # z_dim: submit() can only validate once the cold start has run,
        # so a bad-width request that slipped in during the cold-start
        # window fails ITS response here — one malformed request must
        # never poison the server for everyone else
        bad = [(p, take) for p, take in spans
               if p.z is not None and p.z.shape[1] != s.source.z_dim]
        if bad:
            for p, _ in bad:
                p.resp._fail(ValueError(
                    f"z width {p.z.shape[1]} != source z_dim "
                    f"{s.source.z_dim}"))
            spans = [sp for sp in spans if sp not in bad]
            total = sum(take for _, take in spans)
            if not spans:
                return
        bucket = s.ladder.snap(total)
        t0 = time.monotonic()
        z_rows = []
        lbl_rows = []
        conditional = s.source.num_classes > 0
        for p, take in spans:
            if p.t_first_dispatch is None:
                p.t_first_dispatch = t0
            z_rows.append(p.take_z(take, s.source.z_dim, s.seed))
            if conditional:
                lbl_rows.append(p.take_labels(take))
        pad = bucket - total
        if pad:
            # padding rows are throwaway work: z=0 is a valid latent, the
            # rows are sliced off before any response sees them
            z_rows.append(np.zeros((pad, s.source.z_dim), np.float32))
            if conditional:
                lbl_rows.append(np.zeros((pad,), np.int32))
        z = np.concatenate(z_rows)
        labels = np.concatenate(lbl_rows) if conditional else None
        imgs = s.source.sample(bucket, z, labels)
        infer_ms = (time.monotonic() - t0) * 1e3
        s._record_batch(bucket, pad)
        offset = 0
        for p, take in spans:
            p.parts.append(imgs[offset:offset + take])
            p.buckets.append(bucket)
            p.infer_ms += infer_ms
            p.delivered += take
            offset += take
            if p.delivered == p.num_images:
                now = time.monotonic()
                total_ms = (now - p.t_submit) * 1e3
                p.resp._resolve(
                    np.concatenate(p.parts) if len(p.parts) > 1
                    else p.parts[0],
                    {"queue_ms": (p.t_first_dispatch - p.t_submit) * 1e3,
                     "infer_ms": p.infer_ms,
                     "total_ms": total_ms,
                     "buckets": list(p.buckets)})
                s._record_done(p, total_ms)
