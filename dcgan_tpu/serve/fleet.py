"""The serving fleet: N replicas, one router, live weight promotion.

ISSUE 19 tentpole. A `ServeFleet` owns N in-process `SamplerServer`
replicas — each with its OWN weight source, its own dispatch thread
(worker.py, a declared dispatch-thread owner under DCG001), and its own
AOT-primed bucket ladder — behind a `Router` (router.py) that
dispatches by least queue depth, health-checks replicas via heartbeat,
and fails over mid-flight requests onto healthy peers. One replica
crash, hang, or overload sheds load instead of failing clients.

Startup: replicas cold-start SEQUENTIALLY against a shared persistent
compile cache — the first replica pays the bucket compiles, later ones
hit the cache — and every replica's post-warmup compile-cache baseline
is re-snapshotted once ALL replicas are warm (sequential starts land
later replicas' cache requests after earlier snapshots; without the
rebaseline those read as phantom recompiles).

Promotion (`promote()`): the drain -> swap -> prime -> resume sequence
(`PROMOTION_SEQUENCE`), targeted at exactly the healthy replicas
(`router.promotion_targets` — the same decision function the protocol
tier's virtual fleet drives, so the drain lattice's deadlock-freedom
proof covers this code path). Each target replica hot-swaps behind its
own dispatch thread's implicit drain barrier: the control op is popped
only between batches, the reload restores the newest finalized step into
the existing state template (same avals/shardings — PR 11 sidecar
reshard included), and one throwaway dispatch per rung re-links the
swapped weights through every cached executable (the PR 14 prime()
trick) — zero dropped requests, zero recompiles, proven per replica by
the CompileCacheMonitor request delta in the ticket result. Waits are
per-ticket and bounded, never parked on a dead replica: an unhealthy
replica is simply not in the target set.

An optional watcher thread polls the checkpoint directory for a newly
FINALIZED step (integer-named dir — the Orbax tmp+rename contract) and
triggers `promote()` automatically: train-to-serve weight delivery with
no restart and no client-visible blip.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from dcgan_tpu.serve.router import Router, promotion_targets
from dcgan_tpu.serve.server import SamplerServer, ServeError

#: the promotion drain lattice, in order. Shared with the protocol
#: tier's virtual fleet (analysis/simulate.py) so the simulated barrier
#: sequence and the real one cannot drift apart silently.
PROMOTION_SEQUENCE = ("drain", "swap", "prime", "resume")

#: counters summed across replicas in the fleet report
_SUM_KEYS = ("serve/requests", "serve/completed", "serve/dropped",
             "serve/dropped_overload", "serve/dropped_failover",
             "serve/batches", "serve/images",
             "serve/recompiles_after_warmup")


class ServeFleet:
    """N health-checked sampler replicas behind a failover router.

    `sources` is one weight source PER replica (each replica restores
    and serves its own copy — replica isolation is the point). Server
    knobs are shared across replicas; `cache_dir` should be shared so
    later replicas hit the first one's compiles.
    """

    def __init__(self, sources: Sequence, *,
                 buckets: Optional[Sequence[int]] = None,
                 max_batch: int = 64,
                 max_queue: int = 256,
                 max_wait_ms: float = 10.0,
                 cache_dir: str = "",
                 seed: int = 0,
                 heartbeat_secs: float = 0.25,
                 miss_beats: int = 4,
                 watch_promotions: bool = False,
                 watch_interval_secs: float = 0.5):
        if not sources:
            raise ValueError("fleet needs at least one source")
        self.servers = [
            SamplerServer(src, buckets=buckets, max_batch=max_batch,
                          max_queue=max_queue, max_wait_ms=max_wait_ms,
                          cache_dir=cache_dir, seed=seed,
                          replica_index=i)
            for i, src in enumerate(sources)]
        self.router = Router(self.servers,
                             heartbeat_secs=heartbeat_secs,
                             miss_beats=miss_beats)
        self.watch_interval_secs = watch_interval_secs
        self._watch = watch_promotions
        self._watch_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._promotions = 0
        self._promoted_step: Optional[int] = None
        self.promotion_results: List[List[Dict[str, Any]]] = []
        self.stop_errors: List = []

    # -- lifecycle ----------------------------------------------------------

    def start(self, timeout: Optional[float] = None) -> List[dict]:
        """Cold-start every replica sequentially (shared compile cache:
        replica 0 pays the compiles), rebaseline all compile-cache
        snapshots once the whole fleet is warm, then start the health
        monitor and (optionally) the promotion watcher. Returns the
        per-replica source metadata."""
        metas = []
        for s in self.servers:
            metas.append(s.start(timeout))
        for s in self.servers:
            s._rebaseline_cache()
        step = metas[0].get("step")
        self._promoted_step = int(step) if step is not None else None
        self.router.start_monitor()
        if self._watch:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, name="dcgan-serve-promoter",
                daemon=True)
            self._watch_thread.start()
        return metas

    def submit(self, num_images: int = 1, *, z=None, labels=None,
               seed=None, client_id=None):
        """Route one request through the fleet; see Router.submit."""
        return self.router.submit(num_images, z=z, labels=labels,
                                  seed=seed, client_id=client_id)

    def stop(self, drain: bool = True, timeout: float = 120.0) -> List:
        """Stop the watcher, the monitor, then every replica. A replica
        that already died (chaos kill, poisoned worker) does not block
        the others' drain: its stop error is COLLECTED into the returned
        `stop_errors` list, not raised — the fleet's contract is zero
        failed CLIENT requests, and those were already failed over."""
        self._stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(5.0)
            self._watch_thread = None
        self.router.stop_monitor()
        errors: List = []
        for s in self.servers:
            try:
                s.stop(drain=drain, timeout=timeout)
            except BaseException as e:  # noqa: BLE001 — collected
                errors.append((s.replica_index, repr(e)))
        self.stop_errors = errors
        return errors

    # -- promotion ----------------------------------------------------------

    def promote(self, timeout: float = 300.0) -> List[Dict[str, Any]]:
        """Hot-swap every HEALTHY replica to the newest finalized
        checkpoint step. Targets come from `promotion_targets` over the
        router's live health map — a dead replica is never in the set,
        so the wait below can only block on replicas whose dispatch
        threads are alive (and each wait is bounded anyway). Returns one
        result dict per target: {replica, step, swap_ms,
        compile_requests_delta} or {replica, error}."""
        targets = promotion_targets(self.router.health())
        if not targets:
            raise ServeError("no healthy replicas to promote")
        tickets = [(i, self.servers[i].request_promote())
                   for i in targets]
        results: List[Dict[str, Any]] = []
        ok = 0
        for i, t in tickets:
            try:
                results.append(t.result(timeout))
                ok += 1
            except BaseException as e:  # noqa: BLE001 — per-replica
                results.append({"replica": i, "error": repr(e)})
        if ok:
            self._promotions += 1
            good = [r for r in results if "error" not in r]
            print(f"[dcgan_tpu] serve fleet: promoted "
                  f"{len(good)}/{len(results)} replica(s) to step "
                  f"{good[0].get('step')}", flush=True)
        self.promotion_results.append(results)
        return results

    def _watch_loop(self) -> None:
        """Poll the checkpoint directory for a newly finalized step and
        promote when one lands. Probe errors read as 'nothing new'."""
        probe = getattr(self.servers[0].source, "latest_step_on_disk",
                        None)
        if probe is None:
            return
        while not self._stop.wait(self.watch_interval_secs):
            step = probe()
            if step is None:
                continue
            if self._promoted_step is not None \
                    and step <= self._promoted_step:
                continue
            try:
                self.promote()
            except ServeError:
                continue  # no healthy replicas right now; retry later
            self._promoted_step = step

    # -- reporting ----------------------------------------------------------

    def per_replica_reports(self) -> List[Dict[str, float]]:
        return [s.report() for s in self.servers]

    def report(self) -> Dict[str, float]:
        """The fleet-level serve/* row: replica counters summed, latency
        percentiles recomputed over the merged samples, plus the fleet
        health/failover/promotion accounting."""
        from dcgan_tpu.serve.server import _percentile

        rows = self.per_replica_reports()
        out: Dict[str, float] = {
            k: float(sum(r.get(k, 0.0) for r in rows))
            for k in _SUM_KEYS}
        out["serve/queue_depth_max"] = float(max(
            r.get("serve/queue_depth_max", 0.0) for r in rows))
        padded = sum(s.padded_rows for s in self.servers)
        dispatched = sum(s.dispatched_rows for s in self.servers)
        out["serve/pad_frac"] = padded / max(1, dispatched)
        lat = sorted(x for s in self.servers for x in s._latencies_ms)
        if lat:
            out["serve/p50_ms"] = _percentile(lat, 50.0)
            out["serve/p99_ms"] = _percentile(lat, 99.0)
        starts = [s._t_warm for s in self.servers
                  if s._t_warm is not None]
        ends = [s._t_drained for s in self.servers]
        if starts:
            end = max(e for e in ends if e is not None) \
                if any(e is not None for e in ends) else time.monotonic()
            span = end - min(starts)
            if span > 0:
                out["serve/samples_per_sec"] = \
                    out["serve/images"] / span
        out["serve/fleet_replicas"] = float(len(self.servers))
        out["serve/fleet_unhealthy"] = float(len(
            {i for i, _ in self.router.unhealthy_events}))
        out["serve/fleet_failovers"] = float(self.router.failovers)
        # fleet-level promote() rounds, or replica-level ticket counts
        # when a caller promoted a single server directly
        rounds = self._promotions or max(
            (s.promotions for s in self.servers), default=0)
        if rounds:
            out["serve/promotions"] = float(rounds)
            out["serve/promote_swap_ms"] = max(
                s.promote_swap_ms for s in self.servers)
        return out
