"""The sampler server: queue -> continuous batches -> bucketed dispatch.

Request flow (ISSUE 9 tentpole): callers `submit()` generation requests
from any thread; a single dispatch thread (worker.py) assembles them into
dynamic batches, snaps each batch UP to the nearest AOT-precompiled
bucket (buckets.py), dispatches the per-bucket compiled executable, and
resolves each request's `Response` with its images and per-request
latency accounting (queue wait, device time, end-to-end).

Batching policy — the continuous-batching core:
- a flush happens when the pending work fills the LARGEST bucket (no
  reason to wait: the batch cannot grow) or when the OLDEST pending
  request has waited `max_wait_ms` (the deadline flush: latency is
  bounded by the knob even at trickle load);
- requests coalesce in FIFO order; a request larger than the top bucket
  is chunked across consecutive dispatches, its chunks never reordered
  against later arrivals (drain-on-stop preserves the same ordering);
- when the queue is full the OLDEST pending request is shed and its
  Response fails with `ServeOverloadError` — the drop-oldest
  backpressure of `train/services.py`, same rationale: under overload
  the newest work is the most likely to still matter to its caller, and
  a degraded server sheds load instead of growing an unbounded queue.

Counters flow through `utils/metrics.py::CounterRegistry` (the serve_*
CounterSnapshot fields) and `report()` emits the `serve/*` metric keys
declared in `train/event_keys.py` — the same inventory discipline the
trainer's keys live under (DCG004 lints this module against it).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dcgan_tpu.serve.buckets import BucketLadder, build_ladder

#: default request-queue bound: deep enough to absorb a burst several
#: buckets long, shallow enough that a wedged device sheds load within
#: seconds instead of hoarding latent arrays
DEFAULT_MAX_QUEUE = 256


class ServeError(RuntimeError):
    """The serving plane failed (startup, dispatch, or shutdown)."""


class ServeOverloadError(ServeError):
    """This request was shed by drop-oldest backpressure. Carries the
    shed-time queue state so fleet shedding is attributable (ISSUE 19):
    `queue_depth` is the pending-request count at shed time and
    `oldest_wait_ms` how long the head of the queue had been waiting."""

    def __init__(self, msg: str, *, queue_depth: int = 0,
                 oldest_wait_ms: float = 0.0):
        super().__init__(msg)
        self.queue_depth = queue_depth
        self.oldest_wait_ms = oldest_wait_ms


class Response:
    """Future-like handle for one request; resolved by the dispatch
    thread. `meta` carries the latency accounting: queue_ms (submit ->
    first dispatch), infer_ms (device dispatch + host materialize,
    summed over chunks), total_ms (submit -> resolve), and the bucket
    size(s) the request rode in."""

    def __init__(self) -> None:
        self._ev = threading.Event()
        self._cb_lock = threading.Lock()
        self._cbs: List[Any] = []
        self.images: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.meta: Dict[str, Any] = {}

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._ev.wait(timeout):
            raise TimeoutError("request not resolved within timeout")
        if self.error is not None:
            raise self.error
        return self.images

    def add_done_callback(self, fn) -> None:
        """Run `fn(self)` when the response resolves or fails —
        immediately (on the calling thread) if already done. Callbacks
        run on the resolving thread; keep them cheap. This is the
        router's failover hook: a replica death fails its in-flight
        responses, and the callback re-routes them to a healthy peer."""
        with self._cb_lock:
            if not self._ev.is_set():
                self._cbs.append(fn)
                return
        fn(self)

    # -- dispatch-thread side ---------------------------------------------

    def _finish(self) -> None:
        with self._cb_lock:
            self._ev.set()
            cbs, self._cbs = self._cbs, []
        for fn in cbs:
            fn(self)

    def _resolve(self, images: np.ndarray, meta: Dict[str, Any]) -> None:
        self.images = images
        self.meta.update(meta)
        self._finish()

    def _fail(self, err: BaseException) -> None:
        self.error = err
        self._finish()


class PromotionTicket:
    """Future-like handle for one weight-promotion control op. Resolved
    by the replica's dispatch thread after the drain -> swap -> prime ->
    resume sequence; `info` carries {step, swap_ms,
    compile_requests_delta}."""

    def __init__(self) -> None:
        self._ev = threading.Event()
        self.info: Dict[str, Any] = {}
        self.error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        if not self._ev.wait(timeout):
            raise TimeoutError("promotion not resolved within timeout")
        if self.error is not None:
            raise self.error
        return dict(self.info)

    def _resolve(self, info: Dict[str, Any]) -> None:
        self.info.update(info)
        self._ev.set()

    def _fail(self, err: BaseException) -> None:
        self.error = err
        self._ev.set()


class _Pending:
    """One queued request, tracked by the batcher."""

    __slots__ = ("num_images", "z", "labels", "seed", "serial", "resp",
                 "t_submit", "t_first_dispatch", "remaining", "delivered",
                 "parts", "buckets", "infer_ms", "cursor")

    def __init__(self, num_images: int, z: Optional[np.ndarray],
                 labels: Optional[np.ndarray], seed: Optional[int],
                 serial: int):
        self.num_images = num_images
        self.z = z
        self.labels = labels
        self.seed = seed
        self.serial = serial
        self.resp = Response()
        self.t_submit = time.monotonic()
        self.t_first_dispatch: Optional[float] = None
        self.remaining = num_images   # rows not yet taken into a batch
        self.delivered = 0            # rows already returned by dispatches
        self.parts: List[np.ndarray] = []
        self.buckets: List[int] = []
        self.infer_ms = 0.0
        self.cursor = 0               # next z row to hand to a batch

    def take_z(self, take: int, z_dim: int, base_seed: int) -> np.ndarray:
        """The next `take` latent rows — the caller-provided z, or rows
        drawn once per request from a deterministic per-request stream
        (host RNG on the dispatch thread: nothing here is traced)."""
        if self.z is None:
            seed = self.seed if self.seed is not None \
                else (base_seed, self.serial)
            rng = np.random.default_rng(seed)
            self.z = rng.uniform(-1.0, 1.0, (self.num_images, z_dim)) \
                .astype(np.float32)
        rows = self.z[self.cursor:self.cursor + take]
        self.cursor += take
        return rows

    def take_labels(self, take: int) -> np.ndarray:
        if self.labels is None:
            return np.zeros((take,), np.int32)
        start = self.cursor - take  # cursor already advanced by take_z
        return np.asarray(self.labels[start:start + take], np.int32)


class SamplerServer:
    """Continuous-batching generation server over one weight source.

    Lifecycle: `start()` spawns the dispatch thread, which cold-starts
    (restore/deserialize + AOT bucket warmup) and flips warm; `submit()`
    enqueues from any thread (accepted during cold start — they serve as
    soon as the plane is warm); `stop(drain=True)` stops intake, lets the
    worker drain the queue in FIFO order, and joins it. A worker failure
    fails the in-flight requests loudly and poisons the server (later
    submits are rejected, `stop()` re-raises) — the services-executor
    discipline, not silent half-service.
    """

    def __init__(self, source, *, ladder: Optional[BucketLadder] = None,
                 buckets: Optional[Sequence[int]] = None,
                 max_batch: int = 64,
                 max_queue: int = DEFAULT_MAX_QUEUE,
                 max_wait_ms: float = 10.0,
                 cache_dir: str = "",
                 seed: int = 0,
                 registry=None,
                 replica_index: int = 0):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if max_wait_ms < 0:
            raise ValueError(
                f"max_wait_ms must be >= 0, got {max_wait_ms}")
        self.source = source
        self.max_batch = max_batch
        self.max_queue = max_queue
        self.max_wait_ms = max_wait_ms
        self.cache_dir = cache_dir
        self.seed = seed
        self.replica_index = replica_index  # position in a ServeFleet
                                            # (0 for a bare server)
        self._explicit_ladder = ladder
        self._explicit_buckets = tuple(buckets) if buckets else None
        self.ladder: Optional[BucketLadder] = None   # set at cold start

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queue: "collections.deque[_Pending]" = collections.deque()
        self._control: "collections.deque[PromotionTicket]" = \
            collections.deque()
        self._draining = False
        self._started = False
        self._ready = threading.Event()
        self._error: Optional[BaseException] = None
        self._worker = None

        # counters (ints/floats mutated under _lock, read lock-free by
        # the registry providers — single-word reads are atomic enough
        # for telemetry)
        self.submitted = 0
        self.completed = 0
        self.dropped = 0            # total sheds (overload + failover)
        self.dropped_overload = 0   # drop-oldest backpressure sheds
        self.dropped_failover = 0   # router-abandoned during failover
        self.batches = 0
        self.images_out = 0
        self.padded_rows = 0
        self.dispatched_rows = 0
        self.queue_depth_max = 0
        self.promotions = 0         # completed weight promotions
        self.promote_swap_ms = 0.0  # last promotion's swap wall time
        self.beats = 0              # dispatch-thread liveness heartbeat
        self._beat_mute_until = 0.0
        self._serial = 0
        self._latencies_ms: List[float] = []

        # cold-start / warmup accounting, filled by the worker
        self.meta: Dict[str, Any] = {}
        self.cold_ms: Dict[str, float] = {}
        self.compile_ms: Dict[str, float] = {}
        self._monitor = None
        self._cache_post_warmup: Optional[Dict[str, float]] = None
        self._t_warm: Optional[float] = None
        self._t_drained: Optional[float] = None

        from dcgan_tpu.utils.metrics import CounterRegistry

        self.registry = registry if registry is not None \
            else CounterRegistry()
        self.registry.provide("serve_requests", lambda: self.submitted)
        self.registry.provide("serve_completed", lambda: self.completed)
        self.registry.provide("serve_dropped", lambda: self.dropped)
        self.registry.provide("serve_dropped_overload",
                              lambda: self.dropped_overload)
        self.registry.provide("serve_dropped_failover",
                              lambda: self.dropped_failover)
        self.registry.provide("serve_batches", lambda: self.batches)
        self.registry.provide("serve_queue", lambda: len(self._queue))

    # -- lifecycle ----------------------------------------------------------

    def start(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        """Spawn the dispatch thread and block until the plane is warm
        (cold start done, every bucket compiled); returns the source
        metadata. Raises the cold-start error if startup failed."""
        from dcgan_tpu.serve.worker import ServeWorker

        with self._lock:
            if self._started:
                raise ServeError("server already started")
            self._started = True
        self._worker = ServeWorker(self)
        self._worker.start()
        if not self._ready.wait(timeout):
            raise TimeoutError("serve cold start did not finish in time")
        self.raise_if_failed()
        return dict(self.meta)

    def submit(self, num_images: int = 1, *,
               z: Optional[np.ndarray] = None,
               labels: Optional[np.ndarray] = None,
               seed: Optional[int] = None) -> Response:
        """Enqueue one generation request; returns its Response. Never
        blocks on a full queue: the oldest pending request is shed
        instead (drop-oldest), and a stopped/poisoned server rejects
        immediately via the Response's error."""
        if z is not None:
            z = np.asarray(z, np.float32)
            if z.ndim != 2:
                raise ValueError(f"z must be [n, z_dim], got {z.shape}")
            # z_dim is 0 until the source's cold start resolves it; the
            # worker re-checks at assembly so a cold-start-window submit
            # with the wrong width fails ITS response, not the server
            if self.source.z_dim and z.shape[1] != self.source.z_dim:
                raise ValueError(
                    f"z width {z.shape[1]} != source z_dim "
                    f"{self.source.z_dim}")
            num_images = z.shape[0]
        if num_images < 1:
            raise ValueError(f"num_images must be >= 1, got {num_images}")
        if labels is not None and len(labels) != num_images:
            raise ValueError(
                f"labels length {len(labels)} != num_images {num_images}")
        # responses are failed OUTSIDE the lock: done-callbacks (router
        # failover) may touch other servers or re-enter this one
        fail_after: List[Tuple[Response, BaseException]] = []
        with self._lock:
            if self._draining or self._error is not None:
                p = _Pending(num_images, z, labels, seed, -1)
                fail_after.append((p.resp, ServeError(
                    "server is stopped" if self._error is None else
                    f"server failed: {self._error!r}")))
            else:
                p = _Pending(num_images, z, labels, seed, self._serial)
                self._serial += 1
                self.submitted += 1
                rejected = False
                while len(self._queue) >= self.max_queue:
                    # shed the oldest NEVER-DISPATCHED request: a
                    # partially dispatched head already has device work
                    # banked — failing it would throw those chunks away.
                    # With nothing undispatched to shed (max_queue=1
                    # around a chunking head), the NEW request is the one
                    # rejected.
                    depth = len(self._queue)
                    oldest_ms = (time.monotonic()
                                 - self._queue[0].t_submit) * 1e3
                    overload = ServeOverloadError(
                        f"request shed by drop-oldest backpressure "
                        f"(queue full at {self.max_queue}; depth {depth},"
                        f" oldest waited {oldest_ms:.1f}ms)",
                        queue_depth=depth, oldest_wait_ms=oldest_ms)
                    victim = next(
                        (q for q in self._queue if q.delivered == 0),
                        None)
                    self.dropped += 1
                    self.dropped_overload += 1
                    if victim is None:
                        fail_after.append((p.resp, overload))
                        rejected = True
                        break
                    self._queue.remove(victim)
                    fail_after.append((victim.resp, overload))
                if not rejected:
                    self._queue.append(p)
                    self.queue_depth_max = max(self.queue_depth_max,
                                               len(self._queue))
                    self._work.notify_all()
        for resp, err in fail_after:
            resp._fail(err)
        return p.resp

    def stop(self, drain: bool = True, timeout: float = 120.0) -> None:
        """Stop intake; with drain=True (the graceful path) the worker
        finishes every queued request in FIFO order first. Joins the
        worker and re-raises its failure, if any. Safe to call twice.
        A drain that outlives `timeout` raises TimeoutError — never a
        silent success banner over a still-running worker whose queued
        responses would die with the process."""
        fail_after: List[Tuple[Any, BaseException]] = []
        with self._lock:
            if not self._started:
                return
            self._draining = True
            if not drain:
                err = ServeError("server stopped before dispatch")
                while self._queue:
                    fail_after.append((self._queue.popleft().resp, err))
                while self._control:
                    fail_after.append((self._control.popleft(),
                                       ServeError(
                                           "server stopped before "
                                           "promotion")))
            self._work.notify_all()
        for fut, err in fail_after:
            fut._fail(err)
        if self._worker is not None:
            self._worker.join(timeout)
            if self._worker.is_alive():
                raise TimeoutError(
                    f"serve drain did not finish within {timeout}s — the "
                    "dispatch thread is still running; requests are NOT "
                    "all resolved")
        if self._monitor is not None:
            self._monitor.close()
        self.raise_if_failed()

    def request_promote(self) -> PromotionTicket:
        """Enqueue a weight-promotion control op; returns its ticket.
        The dispatch thread processes control ops with priority over
        pending requests — the in-flight batch completes first (the
        drain barrier falls out of the worker's sequential loop), then
        the worker swaps weights and re-primes every rung before serving
        resumes. A stopped/poisoned server fails the ticket
        immediately."""
        t = PromotionTicket()
        fail: Optional[BaseException] = None
        with self._lock:
            if self._draining or self._error is not None:
                fail = ServeError(
                    "server is stopped" if self._error is None else
                    f"server failed: {self._error!r}")
            else:
                self._control.append(t)
                self._work.notify_all()
        if fail is not None:
            t._fail(fail)
        return t

    def evict_pending(self) -> int:
        """Health-monitor rescue: remove every UNTOUCHED pending request
        (no span ever taken from it) and fail it with a retryable
        ServeError, so router failover callbacks resubmit it to a
        healthy peer. Requests a dispatch already took rows from stay —
        the (possibly just slow) worker still holds references and will
        resolve or fail them itself. Returns the eviction count."""
        victims: List[Any] = []
        with self._lock:
            keep = [p for p in self._queue
                    if p.remaining < p.num_images]
            victims = [p.resp for p in self._queue
                       if p.remaining == p.num_images]
            self._queue.clear()
            self._queue.extend(keep)
        err = ServeError(
            f"request evicted from unhealthy replica "
            f"{self.replica_index}")
        for resp in victims:
            resp._fail(err)
        return len(victims)

    def record_failover_drop(self, n: int = 1) -> None:
        """Router-side accounting: `n` requests parked on this replica
        were abandoned during failover (no healthy peer could take
        them). Kept separate from overload sheds so fleet drops stay
        attributable."""
        with self._lock:
            self.dropped += n
            self.dropped_failover += n

    def queue_depth(self) -> int:
        """Pending-request count (router's load signal)."""
        return len(self._queue)

    def poisoned(self) -> bool:
        """Whether the dispatch thread died (permanent unhealth)."""
        return self._error is not None

    def raise_if_failed(self) -> None:
        err = self._error
        if err is not None:
            raise ServeError(f"serve dispatch thread failed: {err!r}") \
                from err

    # -- reporting ----------------------------------------------------------

    def counters(self):
        """One coherent CounterSnapshot (serve_* fields live)."""
        return self.registry.snapshot()

    def report(self) -> Dict[str, float]:
        """The serve/* metric row (keys declared in train/event_keys.py):
        request/latency/throughput accounting plus the cold-start
        breakdown and the zero-recompile proof."""
        with self._lock:
            lat = sorted(self._latencies_ms)
            out: Dict[str, float] = {
                "serve/requests": float(self.submitted),
                "serve/completed": float(self.completed),
                "serve/dropped": float(self.dropped),
                "serve/dropped_overload": float(self.dropped_overload),
                "serve/dropped_failover": float(self.dropped_failover),
                "serve/batches": float(self.batches),
                "serve/images": float(self.images_out),
                "serve/queue_depth_max": float(self.queue_depth_max),
                "serve/pad_frac": (self.padded_rows
                                   / max(1, self.dispatched_rows)),
            }
            end = self._t_drained if self._t_drained is not None \
                else time.monotonic()
            if self._t_warm is not None and end > self._t_warm:
                out["serve/samples_per_sec"] = \
                    self.images_out / (end - self._t_warm)
        if lat:
            out["serve/p50_ms"] = _percentile(lat, 50.0)
            out["serve/p99_ms"] = _percentile(lat, 99.0)
            out["serve/mean_ms"] = float(np.mean(lat))
        if self.promotions:
            out["serve/promotions"] = float(self.promotions)
            out["serve/promote_swap_ms"] = self.promote_swap_ms
        # explicit literals (not a prefix f-string) so DCG004 lints each
        # cold-start key against the inventory individually
        for key, src in (("serve/restore_ms", "restore_ms"),
                         ("serve/warmup_ms", "warmup_ms"),
                         ("serve/cold_start_ms", "cold_start_ms")):
            if src in self.cold_ms:
                out[key] = self.cold_ms[src]
        for name, ms in self.compile_ms.items():
            out[f"serve/compile_ms/{name}"] = ms
        if self._monitor is not None:
            now = self._monitor.counters()
            out["perf/compile_cache_requests"] = now["requests"]
            out["perf/compile_cache_hits"] = now["hits"]
            out["perf/compile_cache_misses"] = now["misses"]
            if self._cache_post_warmup is not None:
                # the zero-recompile guarantee, measured: compile requests
                # issued AFTER the AOT bucket warmup (must stay 0 — every
                # served batch hits a precompiled bucket executable)
                out["serve/recompiles_after_warmup"] = (
                    now["requests"]
                    - self._cache_post_warmup["requests"])
        return out

    # -- worker side (dispatch thread only) ---------------------------------

    def _resolve_ladder(self) -> BucketLadder:
        """The bucket ladder for this run, aligned to the source's device
        granule: explicit ladder/buckets > the artifact sidecar's hint >
        the default doubling ladder under max_batch."""
        granule = self.source.granule
        if self._explicit_ladder is not None:
            rungs: Tuple[int, ...] = self._explicit_ladder.buckets
        elif self._explicit_buckets is not None:
            rungs = self._explicit_buckets
        else:
            hint = getattr(self.source, "ladder_hint", lambda: None)()
            if hint:
                rungs = tuple(int(b) for b in hint)
            else:
                return build_ladder(self.max_batch, granule)
        return BucketLadder(buckets=tuple(sorted(set(rungs))),
                            granule=granule)

    def _bump_beat(self) -> None:
        """Dispatch-thread liveness heartbeat: bumped on every batcher
        wait iteration and after every dispatch. A wedged worker stops
        bumping, which is exactly the signal the router's health monitor
        watches. A chaos slow-beat fault mutes bumps until a deadline
        (replica still serves but looks dead — the false-positive
        path)."""
        if time.monotonic() < self._beat_mute_until:
            return
        self.beats += 1

    def _mute_beats(self, secs: float) -> None:
        self._beat_mute_until = time.monotonic() + secs

    def _next_batch(self):
        """Block until work is due, then return it: a PromotionTicket
        (control ops take priority — the in-flight batch already
        finished, so this IS the drain barrier), a `(spans, total)`
        request batch (full top bucket, deadline, or drain), or None
        once draining and empty — the worker's exit signal."""
        with self._lock:
            while True:
                self._bump_beat()
                if self._control:
                    return self._control.popleft()
                if not self._queue:
                    if self._draining:
                        self._t_drained = time.monotonic()
                        return None
                    self._work.wait(0.1)
                    continue
                total = sum(p.remaining for p in self._queue)
                top = self.ladder.max_bucket
                now = time.monotonic()
                deadline = self._queue[0].t_submit + self.max_wait_ms / 1e3
                if total >= top or now >= deadline or self._draining:
                    return self._pop_spans(top)
                self._work.wait(min(deadline - now, 0.1))

    def _pop_spans(self, top: int) -> Tuple[List[Tuple[_Pending, int]],
                                            int]:
        spans: List[Tuple[_Pending, int]] = []
        total = 0
        while self._queue and total < top:
            p = self._queue[0]
            take = min(p.remaining, top - total)
            p.remaining -= take
            if p.remaining == 0:
                self._queue.popleft()
            spans.append((p, take))
            total += take
        return spans, total

    def _record_batch(self, bucket: int, pad: int) -> None:
        with self._lock:
            self.batches += 1
            self.padded_rows += pad
            self.dispatched_rows += bucket

    def _record_done(self, p: _Pending, total_ms: float) -> None:
        with self._lock:
            self.completed += 1
            self.images_out += p.num_images
            self._latencies_ms.append(total_ms)

    def _rebaseline_cache(self) -> None:
        """Re-snapshot the post-warmup compile-cache baseline. The fleet
        start path calls this on every replica after ALL replicas are
        warm: sequential cold starts land later replicas' cache requests
        after earlier replicas' snapshots, which would otherwise read as
        phantom recompiles in `serve/recompiles_after_warmup`."""
        if self._monitor is not None:
            self._cache_post_warmup = dict(self._monitor.counters())

    def _fail_all(self, err: BaseException) -> None:
        """Worker death: fail everything still queued (requests AND
        pending promotions), poison intake. Responses fail outside the
        lock so router failover callbacks can resubmit elsewhere."""
        victims: List[Any] = []
        with self._lock:
            self._error = err
            while self._queue:
                victims.append(self._queue.popleft().resp)
            while self._control:
                victims.append(self._control.popleft())
            self._work.notify_all()
        for fut in victims:
            fut._fail(err)


def _percentile(sorted_ms: List[float], pct: float) -> float:
    """Nearest-rank percentile over an ascending list."""
    idx = min(len(sorted_ms) - 1,
              max(0, int(round(pct / 100.0 * (len(sorted_ms) - 1)))))
    return sorted_ms[idx]
