"""The sampler server: queue -> continuous batches -> bucketed dispatch.

Request flow (ISSUE 9 tentpole): callers `submit()` generation requests
from any thread; a single dispatch thread (worker.py) assembles them into
dynamic batches, snaps each batch UP to the nearest AOT-precompiled
bucket (buckets.py), dispatches the per-bucket compiled executable, and
resolves each request's `Response` with its images and per-request
latency accounting (queue wait, device time, end-to-end).

Batching policy — the continuous-batching core:
- a flush happens when the pending work fills the LARGEST bucket (no
  reason to wait: the batch cannot grow) or when the OLDEST pending
  request has waited `max_wait_ms` (the deadline flush: latency is
  bounded by the knob even at trickle load);
- requests coalesce in FIFO order; a request larger than the top bucket
  is chunked across consecutive dispatches, its chunks never reordered
  against later arrivals (drain-on-stop preserves the same ordering);
- when the queue is full the OLDEST pending request is shed and its
  Response fails with `ServeOverloadError` — the drop-oldest
  backpressure of `train/services.py`, same rationale: under overload
  the newest work is the most likely to still matter to its caller, and
  a degraded server sheds load instead of growing an unbounded queue.

Counters flow through `utils/metrics.py::CounterRegistry` (the serve_*
CounterSnapshot fields) and `report()` emits the `serve/*` metric keys
declared in `train/event_keys.py` — the same inventory discipline the
trainer's keys live under (DCG004 lints this module against it).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dcgan_tpu.serve.buckets import BucketLadder, build_ladder

#: default request-queue bound: deep enough to absorb a burst several
#: buckets long, shallow enough that a wedged device sheds load within
#: seconds instead of hoarding latent arrays
DEFAULT_MAX_QUEUE = 256


class ServeError(RuntimeError):
    """The serving plane failed (startup, dispatch, or shutdown)."""


class ServeOverloadError(ServeError):
    """This request was shed by drop-oldest backpressure."""


class Response:
    """Future-like handle for one request; resolved by the dispatch
    thread. `meta` carries the latency accounting: queue_ms (submit ->
    first dispatch), infer_ms (device dispatch + host materialize,
    summed over chunks), total_ms (submit -> resolve), and the bucket
    size(s) the request rode in."""

    def __init__(self) -> None:
        self._ev = threading.Event()
        self.images: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.meta: Dict[str, Any] = {}

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._ev.wait(timeout):
            raise TimeoutError("request not resolved within timeout")
        if self.error is not None:
            raise self.error
        return self.images

    # -- dispatch-thread side ---------------------------------------------

    def _resolve(self, images: np.ndarray, meta: Dict[str, Any]) -> None:
        self.images = images
        self.meta.update(meta)
        self._ev.set()

    def _fail(self, err: BaseException) -> None:
        self.error = err
        self._ev.set()


class _Pending:
    """One queued request, tracked by the batcher."""

    __slots__ = ("num_images", "z", "labels", "seed", "serial", "resp",
                 "t_submit", "t_first_dispatch", "remaining", "delivered",
                 "parts", "buckets", "infer_ms", "cursor")

    def __init__(self, num_images: int, z: Optional[np.ndarray],
                 labels: Optional[np.ndarray], seed: Optional[int],
                 serial: int):
        self.num_images = num_images
        self.z = z
        self.labels = labels
        self.seed = seed
        self.serial = serial
        self.resp = Response()
        self.t_submit = time.monotonic()
        self.t_first_dispatch: Optional[float] = None
        self.remaining = num_images   # rows not yet taken into a batch
        self.delivered = 0            # rows already returned by dispatches
        self.parts: List[np.ndarray] = []
        self.buckets: List[int] = []
        self.infer_ms = 0.0
        self.cursor = 0               # next z row to hand to a batch

    def take_z(self, take: int, z_dim: int, base_seed: int) -> np.ndarray:
        """The next `take` latent rows — the caller-provided z, or rows
        drawn once per request from a deterministic per-request stream
        (host RNG on the dispatch thread: nothing here is traced)."""
        if self.z is None:
            seed = self.seed if self.seed is not None \
                else (base_seed, self.serial)
            rng = np.random.default_rng(seed)
            self.z = rng.uniform(-1.0, 1.0, (self.num_images, z_dim)) \
                .astype(np.float32)
        rows = self.z[self.cursor:self.cursor + take]
        self.cursor += take
        return rows

    def take_labels(self, take: int) -> np.ndarray:
        if self.labels is None:
            return np.zeros((take,), np.int32)
        start = self.cursor - take  # cursor already advanced by take_z
        return np.asarray(self.labels[start:start + take], np.int32)


class SamplerServer:
    """Continuous-batching generation server over one weight source.

    Lifecycle: `start()` spawns the dispatch thread, which cold-starts
    (restore/deserialize + AOT bucket warmup) and flips warm; `submit()`
    enqueues from any thread (accepted during cold start — they serve as
    soon as the plane is warm); `stop(drain=True)` stops intake, lets the
    worker drain the queue in FIFO order, and joins it. A worker failure
    fails the in-flight requests loudly and poisons the server (later
    submits are rejected, `stop()` re-raises) — the services-executor
    discipline, not silent half-service.
    """

    def __init__(self, source, *, ladder: Optional[BucketLadder] = None,
                 buckets: Optional[Sequence[int]] = None,
                 max_batch: int = 64,
                 max_queue: int = DEFAULT_MAX_QUEUE,
                 max_wait_ms: float = 10.0,
                 cache_dir: str = "",
                 seed: int = 0,
                 registry=None):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if max_wait_ms < 0:
            raise ValueError(
                f"max_wait_ms must be >= 0, got {max_wait_ms}")
        self.source = source
        self.max_batch = max_batch
        self.max_queue = max_queue
        self.max_wait_ms = max_wait_ms
        self.cache_dir = cache_dir
        self.seed = seed
        self._explicit_ladder = ladder
        self._explicit_buckets = tuple(buckets) if buckets else None
        self.ladder: Optional[BucketLadder] = None   # set at cold start

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queue: "collections.deque[_Pending]" = collections.deque()
        self._draining = False
        self._started = False
        self._ready = threading.Event()
        self._error: Optional[BaseException] = None
        self._worker = None

        # counters (ints/floats mutated under _lock, read lock-free by
        # the registry providers — single-word reads are atomic enough
        # for telemetry)
        self.submitted = 0
        self.completed = 0
        self.dropped = 0
        self.batches = 0
        self.images_out = 0
        self.padded_rows = 0
        self.dispatched_rows = 0
        self.queue_depth_max = 0
        self._serial = 0
        self._latencies_ms: List[float] = []

        # cold-start / warmup accounting, filled by the worker
        self.meta: Dict[str, Any] = {}
        self.cold_ms: Dict[str, float] = {}
        self.compile_ms: Dict[str, float] = {}
        self._monitor = None
        self._cache_post_warmup: Optional[Dict[str, float]] = None
        self._t_warm: Optional[float] = None
        self._t_drained: Optional[float] = None

        from dcgan_tpu.utils.metrics import CounterRegistry

        self.registry = registry if registry is not None \
            else CounterRegistry()
        self.registry.provide("serve_requests", lambda: self.submitted)
        self.registry.provide("serve_completed", lambda: self.completed)
        self.registry.provide("serve_dropped", lambda: self.dropped)
        self.registry.provide("serve_batches", lambda: self.batches)
        self.registry.provide("serve_queue", lambda: len(self._queue))

    # -- lifecycle ----------------------------------------------------------

    def start(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        """Spawn the dispatch thread and block until the plane is warm
        (cold start done, every bucket compiled); returns the source
        metadata. Raises the cold-start error if startup failed."""
        from dcgan_tpu.serve.worker import ServeWorker

        with self._lock:
            if self._started:
                raise ServeError("server already started")
            self._started = True
        self._worker = ServeWorker(self)
        self._worker.start()
        if not self._ready.wait(timeout):
            raise TimeoutError("serve cold start did not finish in time")
        self.raise_if_failed()
        return dict(self.meta)

    def submit(self, num_images: int = 1, *,
               z: Optional[np.ndarray] = None,
               labels: Optional[np.ndarray] = None,
               seed: Optional[int] = None) -> Response:
        """Enqueue one generation request; returns its Response. Never
        blocks on a full queue: the oldest pending request is shed
        instead (drop-oldest), and a stopped/poisoned server rejects
        immediately via the Response's error."""
        if z is not None:
            z = np.asarray(z, np.float32)
            if z.ndim != 2:
                raise ValueError(f"z must be [n, z_dim], got {z.shape}")
            # z_dim is 0 until the source's cold start resolves it; the
            # worker re-checks at assembly so a cold-start-window submit
            # with the wrong width fails ITS response, not the server
            if self.source.z_dim and z.shape[1] != self.source.z_dim:
                raise ValueError(
                    f"z width {z.shape[1]} != source z_dim "
                    f"{self.source.z_dim}")
            num_images = z.shape[0]
        if num_images < 1:
            raise ValueError(f"num_images must be >= 1, got {num_images}")
        if labels is not None and len(labels) != num_images:
            raise ValueError(
                f"labels length {len(labels)} != num_images {num_images}")
        with self._lock:
            if self._draining or self._error is not None:
                p = _Pending(num_images, z, labels, seed, -1)
                p.resp._fail(ServeError(
                    "server is stopped" if self._error is None else
                    f"server failed: {self._error!r}"))
                return p.resp
            p = _Pending(num_images, z, labels, seed, self._serial)
            self._serial += 1
            self.submitted += 1
            overload = ServeOverloadError(
                f"request shed by drop-oldest backpressure "
                f"(queue full at {self.max_queue})")
            while len(self._queue) >= self.max_queue:
                # shed the oldest NEVER-DISPATCHED request: a partially
                # dispatched head already has device work banked — failing
                # it would throw those chunks away. With nothing
                # undispatched to shed (max_queue=1 around a chunking
                # head), the NEW request is the one rejected.
                victim = next((q for q in self._queue if q.delivered == 0),
                              None)
                if victim is None:
                    self.dropped += 1
                    p.resp._fail(overload)
                    return p.resp
                self._queue.remove(victim)
                self.dropped += 1
                victim.resp._fail(overload)
            self._queue.append(p)
            self.queue_depth_max = max(self.queue_depth_max,
                                       len(self._queue))
            self._work.notify_all()
        return p.resp

    def stop(self, drain: bool = True, timeout: float = 120.0) -> None:
        """Stop intake; with drain=True (the graceful path) the worker
        finishes every queued request in FIFO order first. Joins the
        worker and re-raises its failure, if any. Safe to call twice.
        A drain that outlives `timeout` raises TimeoutError — never a
        silent success banner over a still-running worker whose queued
        responses would die with the process."""
        with self._lock:
            if not self._started:
                return
            self._draining = True
            if not drain:
                while self._queue:
                    self._queue.popleft().resp._fail(
                        ServeError("server stopped before dispatch"))
            self._work.notify_all()
        if self._worker is not None:
            self._worker.join(timeout)
            if self._worker.is_alive():
                raise TimeoutError(
                    f"serve drain did not finish within {timeout}s — the "
                    "dispatch thread is still running; requests are NOT "
                    "all resolved")
        if self._monitor is not None:
            self._monitor.close()
        self.raise_if_failed()

    def raise_if_failed(self) -> None:
        err = self._error
        if err is not None:
            raise ServeError(f"serve dispatch thread failed: {err!r}") \
                from err

    # -- reporting ----------------------------------------------------------

    def counters(self):
        """One coherent CounterSnapshot (serve_* fields live)."""
        return self.registry.snapshot()

    def report(self) -> Dict[str, float]:
        """The serve/* metric row (keys declared in train/event_keys.py):
        request/latency/throughput accounting plus the cold-start
        breakdown and the zero-recompile proof."""
        with self._lock:
            lat = sorted(self._latencies_ms)
            out: Dict[str, float] = {
                "serve/requests": float(self.submitted),
                "serve/completed": float(self.completed),
                "serve/dropped": float(self.dropped),
                "serve/batches": float(self.batches),
                "serve/images": float(self.images_out),
                "serve/queue_depth_max": float(self.queue_depth_max),
                "serve/pad_frac": (self.padded_rows
                                   / max(1, self.dispatched_rows)),
            }
            end = self._t_drained if self._t_drained is not None \
                else time.monotonic()
            if self._t_warm is not None and end > self._t_warm:
                out["serve/samples_per_sec"] = \
                    self.images_out / (end - self._t_warm)
        if lat:
            out["serve/p50_ms"] = _percentile(lat, 50.0)
            out["serve/p99_ms"] = _percentile(lat, 99.0)
            out["serve/mean_ms"] = float(np.mean(lat))
        # explicit literals (not a prefix f-string) so DCG004 lints each
        # cold-start key against the inventory individually
        for key, src in (("serve/restore_ms", "restore_ms"),
                         ("serve/warmup_ms", "warmup_ms"),
                         ("serve/cold_start_ms", "cold_start_ms")):
            if src in self.cold_ms:
                out[key] = self.cold_ms[src]
        for name, ms in self.compile_ms.items():
            out[f"serve/compile_ms/{name}"] = ms
        if self._monitor is not None:
            now = self._monitor.counters()
            out["perf/compile_cache_requests"] = now["requests"]
            out["perf/compile_cache_hits"] = now["hits"]
            out["perf/compile_cache_misses"] = now["misses"]
            if self._cache_post_warmup is not None:
                # the zero-recompile guarantee, measured: compile requests
                # issued AFTER the AOT bucket warmup (must stay 0 — every
                # served batch hits a precompiled bucket executable)
                out["serve/recompiles_after_warmup"] = (
                    now["requests"]
                    - self._cache_post_warmup["requests"])
        return out

    # -- worker side (dispatch thread only) ---------------------------------

    def _resolve_ladder(self) -> BucketLadder:
        """The bucket ladder for this run, aligned to the source's device
        granule: explicit ladder/buckets > the artifact sidecar's hint >
        the default doubling ladder under max_batch."""
        granule = self.source.granule
        if self._explicit_ladder is not None:
            rungs: Tuple[int, ...] = self._explicit_ladder.buckets
        elif self._explicit_buckets is not None:
            rungs = self._explicit_buckets
        else:
            hint = getattr(self.source, "ladder_hint", lambda: None)()
            if hint:
                rungs = tuple(int(b) for b in hint)
            else:
                return build_ladder(self.max_batch, granule)
        return BucketLadder(buckets=tuple(sorted(set(rungs))),
                            granule=granule)

    def _next_batch(self) -> Optional[Tuple[List[Tuple[_Pending, int]],
                                            int]]:
        """Block until a batch is due (full top bucket, deadline, or
        drain), then pop it FIFO; None once draining and empty — the
        worker's exit signal."""
        with self._lock:
            while True:
                if not self._queue:
                    if self._draining:
                        self._t_drained = time.monotonic()
                        return None
                    self._work.wait(0.1)
                    continue
                total = sum(p.remaining for p in self._queue)
                top = self.ladder.max_bucket
                now = time.monotonic()
                deadline = self._queue[0].t_submit + self.max_wait_ms / 1e3
                if total >= top or now >= deadline or self._draining:
                    return self._pop_spans(top)
                self._work.wait(min(deadline - now, 0.1))

    def _pop_spans(self, top: int) -> Tuple[List[Tuple[_Pending, int]],
                                            int]:
        spans: List[Tuple[_Pending, int]] = []
        total = 0
        while self._queue and total < top:
            p = self._queue[0]
            take = min(p.remaining, top - total)
            p.remaining -= take
            if p.remaining == 0:
                self._queue.popleft()
            spans.append((p, take))
            total += take
        return spans, total

    def _record_batch(self, bucket: int, pad: int) -> None:
        with self._lock:
            self.batches += 1
            self.padded_rows += pad
            self.dispatched_rows += bucket

    def _record_done(self, p: _Pending, total_ms: float) -> None:
        with self._lock:
            self.completed += 1
            self.images_out += p.num_images
            self._latencies_ms.append(total_ms)

    def _fail_all(self, err: BaseException) -> None:
        """Worker death: fail everything still queued, poison intake."""
        with self._lock:
            self._error = err
            while self._queue:
                self._queue.popleft().resp._fail(err)
            self._work.notify_all()


def _percentile(sorted_ms: List[float], pct: float) -> float:
    """Nearest-rank percentile over an ascending list."""
    idx = min(len(sorted_ms) - 1,
              max(0, int(round(pct / 100.0 * (len(sorted_ms) - 1)))))
    return sorted_ms[idx]
