"""Model zoo: DCGAN generator / discriminator / sampler (+ conditional variant)."""

from dcgan_tpu.models.dcgan import (  # noqa: F401
    discriminator_apply,
    discriminator_init,
    gan_init,
    generator_apply,
    generator_init,
    sampler_apply,
)
