"""DCGAN generator / discriminator / sampler as pure init/apply functions.

Capability-parity targets in the reference (behavior matched, architecture
re-designed functional — nothing is copied):

- `generator(z)`  distriubted_model.py:83-111 — linear z -> gf*8*4*4, reshape to
  [B,4,4,gf*8], then stride-2 5x5 deconv stages through gf*{4,2,1} with BN+relu,
  final deconv to c_dim + tanh. Batch size was hard-coded in every output_shape
  (distriubted_model.py:93-109); here shapes follow the input batch.
- `discriminator(image, reuse)`  distriubted_model.py:114-128 — stride-2 5x5 conv
  stages through df*{1,2,4,8}, BN on all but stage 0, lrelu(0.2), flatten,
  linear -> 1 logit; returns (sigmoid(logit), logit). TF's `reuse=True` variable
  sharing is simply passing the same params pytree — no variable scopes exist.
- `sampler(z)`  distriubted_model.py:131-153 — generator with train=False BN
  (running EMA statistics). Here that's `generator_apply(..., train=False)` on
  explicit state rather than TF side-state (SURVEY.md §2.4 #9).

Extensions beyond the reference (BASELINE.json configs):
- output_size 128 (or any base_size*2^k) deepens both stacks automatically;
- num_classes > 0 activates class conditioning (the reference's `y` argument is
  accepted-but-ignored, distriubted_model.py:83 / SURVEY.md §2.4 #7): one-hot
  labels concat onto z for G and broadcast as constant channel maps onto the
  image for D;
- attn_res > 0 inserts a SAGAN self-attention block (ops/attention.py) into
  both stacks at that feature-map resolution; `attn_mesh` routes it through
  sequence-parallel ring attention when the spatial mesh shards image height;
- spectral_norm "d"/"gd" divides every D (and G) weight by its power-iterated
  largest singular value each apply (ops/spectral.py) — the SN-GAN/SAGAN
  Lipschitz control, with the iteration vectors as explicit sn_* state leaves;
- conditional_bn makes the generator's BN affine per-class [K, C] tables
  (SAGAN/BigGAN cBN) on top of the z-concat conditioning.

Params/state are plain nested dicts so `jax.tree_util` / optax / checkpointing
all work without a framework dependency.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from dcgan_tpu.config import ModelConfig
from dcgan_tpu.ops.attention import attn_apply, attn_init
from dcgan_tpu.ops.layers import (
    conv2d_apply,
    conv2d_init,
    deconv2d_apply,
    deconv2d_init,
    linear_apply,
    linear_init,
    lrelu,
)
from dcgan_tpu.ops.norm import batch_norm_apply, batch_norm_init
from dcgan_tpu.ops.spectral import spectral_normalize, spectral_u_init

Pytree = dict

_ATTN_SUBLAYERS = ("query", "key", "value", "out")


def _sn_state_init(key, params: Pytree, state: Pytree) -> None:
    """Power-iteration u vectors for every weight in `params` (one level of
    nesting for the attention block), written into `state` as sn_* leaves —
    the explicit-state mirror of torch's hidden SN buffers."""
    j = 0
    for name in sorted(params):
        p = params[name]
        if "w" in p:
            state[f"sn_{name}"] = spectral_u_init(
                jax.random.fold_in(key, j), p["w"].shape[-1])
            j += 1
        elif name == "attn":
            for sub in _ATTN_SUBLAYERS:
                state[f"sn_attn_{sub}"] = spectral_u_init(
                    jax.random.fold_in(key, j), p[sub]["w"].shape[-1])
                j += 1


def _sn_layer(params: Pytree, state: Pytree, new_state: Pytree, name: str,
              train: bool) -> Pytree:
    """params[name] with its weight spectrally normalized; advances the
    layer's u into new_state (train=True) or carries it unchanged."""
    w_sn, u = spectral_normalize(params[name]["w"], state[f"sn_{name}"],
                                 train=train)
    new_state[f"sn_{name}"] = u
    return {**params[name], "w": w_sn}


def _sn_attn(params_attn: Pytree, state: Pytree, new_state: Pytree,
             train: bool) -> Pytree:
    out = dict(params_attn)
    for sub in _ATTN_SUBLAYERS:
        w_sn, u = spectral_normalize(params_attn[sub]["w"],
                                     state[f"sn_attn_{sub}"], train=train)
        new_state[f"sn_attn_{sub}"] = u
        out[sub] = {**params_attn[sub], "w": w_sn}
    return out


_FP8_MIN_RES = 64


def _stage_quant(cfg: ModelConfig, res: int) -> str:
    """fp8 simulated-quantization gate (precision='fp8'): only interior
    conv/deconv stages whose feature maps reach _FP8_MIN_RES quantize their
    GEMM operands — a no-op for every stage of the 64px phase (interior
    maps top out at 32px), biting exactly in the 128/256px progressive
    phases where the arithmetic is. The image-boundary stages (G's final
    deconv to c_dim, D's conv0) never quantize: quality-critical and
    a rounding error of the FLOPs."""
    return cfg.quant if res >= _FP8_MIN_RES else ""


def _dtype(cfg: ModelConfig):
    return jnp.dtype(cfg.param_dtype)


def _cdtype(cfg: ModelConfig):
    return jnp.dtype(cfg.compute_dtype)


# ---------------------------------------------------------------------------
# Generator
# ---------------------------------------------------------------------------

def generator_init(key, cfg: ModelConfig) -> Tuple[Pytree, Pytree]:
    """Returns (params, bn_state) for the generator."""
    if cfg.arch == "resnet":
        from dcgan_tpu.models import resnet

        return resnet.generator_init(key, cfg)
    if cfg.arch == "stylegan":
        from dcgan_tpu.models import stylegan

        return stylegan.generator_init(key, cfg)
    k = cfg.num_up_layers
    dtype = _dtype(cfg)
    keys = jax.random.split(key, 2 * k + 2)

    in_dim = cfg.z_dim + (cfg.num_classes if cfg.num_classes else 0)
    top_ch = cfg.gf_dim * (2 ** (k - 1))
    params: Pytree = {
        "proj": linear_init(keys[0], in_dim, top_ch * cfg.base_size * cfg.base_size,
                            dtype=dtype),
    }
    state: Pytree = {}
    bn_classes = cfg.num_classes if cfg.conditional_bn else 0
    bn_p, bn_s = batch_norm_init(keys[1], top_ch, dtype=dtype,
                                 num_classes=bn_classes)
    params["bn0"], state["bn0"] = bn_p, bn_s

    in_ch = top_ch
    for i in range(1, k + 1):
        out_ch = cfg.c_dim if i == k else cfg.gf_dim * (2 ** (k - 1 - i))
        params[f"deconv{i}"] = deconv2d_init(
            keys[2 * i], in_ch, out_ch, kernel=cfg.kernel_size, dtype=dtype)
        if i < k:
            bn_p, bn_s = batch_norm_init(keys[2 * i + 1], out_ch, dtype=dtype,
                                         num_classes=bn_classes)
            params[f"bn{i}"], state[f"bn{i}"] = bn_p, bn_s
        in_ch = out_ch
    if cfg.attn_res:
        # channels of the stage whose output feature map is attn_res:
        # stage 0 (base_size) has top_ch; stage i (base_size*2^i) has
        # gf_dim * 2^(k-1-i). keys[2k+1] is unused above (stage k has no BN).
        i = int(round(math.log2(cfg.attn_res / cfg.base_size)))
        ch = top_ch if i == 0 else cfg.gf_dim * (2 ** (k - 1 - i))
        params["attn"] = attn_init(keys[2 * k + 1], ch, dtype=dtype)
    if cfg.spectral_norm == "gd":
        # u keys derive from a fold_in of the net key so existing layer init
        # streams (keys[...]) are untouched whatever the flag
        _sn_state_init(jax.random.fold_in(key, 0x53AE), params, state)
    return params, state


def generator_apply(params: Pytree, state: Pytree, z: jax.Array, *,
                    cfg: ModelConfig, train: bool,
                    labels: Optional[jax.Array] = None,
                    axis_name: Optional[str] = None,
                    attn_mesh=None,
                    pallas_mesh=None,
                    capture: Optional[dict] = None
                    ) -> Tuple[jax.Array, Pytree]:
    """z [B, z_dim] (-1..1) -> image [B, S, S, c_dim] in tanh range.

    train=True uses batch BN statistics and returns updated EMA state;
    train=False is the reference's `sampler` path (running stats, state
    unchanged).

    `capture`, when a dict, receives every post-activation tensor keyed
    "h0".."hk" — the functional replacement for the reference's
    `_activation_summary` calls inside the layer stack
    (distriubted_model.py:75-80,94-110); callers turn them into
    histogram/sparsity summaries (utils/metrics.py).
    """
    if cfg.arch == "resnet":
        from dcgan_tpu.models import resnet

        return resnet.generator_apply(
            params, state, z, cfg=cfg, train=train, labels=labels,
            axis_name=axis_name, attn_mesh=attn_mesh,
            pallas_mesh=pallas_mesh, capture=capture)
    if cfg.arch == "stylegan":
        from dcgan_tpu.models import stylegan

        return stylegan.generator_apply(
            params, state, z, cfg=cfg, train=train, labels=labels,
            axis_name=axis_name, attn_mesh=attn_mesh,
            pallas_mesh=pallas_mesh, capture=capture)
    k = cfg.num_up_layers
    cdt = _cdtype(cfg)
    new_state: Pytree = {}
    sn = cfg.spectral_norm == "gd"

    def layer(name):
        return _sn_layer(params, state, new_state, name, train) if sn \
            else params[name]

    def attn_params():
        return _sn_attn(params["attn"], state, new_state, train) if sn \
            else params["attn"]

    if cfg.num_classes:
        if labels is None:
            raise ValueError("conditional generator requires labels")
        onehot = jax.nn.one_hot(labels, cfg.num_classes, dtype=z.dtype)
        z = jnp.concatenate([z, onehot], axis=-1)

    top_ch = cfg.gf_dim * (2 ** (k - 1))
    h = linear_apply(layer("proj"), z.astype(cdt), compute_dtype=cdt)
    h = h.reshape(-1, cfg.base_size, cfg.base_size, top_ch)
    # BN + relu fused (one pass under use_pallas; XLA-fused otherwise)
    bn_labels = labels if cfg.conditional_bn else None
    h, new_state["bn0"] = batch_norm_apply(
        params["bn0"], state["bn0"], h, train=train,
        momentum=cfg.bn_momentum, eps=cfg.bn_eps, axis_name=axis_name,
        act="relu", use_pallas=cfg.bn_use_pallas, labels=bn_labels,
        pallas_mesh=pallas_mesh)
    if cfg.attn_res == cfg.base_size:
        h = attn_apply(attn_params(), h, compute_dtype=cdt,
                       num_heads=cfg.attn_heads,
                       seq_strategy=cfg.attn_seq_strategy,
                       seq_mesh=attn_mesh, use_pallas=cfg.use_pallas,
                       pallas_mesh=pallas_mesh)
    if capture is not None:
        capture["h0"] = h

    for i in range(1, k + 1):
        if cfg.pallas_fused and i < k:
            # the whole interior stage (deconv + bias + BN + relu) as the
            # fused Pallas block — one HBM round-trip instead of three
            from dcgan_tpu.ops.pallas_fused import fused_conv_bn_act

            h, new_state[f"bn{i}"] = fused_conv_bn_act(
                layer(f"deconv{i}"), params[f"bn{i}"], state[f"bn{i}"], h,
                transpose=True, kernel=cfg.kernel_size, stride=2,
                train=train, momentum=cfg.bn_momentum, eps=cfg.bn_eps,
                act="relu", axis_name=axis_name, pallas_mesh=pallas_mesh,
                compute_dtype=cdt,
                quant=_stage_quant(cfg, cfg.base_size * (2 ** i)))
        else:
            h = deconv2d_apply(
                layer(f"deconv{i}"), h, compute_dtype=cdt,
                quant="" if i == k
                else _stage_quant(cfg, cfg.base_size * (2 ** i)))
            if i < k:
                h, new_state[f"bn{i}"] = batch_norm_apply(
                    params[f"bn{i}"], state[f"bn{i}"], h, train=train,
                    momentum=cfg.bn_momentum, eps=cfg.bn_eps,
                    axis_name=axis_name, act="relu",
                    use_pallas=cfg.bn_use_pallas,
                    labels=bn_labels, pallas_mesh=pallas_mesh)
        if i < k:
            if cfg.attn_res == cfg.base_size * (2 ** i):
                h = attn_apply(attn_params(), h, compute_dtype=cdt,
                               num_heads=cfg.attn_heads,
                               seq_strategy=cfg.attn_seq_strategy,
                               seq_mesh=attn_mesh,
                               use_pallas=cfg.use_pallas,
                               pallas_mesh=pallas_mesh)
            if capture is not None:
                capture[f"h{i}"] = h

    out = jnp.tanh(h.astype(jnp.float32))
    if capture is not None:
        capture[f"h{k}"] = out
    return out, new_state


def sampler_apply(params: Pytree, state: Pytree, z: jax.Array, *,
                  cfg: ModelConfig,
                  labels: Optional[jax.Array] = None,
                  pallas_mesh=None) -> jax.Array:
    """Inference-mode generation (reference `sampler`, distriubted_model.py:131)."""
    img, _ = generator_apply(params, state, z, cfg=cfg, train=False,
                             labels=labels, pallas_mesh=pallas_mesh)
    return img


# ---------------------------------------------------------------------------
# Discriminator
# ---------------------------------------------------------------------------

def discriminator_init(key, cfg: ModelConfig) -> Tuple[Pytree, Pytree]:
    """Returns (params, bn_state) for the discriminator.

    Stage 0 has no BN, matching the reference (distriubted_model.py:118; its
    `d_bn0` is created but never used — SURVEY.md §2.4 #7 — we don't create one).
    """
    if cfg.arch in ("resnet", "stylegan"):
        # the stylegan family pairs its G with the same norm-free residual
        # critic (StyleGAN2's own D is a plain resnet; pair with --r1_gamma)
        from dcgan_tpu.models import resnet

        return resnet.discriminator_init(key, cfg)
    k = cfg.num_up_layers
    dtype = _dtype(cfg)
    keys = jax.random.split(key, 2 * k + 2)

    params: Pytree = {}
    state: Pytree = {}
    in_ch = cfg.c_dim + (cfg.num_classes if cfg.num_classes else 0)
    for i in range(k):
        out_ch = cfg.df_dim * (2 ** i)
        params[f"conv{i}"] = conv2d_init(
            keys[2 * i], in_ch, out_ch, kernel=cfg.kernel_size, dtype=dtype)
        if i > 0:
            bn_p, bn_s = batch_norm_init(keys[2 * i + 1], out_ch, dtype=dtype)
            params[f"bn{i}"], state[f"bn{i}"] = bn_p, bn_s
        in_ch = out_ch

    flat = cfg.base_size * cfg.base_size * cfg.df_dim * (2 ** (k - 1))
    params["head"] = linear_init(keys[-1], flat, 1, dtype=dtype)
    if cfg.attn_res:
        # stage i's output feature map is output_size / 2^(i+1) with
        # df_dim * 2^i channels. keys[2k] is unused above: conv keys are the
        # even indices 0..2k-2, BN keys the odd 3..2k-1, head takes 2k+1.
        i = int(round(math.log2(cfg.output_size / cfg.attn_res))) - 1
        params["attn"] = attn_init(keys[2 * k], cfg.df_dim * (2 ** i),
                                   dtype=dtype)
    if cfg.spectral_norm in ("d", "gd"):
        _sn_state_init(jax.random.fold_in(key, 0x53AE), params, state)
    return params, state


def discriminator_apply(params: Pytree, state: Pytree, image: jax.Array, *,
                        cfg: ModelConfig, train: bool,
                        labels: Optional[jax.Array] = None,
                        axis_name: Optional[str] = None,
                        attn_mesh=None,
                        pallas_mesh=None,
                        capture: Optional[dict] = None
                        ) -> Tuple[jax.Array, jax.Array, Pytree]:
    """image [B, S, S, c] -> (sigmoid(logit), logit [B, 1], new_bn_state).

    `capture` (dict) receives post-activation tensors "h0".."h{k-1}" plus the
    final "logit" — see generator_apply.
    """
    if cfg.arch in ("resnet", "stylegan"):
        from dcgan_tpu.models import resnet

        return resnet.discriminator_apply(
            params, state, image, cfg=cfg, train=train, labels=labels,
            axis_name=axis_name, attn_mesh=attn_mesh,
            pallas_mesh=pallas_mesh, capture=capture)
    k = cfg.num_up_layers
    cdt = _cdtype(cfg)
    new_state: Pytree = {}
    sn = cfg.spectral_norm in ("d", "gd")

    def layer(name):
        return _sn_layer(params, state, new_state, name, train) if sn \
            else params[name]

    def attn_params():
        return _sn_attn(params["attn"], state, new_state, train) if sn \
            else params["attn"]

    h = image.astype(cdt)
    if cfg.num_classes:
        if labels is None:
            raise ValueError("conditional discriminator requires labels")
        onehot = jax.nn.one_hot(labels, cfg.num_classes, dtype=h.dtype)
        maps = jnp.broadcast_to(onehot[:, None, None, :],
                                h.shape[:3] + (cfg.num_classes,))
        h = jnp.concatenate([h, maps], axis=-1)

    for i in range(k):
        if cfg.pallas_fused and i > 0:
            # fused conv + bias + BN + lrelu block (stage 0 keeps the
            # reference's no-BN shape and stays on the unfused path)
            from dcgan_tpu.ops.pallas_fused import fused_conv_bn_act

            h, new_state[f"bn{i}"] = fused_conv_bn_act(
                layer(f"conv{i}"), params[f"bn{i}"], state[f"bn{i}"], h,
                transpose=False, kernel=cfg.kernel_size, stride=2,
                train=train, momentum=cfg.bn_momentum, eps=cfg.bn_eps,
                act="lrelu", leak=cfg.leak, axis_name=axis_name,
                pallas_mesh=pallas_mesh, compute_dtype=cdt,
                quant=_stage_quant(cfg, cfg.output_size >> i))
        elif i > 0:
            h = conv2d_apply(layer(f"conv{i}"), h, compute_dtype=cdt,
                             quant=_stage_quant(cfg, cfg.output_size >> i))
            # BN + lrelu fused (stage 0 keeps the reference's no-BN shape)
            h, new_state[f"bn{i}"] = batch_norm_apply(
                params[f"bn{i}"], state[f"bn{i}"], h, train=train,
                momentum=cfg.bn_momentum, eps=cfg.bn_eps,
                axis_name=axis_name, act="lrelu", leak=cfg.leak,
                use_pallas=cfg.bn_use_pallas, pallas_mesh=pallas_mesh)
        else:
            h = conv2d_apply(layer(f"conv{i}"), h, compute_dtype=cdt)
            h = lrelu(h, cfg.leak)
        if cfg.attn_res and cfg.attn_res == cfg.output_size >> (i + 1):
            h = attn_apply(attn_params(), h, compute_dtype=cdt,
                           num_heads=cfg.attn_heads,
                           seq_strategy=cfg.attn_seq_strategy,
                           seq_mesh=attn_mesh, use_pallas=cfg.use_pallas,
                           pallas_mesh=pallas_mesh)
        if capture is not None:
            capture[f"h{i}"] = h

    h = h.reshape(h.shape[0], -1)
    logit = linear_apply(layer("head"), h, compute_dtype=cdt)
    logit = logit.astype(jnp.float32)
    if capture is not None:
        capture["logit"] = logit
    return jax.nn.sigmoid(logit), logit, new_state


# ---------------------------------------------------------------------------
# Whole-GAN convenience
# ---------------------------------------------------------------------------

def gan_init(key, cfg: ModelConfig) -> Tuple[Pytree, Pytree]:
    """Initialize both networks.

    Returns (params, state) with params = {"gen": ..., "disc": ...} — the
    structural replacement for the reference's fragile substring split of one
    flat variable list (`'d_' in name` / `'g_' in name`, image_train.py:107-108,
    SURVEY.md §2.4 #6).
    """
    kg, kd = jax.random.split(key)
    g_params, g_state = generator_init(kg, cfg)
    d_params, d_state = discriminator_init(kd, cfg)
    return ({"gen": g_params, "disc": d_params},
            {"gen": g_state, "disc": d_state})
