"""ResNet GAN generator / discriminator — the framework's second model family.

The reference is DCGAN-only (distriubted_model.py:83-128); this family is the
residual architecture of WGAN-GP (Gulrajani et al. 2017, appendix F) and
SNGAN (Miyato et al. 2018, table 3), selected with `ModelConfig(arch=
"resnet")` and scaled by the same base_size·2^k rule as the DCGAN stacks:

- generator: linear z -> [base, base, top_ch], then k residual up-blocks
  (BN -> relu -> 2x nearest upsample -> conv3x3 -> BN -> relu -> conv3x3,
  skip = upsample (+1x1 conv on channel change)), final BN -> relu ->
  conv3x3 -> tanh;
- discriminator: an "optimized" first down-block (conv3x3 -> relu ->
  conv3x3 -> avgpool; skip = avgpool -> 1x1), then residual down-blocks
  (relu -> conv3x3 -> relu -> conv3x3 [-> avgpool]), relu, global sum
  pool, linear -> 1 logit.

Everything composes with the existing machinery because the integration
surfaces are shared, not copied:

- params/state are flat dicts of {"w","b"} layers and bn*/sn_* leaves, so
  the spectral-norm wrappers (dcgan._sn_layer), the TP sharding rules
  (parallel/sharding.py keys on "w"/"proj"/"head" names), Adam/optax, and
  Orbax checkpointing all apply unchanged;
- normalization is ops/norm.batch_norm_apply — synced moments, cBN [K, C]
  tables, fused Pallas kernels, and the nested-shard_map gspmd path come
  for free;
- attn_res inserts the same SAGAN block (ops/attention.py), sequence-
  parallel under a spatial mesh, exactly as in the DCGAN stacks;
- conditioning mirrors dcgan.py: one-hot concat onto z for G, constant
  channel maps for D.

Entry points match dcgan.py's signatures; models/dcgan.py dispatches on
cfg.arch so every caller (steps, parallel, generate, evals) is untouched.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from dcgan_tpu.config import ModelConfig
from dcgan_tpu.ops.attention import attn_apply, attn_init
from dcgan_tpu.ops.layers import (
    conv2d_apply,
    conv2d_init,
    linear_apply,
    linear_init,
)
from dcgan_tpu.ops.norm import batch_norm_apply, batch_norm_init

Pytree = dict


def _upsample(x: jax.Array) -> jax.Array:
    """2x nearest-neighbor upsample, NHWC."""
    return x.repeat(2, axis=1).repeat(2, axis=2)


def _avgpool(x: jax.Array) -> jax.Array:
    """2x2 average pool, NHWC (shapes here are powers of two)."""
    b, h, w, c = x.shape
    return x.reshape(b, h // 2, 2, w // 2, 2, c).mean(axis=(2, 4))


def _g_channels(cfg: ModelConfig):
    """Per-stage channel plan: top_ch at base_size, halving as resolution
    doubles and flooring at gf_dim (the last up-block keeps its width, the
    SNGAN/BigGAN convention), so gf_dim means the same thing in both
    families."""
    k = cfg.num_up_layers
    return [cfg.gf_dim * (2 ** max(0, k - 1 - i)) for i in range(k + 1)]


def _d_channels(cfg: ModelConfig):
    """Mirror of the generator plan: df_dim at full resolution, doubling as
    resolution halves."""
    k = cfg.num_up_layers
    return [cfg.df_dim * (2 ** i) for i in range(k)]


# ---------------------------------------------------------------------------
# Generator
# ---------------------------------------------------------------------------

def generator_init(key, cfg: ModelConfig) -> Tuple[Pytree, Pytree]:
    """Returns (params, bn_state); flat layer names (b{i}_*) keep the
    spectral-norm and sharding machinery applicable as-is."""
    k = cfg.num_up_layers
    dtype = jnp.dtype(cfg.param_dtype)
    chans = _g_channels(cfg)
    keys = jax.random.split(key, 6 * k + 4)
    bn_classes = cfg.num_classes if cfg.conditional_bn else 0

    in_dim = cfg.z_dim + (cfg.num_classes if cfg.num_classes else 0)
    params: Pytree = {
        "proj": linear_init(keys[0], in_dim,
                            chans[0] * cfg.base_size * cfg.base_size,
                            dtype=dtype),
    }
    state: Pytree = {}
    for i in range(1, k + 1):
        cin, cout = chans[i - 1], chans[i]
        kk = keys[6 * i - 5:6 * i + 1]
        bn_p, bn_s = batch_norm_init(kk[0], cin, dtype=dtype,
                                     num_classes=bn_classes)
        params[f"b{i}_bn1"], state[f"b{i}_bn1"] = bn_p, bn_s
        params[f"b{i}_conv1"] = conv2d_init(kk[1], cin, cout, kernel=3,
                                            dtype=dtype)
        bn_p, bn_s = batch_norm_init(kk[2], cout, dtype=dtype,
                                     num_classes=bn_classes)
        params[f"b{i}_bn2"], state[f"b{i}_bn2"] = bn_p, bn_s
        params[f"b{i}_conv2"] = conv2d_init(kk[3], cout, cout, kernel=3,
                                            dtype=dtype)
        if cin != cout:
            params[f"b{i}_skip"] = conv2d_init(kk[4], cin, cout, kernel=1,
                                               dtype=dtype)
    bn_p, bn_s = batch_norm_init(keys[6 * k + 1], chans[k], dtype=dtype,
                                 num_classes=bn_classes)
    params["bn_out"], state["bn_out"] = bn_p, bn_s
    params["out_conv"] = conv2d_init(keys[6 * k + 2], chans[k], cfg.c_dim,
                                     kernel=3, dtype=dtype)
    if cfg.attn_res:
        i = int(round(math.log2(cfg.attn_res / cfg.base_size)))
        params["attn"] = attn_init(keys[6 * k + 3], chans[i], dtype=dtype)
    if cfg.spectral_norm == "gd":
        from dcgan_tpu.models.dcgan import _sn_state_init

        _sn_state_init(jax.random.fold_in(key, 0x53AE), params, state)
    return params, state


def generator_apply(params: Pytree, state: Pytree, z: jax.Array, *,
                    cfg: ModelConfig, train: bool,
                    labels: Optional[jax.Array] = None,
                    axis_name: Optional[str] = None,
                    attn_mesh=None,
                    pallas_mesh=None,
                    capture: Optional[dict] = None
                    ) -> Tuple[jax.Array, Pytree]:
    """z [B, z_dim] (-1..1) -> image [B, S, S, c_dim] in tanh range."""
    from dcgan_tpu.models.dcgan import _sn_layer

    k = cfg.num_up_layers
    cdt = jnp.dtype(cfg.compute_dtype)
    chans = _g_channels(cfg)
    new_state: Pytree = {}
    sn = cfg.spectral_norm == "gd"

    def layer(name):
        return _sn_layer(params, state, new_state, name, train) if sn \
            else params[name]

    def bn(name, x, act):
        y, new_state[name] = batch_norm_apply(
            params[name], state[name], x, train=train,
            momentum=cfg.bn_momentum, eps=cfg.bn_eps, axis_name=axis_name,
            act=act, use_pallas=cfg.bn_use_pallas, labels=bn_labels,
            pallas_mesh=pallas_mesh)
        return y

    if cfg.num_classes:
        if labels is None:
            raise ValueError("conditional generator requires labels")
        onehot = jax.nn.one_hot(labels, cfg.num_classes, dtype=z.dtype)
        z = jnp.concatenate([z, onehot], axis=-1)
    bn_labels = labels if cfg.conditional_bn else None

    h = linear_apply(layer("proj"), z.astype(cdt), compute_dtype=cdt)
    h = h.reshape(-1, cfg.base_size, cfg.base_size, chans[0])
    if cfg.attn_res == cfg.base_size:
        h = _attn(cfg, params, state, new_state, h, cdt, attn_mesh, sn,
                  train, pallas_mesh=pallas_mesh)
    if capture is not None:
        capture["h0"] = h

    for i in range(1, k + 1):
        r = bn(f"b{i}_bn1", h, "relu")
        r = _upsample(r)
        r = conv2d_apply(layer(f"b{i}_conv1"), r, stride=1,
                         compute_dtype=cdt)
        r = bn(f"b{i}_bn2", r, "relu")
        r = conv2d_apply(layer(f"b{i}_conv2"), r, stride=1,
                         compute_dtype=cdt)
        s = _upsample(h)
        if f"b{i}_skip" in params:
            s = conv2d_apply(layer(f"b{i}_skip"), s, stride=1,
                             compute_dtype=cdt)
        h = r + s
        if cfg.attn_res == cfg.base_size * (2 ** i) and i < k:
            h = _attn(cfg, params, state, new_state, h, cdt, attn_mesh, sn,
                      train, pallas_mesh=pallas_mesh)
        if capture is not None:
            capture[f"h{i}"] = h

    h = bn("bn_out", h, "relu")
    h = conv2d_apply(layer("out_conv"), h, stride=1, compute_dtype=cdt)
    out = jnp.tanh(h.astype(jnp.float32))
    if capture is not None:
        capture[f"h{k + 1}"] = out
    return out, new_state


def _attn(cfg, params, state, new_state, h, cdt, attn_mesh, sn, train,
          pallas_mesh=None):
    from dcgan_tpu.models.dcgan import _sn_attn

    p = _sn_attn(params["attn"], state, new_state, train) if sn \
        else params["attn"]
    return attn_apply(p, h, compute_dtype=cdt, num_heads=cfg.attn_heads,
                      seq_strategy=cfg.attn_seq_strategy,
                      seq_mesh=attn_mesh, use_pallas=cfg.use_pallas,
                      pallas_mesh=pallas_mesh)


# ---------------------------------------------------------------------------
# Discriminator
# ---------------------------------------------------------------------------

def discriminator_init(key, cfg: ModelConfig) -> Tuple[Pytree, Pytree]:
    """Returns (params, state). No BN anywhere (the SNGAN/WGAN-GP critic is
    norm-free — WGAN-GP's penalty is per-example, and SN replaces BN's
    conditioning role), so `state` carries only sn_* leaves when spectral
    norm is on — which also makes the whole family valid under loss=
    'wgan-gp' without cross-example coupling."""
    k = cfg.num_up_layers
    dtype = jnp.dtype(cfg.param_dtype)
    chans = _d_channels(cfg)
    keys = jax.random.split(key, 3 * k + 3)

    cin0 = cfg.c_dim + (cfg.num_classes if cfg.num_classes else 0)
    params: Pytree = {}
    state: Pytree = {}
    in_ch = cin0
    for i in range(k):
        out_ch = chans[i]
        params[f"b{i}_conv1"] = conv2d_init(keys[3 * i], in_ch, out_ch,
                                            kernel=3, dtype=dtype)
        params[f"b{i}_conv2"] = conv2d_init(keys[3 * i + 1], out_ch, out_ch,
                                            kernel=3, dtype=dtype)
        if in_ch != out_ch:
            params[f"b{i}_skip"] = conv2d_init(keys[3 * i + 2], in_ch,
                                               out_ch, kernel=1, dtype=dtype)
        in_ch = out_ch
    params["head"] = linear_init(keys[3 * k], in_ch, 1, dtype=dtype)
    if cfg.attn_res:
        i = int(round(math.log2(cfg.output_size / cfg.attn_res)))
        params["attn"] = attn_init(keys[3 * k + 1], chans[i - 1],
                                   dtype=dtype)
    if cfg.spectral_norm in ("d", "gd"):
        from dcgan_tpu.models.dcgan import _sn_state_init

        _sn_state_init(jax.random.fold_in(key, 0xD15C), params, state)
    return params, state


def discriminator_apply(params: Pytree, state: Pytree, image: jax.Array, *,
                        cfg: ModelConfig, train: bool,
                        labels: Optional[jax.Array] = None,
                        axis_name: Optional[str] = None,
                        attn_mesh=None,
                        pallas_mesh=None,
                        capture: Optional[dict] = None
                        ) -> Tuple[jax.Array, jax.Array, Pytree]:
    """image -> (sigmoid(logit), logit [B, 1], new_state)."""
    from dcgan_tpu.models.dcgan import _sn_layer

    k = cfg.num_up_layers
    cdt = jnp.dtype(cfg.compute_dtype)
    new_state: Pytree = {}
    sn = cfg.spectral_norm in ("d", "gd")

    def layer(name):
        return _sn_layer(params, state, new_state, name, train) if sn \
            else params[name]

    h = image.astype(cdt)
    if cfg.num_classes:
        if labels is None:
            raise ValueError("conditional discriminator requires labels")
        onehot = jax.nn.one_hot(labels, cfg.num_classes, dtype=h.dtype)
        maps = jnp.broadcast_to(onehot[:, None, None, :],
                                h.shape[:3] + (cfg.num_classes,))
        h = jnp.concatenate([h, maps], axis=-1)

    for i in range(k):
        # block 0 is the "optimized" form (no pre-activation on raw pixels);
        # later blocks pre-activate (relu first)
        r = h if i == 0 else jax.nn.relu(h)
        r = conv2d_apply(layer(f"b{i}_conv1"), r, stride=1,
                         compute_dtype=cdt)
        r = jax.nn.relu(r)
        r = conv2d_apply(layer(f"b{i}_conv2"), r, stride=1,
                         compute_dtype=cdt)
        r = _avgpool(r)
        s = _avgpool(h)
        if f"b{i}_skip" in params:
            s = conv2d_apply(layer(f"b{i}_skip"), s, stride=1,
                             compute_dtype=cdt)
        h = r + s
        if cfg.attn_res and cfg.attn_res == cfg.output_size >> (i + 1):
            h = _attn(cfg, params, state, new_state, h, cdt, attn_mesh, sn,
                      train, pallas_mesh=pallas_mesh)
        if capture is not None:
            capture[f"h{i}"] = h

    h = jax.nn.relu(h)
    h = h.sum(axis=(1, 2))                       # global sum pool
    logit = linear_apply(layer("head"), h, compute_dtype=cdt)
    logit = logit.astype(jnp.float32)
    if capture is not None:
        capture["logit"] = logit
    return jax.nn.sigmoid(logit), logit, new_state
